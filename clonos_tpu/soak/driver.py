"""Open-loop soak driver: fixed-rate load + chaos + exactly-once audit.

The driver paces a live :class:`ClusterRunner` with a token bucket: a
chunk of supersteps is DUE at a fixed schedule (``rate`` records/sec),
regardless of whether the cluster is keeping up. End-to-end latency is
measured from the chunk's *intended*-send instant — a chunk that runs
while the driver is busy recovering a kill is charged the whole stall,
which is what an open-loop client would have experienced (the
coordinated-omission correction the closed-loop bench numbers lack).

The chaos harness applies :class:`soak.chaos.ChaosEvent` faults to the
running cluster and, after every event, re-validates the audit ledger
against a fault-free **control twin**: a second runner of the same job,
same seed, logical time on both, advanced epoch-by-epoch to the soak
runner's last sealed epoch. Any digest divergence is an exactly-once
violation and fails the run — the Jepsen-style check the Clonos
reference delegates to flink-jepsen.

Kill scheduling detail: a kill is applied only in the epoch after a
*completing* fence (the driver forces one when a kill is due). With no
pending checkpoints, recovery ignores nothing, so the healthy tasks log
no IGNORE_CHECKPOINT determinants and the post-recovery digest chain
stays byte-comparable with the control twin — the audit asserts the
recovery itself was exactly-once, not merely that the run finished.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time as _time
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from clonos_tpu.autoscale import SignalAggregator
from clonos_tpu.obs import get_tracer
from clonos_tpu.obs.detect import GraySnapshot, get_detector
from clonos_tpu.obs.digest import diff_ledgers

from .chaos import ChaosEvent, ChaosSchedule
from .slo import SLOSpec, SLOTracker, quantile

#: multiplicative salt for the injected nondeterminism fault — the
#: examples/audit_nondet.py pattern: perturb ring VALUES only (keys,
#: counts, and ordering stay plausible) so every structural invariant
#: passes and only the digest chain catches it.
_NONDET_MULT, _NONDET_ADD, _NONDET_MOD = 31, 1009, 9973


def _keyed_parallelism(runner) -> int:
    """The re-cuttable cut: the keyed (interior) stages' parallelism —
    the quantity ``rescale_live``'s target names. Source and sink keep
    theirs across a re-cut, so only interior vertices count."""
    job = runner.job
    pars = [v.parallelism for v in job.vertices
            if job.in_edges(v.vertex_id) and job.out_edges(v.vertex_id)]
    if not pars:
        pars = [v.parallelism for v in job.vertices]
    return max(pars)


def _max_actions_per_cooldown(records, cooldown: int) -> int:
    """Worst-case count of SCALE ACTIONS (non-holds) inside any
    ``cooldown``-fence window of the decision log — the verdict's
    rate-limit witness (must be <= 1 when the cooldown held)."""
    seqs = [r["decision"]["seq"] for r in records
            if r["decision"]["action"] != "hold"]
    best = 0
    for i, s in enumerate(seqs):
        n = sum(1 for t in seqs[i:] if t - s < cooldown)
        best = max(best, n)
    return best


class SoakHarness:
    """Applies chaos events to a live runner and owns the post-event
    audit re-validation against the fault-free control twin."""

    def __init__(self, runner, control=None, election=None, tracer=None):
        self.runner = runner
        self.control = control
        self.election = election
        self.tracer = tracer or get_tracer()
        #: flat subtask -> soak-clock instant its gray failure expires
        self.gray_until: Dict[int, float] = {}
        #: current per-chunk transport slowdown from active gray faults
        self.gray_delay_s = 0.0
        self._stall_orig = None
        self._stall_until = 0.0
        #: soak-clock instant until which checkpoint completion is
        #: suppressed (the `backlog` fault): truncation stops, the
        #: replay backlog grows past the device rings into the spill
        #: tiers (storage/tiered.py), and any recovery in that window
        #: replays from host/disk segments.
        self.backlog_until = 0.0
        #: set on every applied fault; the driver runs an audit check at
        #: the next fence and clears it
        self.audit_pending = False
        self.divergences: List[str] = []
        self.epochs_checked = 0
        self.faults_injected = 0
        self.faults_survived = 0
        self.by_kind: Dict[str, int] = {}
        self.recoveries_ms: List[float] = []
        #: per-kill overlapped-recovery evidence: every chaos kill runs
        #: the overlapped finalize tail, so each appends its
        #: finalize.overlap-saved attribution and the immediate
        #: post-recovery ledger re-diff vs the control twin (must stay
        #: empty — a mis-speculated replay is caught HERE, before the
        #: job resumes, not at the next fence).
        self.kill_overlap_saved_ms: List[float] = []
        self.kill_rediff_problems = 0
        #: kills that landed while the PIPELINED fence tail (seal /
        #: ledger / checkpoint on the fence worker) was still in
        #: flight — inject_failure joins the tail first, so each such
        #: kill proves the drain ordering under fire.
        self.kills_mid_fence_tail = 0
        #: read tier under test (runtime/serve.ServeTier), attached by
        #: the driver when a serve read load rides the run — the
        #: ``replica-kill`` fault targets it (and a ``rescale`` re-homes
        #: it onto the new incarnation).
        self.serve_tier = None
        self.replica_kills = 0
        #: live re-cuts applied (the ``rescale`` chaos event): count and
        #: per-event handoff stats for the verdict
        self.rescales = 0
        self.rescale_stats: List[Dict[str, Any]] = []
        #: offered-load spike (the ``load-spike`` chaos event): the
        #: token bucket's chunk period divides by this factor until the
        #: expiry instant. Pacing ONLY — record contents are logical-
        #: time-deterministic on both runners, so the fault-free
        #: control twin experiences the identical spike and the ledger
        #: diff keeps gating byte-exactly through it.
        self.spike_factor = 1.0
        self.spike_until = 0.0
        #: self-directed re-cuts (autoscale/controller.py closing the
        #: loop): counted apart from the operator ``rescale`` event —
        #: the closed-loop acceptance bar is ZERO operator events with
        #: the system re-cutting itself.
        self.autoscale_rescales = 0
        self.autoscale_stats: List[Dict[str, Any]] = []

    # --- fault application ---------------------------------------------------

    def apply(self, event: ChaosEvent, now_s: float) -> None:
        """Apply one fault NOW (``now_s`` is the soak clock, for expiry
        bookkeeping + the trace instant)."""
        self.tracer.event("soak.chaos", kind=event.kind,
                          at_s=round(now_s, 3),
                          targets=list(event.targets))
        from clonos_tpu.obs import get_timeline
        tl = get_timeline()
        if tl.enabled:
            tl.record("chaos", chaos_kind=event.kind,
                      at_s=round(now_s, 3), targets=list(event.targets))
        self.faults_injected += 1
        self.by_kind[event.kind] = self.by_kind.get(event.kind, 0) + 1
        getattr(self, "_apply_" + event.kind.replace("-", "_"))(
            event, now_s)
        self.audit_pending = True

    def _apply_kill(self, event: ChaosEvent, now_s: float) -> None:
        # Cascading SIGKILL mid-epoch (the config4 pattern when the
        # schedule targets one subtask per vertex class), then the full
        # causal-recovery protocol inline — the pacer keeps charging
        # intended-send time throughout, so the outage lands in p99.
        r = self.runner
        r.inject_failure(list(event.targets))
        t0 = _time.monotonic()
        report = r.recover()
        ms = (_time.monotonic() - t0) * 1e3
        self.recoveries_ms.append(ms)
        self.faults_survived += 1
        # Overlapped-tail acceptance, under fire: record the kill's
        # finalize.overlap-saved attribution (present == the overlapped
        # pipeline ran) and re-diff the ledger against the fault-free
        # control twin IMMEDIATELY — not only at the next fence — so a
        # mis-speculated replay is caught before the job resumes.
        saved = report.phase_ms.get("finalize.overlap-saved", 0.0)
        self.kill_overlap_saved_ms.append(round(saved, 1))
        rediff = self.audit_check()
        self.kill_rediff_problems += len(rediff)
        self.tracer.event("soak.chaos.recovered", kind="kill",
                          targets=list(event.targets),
                          recovery_ms=round(ms, 1),
                          overlap_saved_ms=round(saved, 1),
                          rediff_problems=len(rediff))

    def _apply_gray(self, event: ChaosEvent, now_s: float) -> None:
        # Degraded, not dead: the worker's heartbeats arrive late and
        # its transport stretches every chunk, but it keeps stepping.
        # The monitor must report it in degraded(), never in expired().
        flat = event.targets[0]
        self.runner.heartbeats.lag[flat] = event.delay_s
        self.gray_until[flat] = now_s + event.duration_s
        self.gray_delay_s = max(self.gray_delay_s, event.delay_s)

    def _apply_leader_loss(self, event: ChaosEvent, now_s: float) -> None:
        # A rival claims the next fencing epoch: our renew() becomes a
        # no-op for every reader and returns False. The driver pauses
        # ingestion while deposed and re-acquires once the rival's
        # deadline lapses (hold_s).
        el = self.election
        if el is None:
            return
        import json as _json
        epoch = (el.epoch or max(el._claims() or [0])) + 1
        tmp = el._claim_path(epoch) + ".chaos.tmp"
        with open(tmp, "w") as f:
            _json.dump({"leader_id": "chaos-rival",
                        "deadline_wall": el._clock() + event.hold_s}, f)
        os.replace(tmp, el._claim_path(epoch))

    def _apply_stall(self, event: ChaosEvent, now_s: float) -> None:
        # Checkpoint-storage write stall: every durable write sleeps
        # delay_s for the fault's duration. run_epoch triggers with
        # async_write=False, so the stall lands squarely in fence
        # latency (and therefore in the corrected latency of the chunks
        # queued behind it).
        storage = self.runner.coordinator.storage
        if self._stall_orig is None:
            self._stall_orig = storage.write
        orig, delay = self._stall_orig, event.delay_s

        def stalled_write(*a, **k):
            _time.sleep(delay)
            return orig(*a, **k)

        storage.write = stalled_write
        # The same fault tortures the spill path: segment writes on the
        # tiered stores' writer threads sleep too. The fence must NOT
        # stretch by this (spilling is asynchronous — the soak stall
        # scenario pins exactly that), and replay through the stalled
        # tier must still round-trip bit-identically.
        for st in self.runner.executor._tier_stores():
            st.write_delay_s = max(st.write_delay_s, delay)
        self._stall_until = max(self._stall_until,
                                now_s + event.duration_s)

    def _apply_backlog(self, event: ChaosEvent, now_s: float) -> None:
        # Long-backlog torture: the driver suppresses checkpoint
        # completion while active (see _run_paced), so truncation stops
        # and sealed epochs pile up past device ring capacity — replay
        # after this window MUST refill from the host/disk tiers.
        self.backlog_until = max(self.backlog_until,
                                 now_s + event.duration_s)

    def backlog_active(self, now_s: float) -> bool:
        return now_s < self.backlog_until

    def _apply_load_spike(self, event: ChaosEvent,
                          now_s: float) -> None:
        # Not a fault in the cluster — a LOAD event: the open-loop
        # client offers chunks factor-x faster for the window (the
        # autoscaler's cue). Only wall-clock pacing changes; logical
        # time keeps record contents identical on both runners, so the
        # control twin's ledger stays byte-comparable through the
        # spike and the exactly-once audit keeps gating.
        self.spike_factor = max(self.spike_factor, event.factor)
        self.spike_until = max(self.spike_until,
                               now_s + event.duration_s)
        self.tracer.event("soak.chaos.load-spike",
                          factor=event.factor,
                          until_s=round(self.spike_until, 3))

    def rate_factor(self, now_s: float) -> float:
        """Current offered-rate multiplier (1.0 outside a spike)."""
        return self.spike_factor if now_s < self.spike_until else 1.0

    def _apply_replica_kill(self, event: ChaosEvent,
                            now_s: float) -> None:
        # Read-tier chaos: a serve replica dies mid-run. Degradation —
        # not failure — is the acceptance bar: the router re-routes the
        # dead replica's key groups to the owner (a counted REROUTE,
        # zero client-visible errors; the read load's error counter is
        # the witness), staleness spikes, and the replica revives at the
        # next seal from the standby pool's restore point. No audit
        # impact: the read tier never writes job state.
        tier = self.serve_tier
        if tier is None:
            self.tracer.event("soak.chaos.replica-kill.skipped",
                              reason="no serve tier attached")
            return
        idx = event.targets[0] if event.targets else 0
        tier.kill_replica(idx)
        self.replica_kills += 1
        self.faults_survived += 1
        self.tracer.event("soak.chaos.replica-kill", replica=idx)

    def _apply_rescale(self, event: ChaosEvent, now_s: float) -> None:
        # Elastic re-cut under live traffic: at the completing fence the
        # driver just forced, hand the job off to a new incarnation at
        # the event's keyed parallelism (fence -> drain -> migrate ->
        # redirect; runtime/cluster.rescale_live). The control twin is
        # re-cut identically at the SAME fence, so the ledger diff stays
        # byte-comparable across the re-cut — exactly-once over a live
        # repartition is audited, not assumed.
        target = int(event.targets[0])
        rescale = getattr(self.runner, "_soak_rescaler", None)
        if rescale is None:
            self.tracer.event("soak.chaos.rescale.skipped",
                              reason="runner has no rescaler attached")
            return
        if self._stall_orig is not None:
            # an active storage stall dies with the old incarnation —
            # restoring it later onto the NEW runner's storage would
            # rebind writes to the fenced-off one
            self.runner.coordinator.storage.write = self._stall_orig
            self._stall_orig = None
            for st in self.runner.executor._tier_stores():
                st.write_delay_s = 0.0
            self._stall_until = 0.0
        t0 = _time.monotonic()
        self.runner, stats = rescale(target)
        stall_ms = (_time.monotonic() - t0) * 1e3
        c = self.control
        if c is not None:
            while c.executor.epoch_id < stats["from_epoch"]:
                c.run_epoch(complete_checkpoint=True)
            c.drain_fence()
            self.control, _ = c._soak_rescaler(target)
        if self.serve_tier is not None:
            # read tier re-homes onto the new incarnation: reads in
            # the handoff window reroute to live views, never error
            self.serve_tier.rehome(self.runner)
        self.rescales += 1
        self.rescale_stats.append({
            "target": target,
            "fence_checkpoint": stats["fence_checkpoint"],
            "groups": stats["groups"],
            "drained_records": stats["drained_records"],
            "moved_key_groups": stats["moved_key_groups"],
            "fence_stall_ms": round(stall_ms, 1),
        })
        # the fence stall is an outage the open-loop client saw:
        # charge it like a recovery so SLO windows see it
        self.recoveries_ms.append(stall_ms)
        self.faults_survived += 1
        self.tracer.event("soak.chaos.rescaled", target=target,
                          fence_checkpoint=stats["fence_checkpoint"],
                          drained=stats["drained_records"],
                          stall_ms=round(stall_ms, 1))

    def autoscale_rescale(self, target: int) -> Dict[str, Any]:
        """Execute an autoscaler-decided re-cut at the completed fence
        the driver just drained — the exact fence → drain → migrate →
        redirect path the operator ``rescale`` event takes (control
        twin re-cut identically at the SAME fence, serve tier re-homed)
        but charged to the AUTOSCALE ledger, not the fault counters:
        the closed-loop acceptance bar is zero operator events."""
        target = int(target)
        rescale = getattr(self.runner, "_soak_rescaler", None)
        if rescale is None:
            raise RuntimeError(
                "autoscale re-cut requested but the runner has no "
                "rescaler attached (build_soak_fixture arms one)")
        if self._stall_orig is not None:
            # same rule as the operator path: an active storage stall
            # dies with the old incarnation
            self.runner.coordinator.storage.write = self._stall_orig
            self._stall_orig = None
            for st in self.runner.executor._tier_stores():
                st.write_delay_s = 0.0
            self._stall_until = 0.0
        t0 = _time.monotonic()
        self.runner, stats = rescale(target)
        stall_ms = (_time.monotonic() - t0) * 1e3
        c = self.control
        if c is not None:
            while c.executor.epoch_id < stats["from_epoch"]:
                c.run_epoch(complete_checkpoint=True)
            c.drain_fence()
            self.control, _ = c._soak_rescaler(target)
        if self.serve_tier is not None:
            self.serve_tier.rehome(self.runner)
        self.autoscale_rescales += 1
        self.autoscale_stats.append({
            "target": target,
            "fence_checkpoint": stats["fence_checkpoint"],
            "groups": stats["groups"],
            "drained_records": stats["drained_records"],
            "moved_key_groups": stats["moved_key_groups"],
            "fence_stall_ms": round(stall_ms, 1),
        })
        # the fence stall is still an outage the open-loop client saw
        self.recoveries_ms.append(stall_ms)
        # re-validate exactly-once at the next fence, like any re-cut
        self.audit_pending = True
        self.tracer.event("soak.autoscale.rescaled", target=target,
                          fence_checkpoint=stats["fence_checkpoint"],
                          drained=stats["drained_records"],
                          stall_ms=round(stall_ms, 1))
        return stats

    def _apply_nondet(self, event: ChaosEvent, now_s: float) -> None:
        # Unlogged value perturbation on-device (audit bait): occupied
        # in-flight ring slots get salted values. Counts, keys, and
        # timestamps stay exactly right — the next seal's ring-channel
        # digest is the only thing that can catch this.
        ex = self.runner.executor
        rings = tuple(
            el._replace(values=jnp.where(
                el.valid,
                (el.values * _NONDET_MULT + _NONDET_ADD) % _NONDET_MOD,
                el.values))
            for el in ex.carry.out_rings)
        ex.carry = ex.carry._replace(out_rings=rings)

    # --- expiry + audit ------------------------------------------------------

    def tick(self, now_s: float) -> None:
        """Expire time-bounded degradations (gray, stall)."""
        for flat, until in list(self.gray_until.items()):
            if now_s >= until:
                del self.gray_until[flat]
                self.runner.heartbeats.lag.pop(flat, None)
                self.faults_survived += 1
                self.tracer.event("soak.chaos.expired", kind="gray",
                                  target=flat)
        if not self.gray_until:
            self.gray_delay_s = 0.0
        if self._stall_orig is not None and now_s >= self._stall_until:
            self.runner.coordinator.storage.write = self._stall_orig
            self._stall_orig = None
            for st in self.runner.executor._tier_stores():
                st.write_delay_s = 0.0
            self.faults_survived += 1
            self.tracer.event("soak.chaos.expired", kind="stall")
        if self.backlog_until and now_s >= self.backlog_until:
            self.backlog_until = 0.0
            self.faults_survived += 1
            self.tracer.event("soak.chaos.expired", kind="backlog")
        if self.spike_until and now_s >= self.spike_until:
            self.spike_until = 0.0
            self.spike_factor = 1.0
            self.faults_survived += 1
            self.tracer.event("soak.chaos.expired", kind="load-spike")

    def audit_check(self) -> List[str]:
        """Advance the control twin to the soak runner's last sealed
        epoch and diff the two ledgers. Divergences accumulate; any at
        run end means exactly-once did NOT hold."""
        r, c = self.runner, self.control
        if c is None or not r.auditor.enabled:
            return []
        while c.auditor.last_epoch < r.auditor.last_epoch:
            c.run_epoch(complete_checkpoint=True)
        hi = r.auditor.last_epoch
        expected = [e for e in c.auditor.ledger() if e["epoch"] <= hi]
        actual = [e for e in r.auditor.ledger() if e["epoch"] <= hi]
        problems = diff_ledgers(expected, actual)
        self.epochs_checked = max(self.epochs_checked, len(actual))
        for p in problems:
            if p not in self.divergences:
                self.divergences.append(p)
                self.tracer.event("soak.audit.divergence", problem=p)
                # Capture the flight-recorder bundle while both
                # ledgers + determinant windows are still in hand
                # (no-op when the incident plane is disabled).
                from clonos_tpu.obs.incident import get_incidents
                m = re.match(r"epoch (\d+)", p)
                get_incidents().signal(
                    "audit.divergence",
                    epoch=int(m.group(1)) if m else None,
                    problem=p)
        return problems


@dataclasses.dataclass
class SoakConfig:
    """Pacing + cadence knobs for one soak run."""

    rate: float                  # records/sec the token bucket releases
    duration_s: float = 60.0
    window_s: float = 5.0        # SLO evaluation window
    chunk_steps: int = 8         # supersteps released per token
    #: complete every Nth checkpoint: the in-between fences leave their
    #: checkpoints pending, so the in-flight rings grow across epochs —
    #: checkpoint-under-load and the spill regime stay engaged.
    complete_every: int = 2
    #: beats later than this (but inside the death timeout) classify a
    #: worker as degraded
    degraded_grace_s: float = 0.01
    #: renew the leader lease at most this often
    renew_every_s: float = 0.5


class SoakDriver:
    """Runs the paced loop: token-bucket ingestion, chaos events on the
    soak clock, SLO windows, and a JSON verdict."""

    def __init__(self, runner, config: SoakConfig,
                 schedule: Optional[ChaosSchedule] = None,
                 spec: Optional[SLOSpec] = None,
                 control=None, election=None,
                 records_per_step: Optional[int] = None,
                 read_load=None, autoscaler=None, detector=None):
        self.runner = runner
        self.cfg = config
        self.schedule = schedule if schedule is not None \
            else ChaosSchedule([])
        self.spec = spec or SLOSpec()
        self.tracer = get_tracer()
        self.harness = SoakHarness(runner, control=control,
                                   election=election,
                                   tracer=self.tracer)
        #: mixed-load read side (soak.serveload.ServeLoad): pumped once
        #: per ingest chunk so reads contend with live ingestion, and
        #: the replica-kill fault has a tier to hit.
        self.read_load = read_load
        if read_load is not None:
            self.harness.serve_tier = read_load.tier
        self.slo = SLOTracker(self.spec, window_s=config.window_s,
                              tracer=self.tracer)
        self.records_per_step = records_per_step
        self._rate_now = 0.0
        self._backlog_chunks = 0
        self._truncated = False
        self._soak_now = 0.0
        #: closed-loop policy engine (autoscale.AutoscaleController):
        #: when attached, the driver samples ScaleSignals at every
        #: completed+drained fence and lets the controller decide and
        #: execute — worker re-cuts ride harness.autoscale_rescale
        #: (zero operator events), replica moves ride the serve tier.
        #: gray-failure detector (obs/detect.py): scored at every
        #: completed+drained fence; its sustained-suspect count feeds
        #: the signal plane's unhealthy arm. Defaults to the process
        #: detector — NullDetector unless configure_detector() ran.
        self.detector = detector if detector is not None \
            else get_detector()
        self.autoscaler = autoscaler
        self._signals = None
        if autoscaler is not None:
            self._signals = SignalAggregator()
            tier = self.harness.serve_tier
            autoscaler.bind(
                execute_workers=self.harness.autoscale_rescale,
                add_replica=(tier.add_replica if tier is not None
                             else None),
                drop_replica=(tier.drop_replica if tier is not None
                              else None),
                healthy=lambda: (
                    not self.runner.heartbeats.expired()
                    and not self.runner.fence_tail_in_flight()))
        self._register_gauges()
        self._attach_incident_providers()

    def _attach_incident_providers(self) -> None:
        """Hand the flight recorder (obs/incident.py) the soak run's
        evidence sources: both ledgers (runner vs control twin), both
        determinant windows, the chaos schedule, the decision log, the
        cluster metrics rollup, and the run config. Providers are
        closures over live objects — the manager snapshots through
        them only at capture time, so the enabled-but-quiet cost is
        zero; when the plane is disabled this attaches nothing at
        all."""
        from clonos_tpu.obs.incident import (capture_epoch_window,
                                             get_incidents)
        mgr = get_incidents()
        if not mgr.enabled:
            return

        def ledgers():
            out = {"actual": list(self.runner.auditor.ledger())}
            if self.harness.control is not None:
                out["expected"] = list(
                    self.harness.control.auditor.ledger())
            return out

        def det_window(epoch):
            out = {"actual": capture_epoch_window(
                self.runner.executor, epoch)}
            if self.harness.control is not None:
                out["expected"] = capture_epoch_window(
                    self.harness.control.executor, epoch)
            return out

        mgr.attach(
            ledgers=ledgers,
            det_window=det_window,
            chaos=lambda: self.schedule.to_text(),
            metrics=lambda: [{"metrics": self.runner.metrics.snapshot()}],
            decisions=lambda: (list(self.autoscaler.log.records)
                               if self.autoscaler is not None else []),
            config=lambda: {"rate": self.cfg.rate,
                            "duration_s": self.cfg.duration_s,
                            "window_s": self.cfg.window_s,
                            "chunk_steps": self.cfg.chunk_steps},
        )
        mgr.register_gauges(self.runner.metrics)

    def _register_gauges(self) -> None:
        g = self.runner.metrics.group("soak")
        cfg, h, slo = self.cfg, self.harness, self.slo
        g.gauge("target-rate", lambda: cfg.rate)
        # what the open-loop client is CURRENTLY offering: the base
        # rate times any live load-spike factor — the signal plane's
        # numerator (autoscale/signals.py reads it by suffix).
        g.gauge("offered-rate", lambda: round(
            cfg.rate * h.rate_factor(self._soak_now), 1))
        g.gauge("rate", lambda: round(self._rate_now, 1))
        g.gauge("backlog-chunks", lambda: self._backlog_chunks)
        g.gauge("windows-breached",
                lambda: len(slo.breached_windows()))
        g.gauge("faults-injected", lambda: h.faults_injected)
        g.gauge("faults-survived", lambda: h.faults_survived)
        g.gauge("p99-ms", lambda: round(quantile(
            (slo.closed[-1].corrected_ms if slo.closed
             else slo.current.corrected_ms), 0.99), 3))
        g.gauge("audit-ok", lambda: int(not h.divergences))
        g.gauge("rescales", lambda: h.rescales)
        g.gauge("degraded-workers", lambda: len(
            self.runner.heartbeats.degraded(cfg.degraded_grace_s)))
        if self.detector.enabled:
            # cluster.health.* rides the same rollup — re-registered
            # (like soak.*) on the NEW incarnation's registry
            self.detector.register_gauges(self.runner.metrics)
        if self.autoscaler is not None:
            # autoscale.* rides the same rollup — re-registered (like
            # soak.*) on the NEW incarnation's registry after a re-cut
            self.autoscaler.register_gauges(
                self.runner.metrics,
                actual_workers=lambda: _keyed_parallelism(self.runner),
                actual_replicas=lambda: (
                    len(self.read_load.tier.replicas)
                    if self.read_load is not None else 0))

    # --- leadership gate -----------------------------------------------------

    def _leadership_gate(self, soak_now: float) -> None:
        el = self.harness.election
        if el is None:
            return
        if soak_now < getattr(self, "_next_renew_s", 0.0):
            return
        self._next_renew_s = soak_now + self.cfg.renew_every_s
        if el.renew():
            return
        # Deposed: ingestion pauses (split-brain structurally excluded —
        # a non-leader never fences deployments) while records keep
        # queueing on the intended schedule; the pause is an outage the
        # corrected latency and max_recovery_ms both see.
        self.tracer.event("soak.leader.lost")
        t0 = _time.monotonic()
        while not el.try_acquire():
            _time.sleep(0.02)
        ms = (_time.monotonic() - t0) * 1e3
        self.harness.recoveries_ms.append(ms)
        self.harness.faults_survived += 1
        self.slo.observe_recovery(soak_now, ms)
        self.tracer.event("soak.leader.reacquired",
                          pause_ms=round(ms, 1))

    # --- gray-failure detection ----------------------------------------------

    def _detect_fence(self, r, ex) -> None:
        """One detector evaluation at a completed+drained fence: build
        the pinnable :class:`GraySnapshot` off the same rollup the
        signal plane samples (plus the heartbeat monitor's peer-relative
        ages) and run the pure scorer. Emits ``health.gray-suspect``
        timeline events and updates the ``cluster.health.suspects``
        gauge — BEFORE the autoscale sample of the same fence, so the
        policy's unhealthy arm sees this fence's verdict."""
        snap = r.metrics.snapshot()
        staleness = {
            k[:-len(".staleness-epochs")]: float(v)
            for k, v in snap.items()
            if k.endswith(".staleness-epochs")
            and isinstance(v, (int, float))}
        epoch_ms = {}
        for k, v in snap.items():
            # per-worker epoch timing from the cluster rollup
            # (worker.<eid>.….epoch.steps-ms histograms)
            if k.endswith(".epoch.steps-ms") and k.startswith("worker.") \
                    and isinstance(v, dict):
                epoch_ms[k.split(".", 2)[1]] = float(v.get("mean", 0.0))
        stall = 0.0
        for k, v in snap.items():
            if k.endswith("epoch.fence-ms") and isinstance(v, dict):
                stall = max(stall,
                            float(v.get("p99", 0.0))
                            - float(v.get("p50", 0.0)))
        self.detector.on_fence(GraySnapshot.build(
            epoch=ex.epoch_id,
            hb_age_ms={f"w{f}": a
                       for f, a in r.heartbeats.ages_ms().items()},
            epoch_ms=epoch_ms, staleness=staleness,
            fence_stall_ms=stall))

    # --- the closed loop -----------------------------------------------------

    def _autoscale_fence(self, r, ex, now_s: float):
        """One autoscaler evaluation at a completed+drained fence:
        sample :class:`ScaleSignals` off the metric rollup, let the
        controller decide (the decision and its snapshot land in the
        SCALE determinant log regardless of outcome) and execute. An
        executed worker re-cut swaps the runner incarnation underneath
        us — rebind every live handle and re-register the gauges,
        exactly like the operator ``rescale`` path. Returns the
        (possibly new) ``(runner, executor)`` pair."""
        h = self.harness
        sigs = self._signals.sample_from(
            r.metrics.snapshot(), epoch=ex.epoch_id,
            workers=_keyed_parallelism(r),
            failed_subtasks=len(r.heartbeats.expired()),
            unfenced=r.fence_tail_in_flight(),
            gray_suspects=len(self.detector.suspects()))
        decision, executed = self.autoscaler.on_fence(ex.epoch_id, sigs)
        if executed is not None and h.runner is not r:
            # a worker re-cut ran: the fence stall is an outage the
            # paced load paid — charge it like any recovery window
            self.slo.observe_recovery(now_s, h.recoveries_ms[-1])
            r = self.runner = h.runner
            ex = r.executor
            self._register_gauges()
        return r, ex

    # --- the paced loop ------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        cfg, r, h = self.cfg, self.runner, self.harness
        ex = r.executor
        spe = ex.steps_per_epoch
        if spe % cfg.chunk_steps:
            raise ValueError(
                f"steps_per_epoch {spe} must be a multiple of "
                f"chunk_steps {cfg.chunk_steps}")
        max_epochs = ex.compiled.max_epochs
        with self.tracer.span("soak", rate=cfg.rate,
                              duration_s=cfg.duration_s,
                              events=len(self.schedule)):
            verdict = self._run_paced(cfg, r, h, ex, spe, max_epochs)
        return verdict

    def _run_paced(self, cfg, r, h, ex, spe, max_epochs):
        # Warmup epoch 0 via run_epoch (staged program + restore point),
        # epoch 1 via step() chunks (the K=1 live program the paced loop
        # uses compiles here, off the measured clock).
        r.run_epoch(complete_checkpoint=True)
        for _ in range(spe):
            r.step()
        r.run_epoch(complete_checkpoint=True)   # fence-only: 0 steps left
        # deployed-standby analog: recovery programs compile off the
        # paced clock, so the first kill measures the protocol
        r.prewarm_recovery()
        # pipelined fence: _last_records_total is absorbed on the fence
        # worker — join any in-flight warmup tail before reading it
        r.drain_fence()
        if self.records_per_step is None:
            self.records_per_step = max(
                1, r._last_records_total // max(r.global_step, 1))
        rps = self.records_per_step
        chunk_records = cfg.chunk_steps * rps
        period_s = chunk_records / cfg.rate
        events = list(self.schedule)
        ei = 0
        due: List[ChaosEvent] = []
        pending_kills: List[ChaosEvent] = []
        pending_rescales: List[ChaosEvent] = []
        kill_armed = False       # last fence completed; no pendings
        force_complete = False
        fences = 0
        sent_chunks = 0
        sent_records = 0
        t0 = _time.monotonic()
        # accumulating token bucket: ``intended_s`` is the instant the
        # NEXT chunk is due. A live load-spike divides the period, so
        # the offered schedule genuinely accelerates mid-run (and the
        # corrected latency of every queued chunk is charged against
        # the spiked schedule, open-loop style).
        intended_s = 0.0

        while True:
            if intended_s >= cfg.duration_s:
                break
            if ex.epoch_id >= max_epochs - 2:
                self._truncated = True
                self.tracer.event("soak.truncated",
                                  epoch=ex.epoch_id)
                break
            now_s = _time.monotonic() - t0
            if now_s < intended_s:
                _time.sleep(intended_s - now_s)
                now_s = intended_s
            self._backlog_chunks = max(
                0, int((now_s - intended_s) / period_s))
            # -- due chaos events (soak clock): collected here, applied
            # AFTER the chunk — every fault lands mid-epoch, with this
            # epoch's window already holding live causal state for the
            # perturbation (nondet) or replay span (kill) to hit.
            while ei < len(events) and events[ei].at_s <= now_s:
                ev = events[ei]
                ei += 1
                if ev.kind == "kill":
                    # defer further, to the epoch after a completing
                    # fence: with nothing pending, recovery appends no
                    # IGNORE_CHECKPOINT determinants and the digest
                    # chain stays control-comparable (module docstring)
                    pending_kills.append(ev)
                    force_complete = True
                elif ev.kind == "rescale":
                    # a re-cut happens AT a completing fence (the
                    # protocol's fence phase) — defer like a kill,
                    # forcing the next fence to complete
                    pending_rescales.append(ev)
                    force_complete = True
                else:
                    due.append(ev)
            self._leadership_gate(now_s)
            # -- one chunk of supersteps (the token's worth of load)
            send_wall = _time.monotonic()
            for _ in range(cfg.chunk_steps):
                r.step()
            if h.gray_delay_s:
                # the degraded worker stretches the chunk's transport
                _time.sleep(h.gray_delay_s)
            done_wall = _time.monotonic()
            now_s = done_wall - t0
            self._soak_now = now_s
            sent_chunks += 1
            sent_records += chunk_records
            self._rate_now = sent_records / max(now_s, 1e-9)
            self.slo.observe(now_s,
                             corrected_ms=(now_s - intended_s) * 1e3,
                             actual_ms=(done_wall - send_wall) * 1e3,
                             records=chunk_records)
            # advance the bucket by one (possibly spiked) period — the
            # factor at the chunk's wall instant, so a spike window on
            # the soak clock accelerates exactly the chunks inside it
            intended_s += period_s / h.rate_factor(now_s)
            # -- read load rides the same clock: each ingest chunk is
            # chased by a burst of routed reads, so read latency and
            # staleness are measured UNDER concurrent ingest, and a
            # replica-kill mid-run shows up as reroutes + a staleness
            # spike in the read windows — never as client errors.
            if self.read_load is not None:
                self.read_load.pump(now_s)
            # -- collected events fire mid-epoch, right after a chunk
            for ev in due:
                h.apply(ev, now_s)
                self.slo.observe_fault(now_s, ev.kind)
                # A replica-kill's degradation window can close at the
                # very next seal (revival is one fence away) — chase it
                # with an immediate read burst so the reroutes and the
                # staleness spike are WITNESSED while the replica is
                # down, not inferred.
                if (ev.kind == "replica-kill"
                        and self.read_load is not None):
                    self.read_load.pump(now_s)
            due.clear()
            # -- armed kills fire mid-epoch, right after a chunk
            if kill_armed and pending_kills and ex.step_in_epoch > 0:
                for ev in pending_kills:
                    h.apply(ev, now_s)
                    self.slo.observe_fault(now_s, ev.kind)
                    if h.recoveries_ms:
                        self.slo.observe_recovery(
                            now_s, h.recoveries_ms[-1])
                pending_kills.clear()
                kill_armed = False
            # -- epoch fence
            if ex.step_in_epoch >= spe:
                # backlog fault: suppress completion (truncation stops,
                # the spill tiers absorb the sealed epochs), but a
                # deferred kill's fence still completes — the kill
                # invariant (no pendings at kill time) wins.
                complete = (force_complete
                            or (fences % cfg.complete_every == 0
                                and not h.backlog_active(now_s)))
                r.run_epoch(complete_checkpoint=complete)
                fences += 1
                if not complete and h.backlog_active(now_s):
                    # abandon immediately: suppressed fences must leave
                    # nothing pending either, or a kill in the backlog
                    # window appends IGNORE determinants the control
                    # twin never sees (digest divergence by design,
                    # not by bug). Pipelined fence: the in-flight tail
                    # is still creating this epoch's pending — join it
                    # first or the discard races the worker's trigger.
                    r.drain_fence()
                    r.coordinator.discard_pending_through(
                        ex.epoch_id - 1)
                if complete:
                    fence_drained = False
                    if pending_kills and r.fence_tail_in_flight():
                        # kill MID-fence-tail: abandon only the OLDER
                        # skipped checkpoints (sparing the in-flight
                        # epoch's), then fire the kill NOW, while the
                        # seal/ledger/checkpoint tail is still on the
                        # fence worker. inject_failure joins the tail
                        # first, so the seal and ack complete, nothing
                        # is pending at kill time, and recovery appends
                        # no IGNORE determinants — the digest chain
                        # stays byte-comparable with the control twin.
                        r.coordinator.discard_pending_through(
                            ex.epoch_id - 2)
                        h.kills_mid_fence_tail += len(pending_kills)
                        for ev in pending_kills:
                            h.apply(ev, now_s)
                            self.slo.observe_fault(now_s, ev.kind)
                            if h.recoveries_ms:
                                self.slo.observe_recovery(
                                    now_s, h.recoveries_ms[-1])
                        pending_kills.clear()
                    else:
                        # abandon OLDER skipped fences' checkpoints: a
                        # completing fence must leave nothing pending,
                        # or the next kill's recovery ignores them and
                        # the IGNORE determinants diverge from the
                        # control. Join the in-flight tail first — its
                        # ack (completion) lands at the join.
                        r.drain_fence()
                        r.coordinator.discard_pending_through(
                            ex.epoch_id - 1)
                        fence_drained = True
                    force_complete = False
                    kill_armed = bool(pending_kills)
                    if pending_rescales:
                        # the fence completed and drained: the handoff
                        # point (latest completed checkpoint == this
                        # fence) exists NOW, before the next chunk
                        r.drain_fence()
                        for ev in pending_rescales:
                            h.apply(ev, now_s)
                            self.slo.observe_fault(now_s, ev.kind)
                            if h.recoveries_ms:
                                self.slo.observe_recovery(
                                    now_s, h.recoveries_ms[-1])
                        pending_rescales.clear()
                        # the harness swapped incarnations underneath
                        # us: rebind every live handle and re-register
                        # the gauges on the new runner's registry
                        r = self.runner = h.runner
                        ex = r.executor
                        self._register_gauges()
                    if fence_drained and self.detector.enabled:
                        # gray-failure scoring at the same fence cadence
                        # as the signal plane, and BEFORE its sample —
                        # this fence's verdict reaches this fence's
                        # policy evaluation
                        self._detect_fence(r, ex)
                    if self.autoscaler is not None and fence_drained:
                        # the closed loop: signals sampled off the
                        # metric rollup at THIS completed+drained
                        # fence, policy decides, and a scale action
                        # executes here — the only place a self-
                        # directed re-cut is allowed to happen
                        r, ex = self._autoscale_fence(r, ex, now_s)
                if h.audit_pending:
                    # the fence worker may be mid seal -> ledger
                    # append; diffing now would report a false
                    # missing-entry divergence
                    r.drain_fence()
                    h.audit_check()
                    h.audit_pending = False
            h.tick(now_s)

        # -- drain: still-pending kills get their completed fence first
        # (same no-IGNORE invariant as the paced path), then the last
        # epoch closes and the final audit sweep covers every seal.
        now_s = _time.monotonic() - t0
        if due:
            if ex.step_in_epoch == 0:
                for _ in range(cfg.chunk_steps):
                    r.step()
            for ev in due:
                h.apply(ev, now_s)
                self.slo.observe_fault(now_s, ev.kind)
            due.clear()
        if pending_kills:
            r.run_epoch(complete_checkpoint=True)
            r.drain_fence()
            r.coordinator.discard_pending_through(ex.epoch_id - 1)
            for _ in range(cfg.chunk_steps):
                r.step()
            for ev in pending_kills:
                h.apply(ev, now_s)
                self.slo.observe_fault(now_s, ev.kind)
                if h.recoveries_ms:
                    self.slo.observe_recovery(now_s,
                                              h.recoveries_ms[-1])
        h.tick(float("inf"))
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()      # final sweep must see every in-flight seal
        if pending_rescales:
            # a re-cut due in the last window still hands off at a real
            # completed fence (the one just run) — the final audit then
            # covers the post-re-cut ledger too
            for ev in pending_rescales:
                h.apply(ev, now_s)
                self.slo.observe_fault(now_s, ev.kind)
                if h.recoveries_ms:
                    self.slo.observe_recovery(now_s,
                                              h.recoveries_ms[-1])
            pending_rescales.clear()
            r = self.runner = h.runner
            ex = r.executor
        h.audit_check()
        if self.read_load is not None:
            # one post-drain pump: the final fence sealed, so this burst
            # witnesses staleness RECOVERY after any replica-kill
            self.read_load.pump(_time.monotonic() - t0, final=True)
        wall_s = _time.monotonic() - t0
        return self._verdict(wall_s, sent_records, ei)

    # --- verdict -------------------------------------------------------------

    def _verdict(self, wall_s: float, sent_records: int,
                 events_fired: int) -> Dict[str, Any]:
        h, cfg = self.harness, self.cfg
        windows = self.slo.finish()
        corrected = self.slo.all_corrected_ms()
        actual = self.slo.all_actual_ms()
        audited = h.control is not None and h.runner.auditor.enabled
        audit_ok = audited and not h.divergences
        exactly_once = (audit_ok and h.epochs_checked > 0) \
            if audited else None
        breached = self.slo.breached_windows()
        slo_ok = not breached
        passed = slo_ok and (not self.spec.exactly_once
                             or bool(exactly_once))
        worst = self.slo.worst_window()
        out = {
            "metric": "soak_slo_verdict",
            "pass": passed,
            "rate_target": cfg.rate,
            "rate_achieved": round(sent_records / max(wall_s, 1e-9), 1),
            "duration_s": round(wall_s, 2),
            "records": sent_records,
            "latency": {
                "basis": "corrected (intended-send time; "
                         "coordinated-omission-free)",
                "p50_ms": round(quantile(corrected, 0.50), 3),
                "p99_ms": round(quantile(corrected, 0.99), 3),
                "p999_ms": round(quantile(corrected, 0.999), 3),
                "actual_send_p99_ms": round(quantile(actual, 0.99), 3),
            },
            "windows": [w.stats() for w in windows],
            "worst_window": worst.stats() if worst else None,
            "windows_breached": len(breached),
            "faults": {
                "injected": h.faults_injected,
                "survived": h.faults_survived,
                "by_kind": dict(sorted(h.by_kind.items())),
                "recoveries_ms": [round(m, 1)
                                  for m in h.recoveries_ms],
                # Overlapped recovery under chaos kill: per-kill
                # finalize.overlap-saved attribution, and the count of
                # ledger problems from the immediate post-kill re-diff
                # vs the control twin (0 == every overlapped recovery
                # left bit-identical state).
                "kill_overlap_saved_ms": list(h.kill_overlap_saved_ms),
                "kill_rediff_problems": h.kill_rediff_problems,
                # kills fired while the pipelined fence tail was still
                # in flight (inject joins it first): each one exercised
                # the kill-mid-seal drain ordering under load.
                "kills_mid_fence_tail": h.kills_mid_fence_tail,
                # live re-cuts (the `rescale` event): per-handoff fence
                # checkpoint, drained in-flight records, moved key
                # groups, and the fence-stall cost the paced load paid.
                "rescales": h.rescales,
                "rescale_stats": list(h.rescale_stats),
            },
            "audit": {
                "enabled": audited,
                "exactly_once": exactly_once,
                "epochs_checked": h.epochs_checked,
                "divergences": h.divergences[:8],
            },
            "slo": self.spec.to_dict(),
            "events_fired": events_fired,
            "schedule": self.schedule.to_text(),
            "truncated": self._truncated,
        }
        if self.read_load is not None:
            # Read-tier verdict rides the soak verdict: the serve
            # numbers only mean anything against the ingest load they
            # contended with (the honest-measurement requirement).
            out["serve"] = self.read_load.summary()
            out["serve"]["replica_kills"] = h.replica_kills
        if self.detector.enabled:
            # Gray-failure verdict: the sustained suspects at run end,
            # the per-fence scoring history length, and a bit-identical
            # replay proof over the pinned snapshots (the same
            # discipline the SCALE log's verdict pins with its digest).
            d = self.detector
            try:
                d.replay()
                replay_ok = True
            except ValueError:
                replay_ok = False
            out["health"] = {
                "suspects": d.suspects(),
                "gray_events": d.events_emitted,
                "fences_scored": len(d.log),
                "replay_bit_identical": replay_ok,
            }
        if self.autoscaler is not None:
            # Closed-loop verdict: every decision is in the SCALE log
            # (digest pins the byte encoding), scale actions are rate-
            # limited by the cooldown (max_actions_per_cooldown must be
            # <= 1 for a well-behaved policy), and the self-directed
            # re-cuts are itemized apart from operator events — the
            # acceptance bar is operator_rescale_events == 0 with
            # autoscale_rescales > 0 under a load spike.
            a = self.autoscaler
            by_action: Dict[str, int] = {}
            for rec in a.log.records:
                act = rec["decision"]["action"]
                by_action[act] = by_action.get(act, 0) + 1
            out["autoscale"] = {
                "decisions": len(a.log),
                "by_action": dict(sorted(by_action.items())),
                "rescales_executed": a.rescales_executed,
                "replicas_added": a.replicas_added,
                "replicas_dropped": a.replicas_dropped,
                "refusals": a.refusals,
                "replayed_decisions": a.replayed_decisions,
                "max_actions_per_cooldown": _max_actions_per_cooldown(
                    a.log.records, a.policy.cfg.cooldown_fences),
                "cooldown_fences": a.policy.cfg.cooldown_fences,
                "operator_rescale_events": h.rescales,
                "autoscale_rescales": h.autoscale_rescales,
                "rescale_stats": list(h.autoscale_stats),
                "log_digest": a.log.digest(),
                "log_path": a.log.path,
            }
        # The FT call-site population this run exercised
        # (analysis/census.py): SOAK_r0N.json numbers stay traceable
        # to the exact source shape that produced them.
        try:
            from clonos_tpu.analysis import census_fingerprint
            out["census_fingerprint"] = census_fingerprint()
        except Exception:                             # pragma: no cover
            out["census_fingerprint"] = None
        return out


def default_kill_targets(job) -> List[int]:
    """One flat subtask per vertex class (subtask 1 where parallelism
    allows, else 0) — the config4 cascading-failure pattern. A cascade
    drawn from this pool never takes out ALL replicas of one vertex,
    which would leave no survivor holding the dead task's determinant
    log (unrecoverable by design, not a harness bug)."""
    return [job.subtask_base(v.vertex_id) + min(1, v.parallelism - 1)
            for v in job.vertices]


def next_soak_artifact_path(root: Optional[str] = None) -> str:
    """Next free ``SOAK_r0N.json`` slot next to the BENCH artifacts."""
    root = root or os.getcwd()
    n = 1
    while os.path.exists(os.path.join(root, f"SOAK_r{n:02d}.json")):
        n += 1
    return os.path.join(root, f"SOAK_r{n:02d}.json")


def next_serve_artifact_path(root: Optional[str] = None) -> str:
    """Next free ``SERVE_r0N.json`` slot (the ``bench --serve``
    verdict artifact, sibling of SOAK/BENCH)."""
    root = root or os.getcwd()
    n = 1
    while os.path.exists(os.path.join(root, f"SERVE_r{n:02d}.json")):
        n += 1
    return os.path.join(root, f"SERVE_r{n:02d}.json")


def next_rescale_artifact_path(root: Optional[str] = None) -> str:
    """Next free ``RESCALE_r0N.json`` slot (the ``bench --rescale``
    verdict artifact, sibling of SOAK/BENCH/SERVE)."""
    root = root or os.getcwd()
    n = 1
    while os.path.exists(os.path.join(root, f"RESCALE_r{n:02d}.json")):
        n += 1
    return os.path.join(root, f"RESCALE_r{n:02d}.json")


def next_autoscale_artifact_path(root: Optional[str] = None) -> str:
    """Next free ``AUTOSCALE_r0N.json`` slot (the ``soak --autoscale``
    closed-loop verdict artifact, sibling of SOAK/BENCH/SERVE)."""
    root = root or os.getcwd()
    n = 1
    while os.path.exists(os.path.join(root,
                                      f"AUTOSCALE_r{n:02d}.json")):
        n += 1
    return os.path.join(root, f"AUTOSCALE_r{n:02d}.json")


def build_soak_fixture(workdir: str, rate: float, duration_s: float,
                       steps_per_epoch: int = 64, par: int = 2,
                       batch: int = 8, seed: int = 11,
                       audit: bool = True, lease_ttl_s: float = 2.0,
                       num_keys: int = 101,
                       overlap_epoch: bool = False,
                       serve_vertex: bool = False):
    """Construct the soak trio: runner, fault-free control twin, and a
    held leader lease — same job, same seed, logical time on BOTH
    runners (digest chains are only byte-comparable across runs when
    timestamps are causal step counts, the multichip-probe precedent).

    Sizing: the ring must hold the longest un-truncated span
    (``complete_every`` epochs plus the live one), the log the same span
    of determinant rows, and ``max_epochs`` the whole run plus warmup
    slack — all rounded to powers of two, the bench idiom.
    """
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.runtime.leader import FileLeaderElection

    def build(keyed_par=None):
        # ``keyed_par`` re-cuts the keyed stages only (the live-rescale
        # job shape: source and sink keep their parallelism, keyed
        # vertices move — restore_rescaled's constraint).
        env = StreamEnvironment(name="soak", num_key_groups=16)
        s = (env.synthetic_source(vocab=num_keys, batch_size=batch,
                                  parallelism=par)
             .key_by()
             .window_count(num_keys=num_keys, window_size=1 << 30,
                           name="window", parallelism=keyed_par))
        if serve_vertex:
            # a KeyedReduceOperator stage (emits_running_value) so the
            # read tier's replicas can tail it to fence freshness
            s = s.key_by().reduce(num_keys=num_keys, name="reduce",
                                  parallelism=keyed_par)
        # the sink keeps its cut across a re-cut (it would otherwise
        # inherit the keyed stage's), and its input edge is HASH so the
        # edge type is stable when the upstream parallelism moves —
        # restore_rescaled re-routes HASH buffers, not FORWARD ones
        s.key_by().sink(parallelism=par)
        return env.build()

    records_per_step = par * batch
    expected_epochs = int(np.ceil(
        duration_s * rate / (records_per_step * steps_per_epoch)))
    max_epochs = 1 << (expected_epochs + 8).bit_length()
    span = 4 * steps_per_epoch
    log_capacity = 1 << (2 * span * DETS_PER_STEP).bit_length()
    ring_steps = 1 << (span - 1).bit_length()

    def lineage_for(sub):
        # One plane per twin, SAME dye config (k, salt come from the
        # armed process plane): both runners dye identical records —
        # the dye is a pure key-hash function and logical time makes
        # their windows bit-identical — but observations land in
        # per-twin files, so `clonos_tpu lineage` can diff the faulted
        # path against the fault-free one byte for byte.
        from clonos_tpu.obs.lineage import LineagePlane, get_lineage
        g = get_lineage()
        if not g.enabled:
            return None
        return LineagePlane(g.root, service=f"soak-{sub}", k=g.k,
                            salt=g.salt)

    def runner_for(sub, overlap=False):
        return ClusterRunner(
            build(), steps_per_epoch=steps_per_epoch,
            log_capacity=log_capacity, max_epochs=max_epochs,
            inflight_ring_steps=ring_steps,
            checkpoint_dir=os.path.join(workdir, sub),
            audit=audit, logical_time=True, seed=seed,
            lineage=lineage_for(sub),
            overlap_epoch=overlap)

    def arm_rescaler(r, sub, overlap=False):
        """Arm a runner for the chaos ``rescale`` event: a closure that
        re-cuts THIS runner to a new keyed parallelism at its completed
        fence (ClusterRunner.rescale_live) with the same sizing knobs,
        then re-arms the new incarnation so repeated re-cuts compose."""
        def rescale(target):
            nr, stats = r.rescale_live(
                build(keyed_par=int(target)),
                steps_per_epoch=steps_per_epoch,
                log_capacity=log_capacity, max_epochs=max_epochs,
                inflight_ring_steps=ring_steps,
                checkpoint_dir=os.path.join(workdir, sub),
                audit=audit, logical_time=True, seed=seed,
                lineage=r.lineage, overlap_epoch=overlap)
            arm_rescaler(nr, sub, overlap)
            return nr, stats
        r._soak_rescaler = rescale
        return r

    # Only the soak runner pipelines its fence; the control twin stays
    # strictly sequential, so the ledger diff is always overlapped-vs-
    # sequential — the strongest bit-identity witness available.
    runner = arm_rescaler(runner_for("run", overlap_epoch), "run",
                          overlap_epoch)
    control = (arm_rescaler(runner_for("control"), "control")
               if audit else None)
    election = FileLeaderElection(os.path.join(workdir, "lease"),
                                  "soak-driver", lease_ttl_s=lease_ttl_s)
    election.try_acquire()
    return runner, control, election
