"""Exactly-once auditing: per-epoch digest seal + process-global switch.

Mirrors obs/trace.py's shape exactly: a zero-overhead :class:`NullAuditor`
is the process default (``enabled`` is a class attribute, so the hot
``if auditor.enabled`` check costs one attribute load and audit-off runs
do no per-record host work and add no wire fields), and
:func:`configure` swaps in a live :class:`Auditor` under a lock.

The Auditor itself is thin: policy (warn vs abort on divergence) plus an
in-memory ledger of sealed digests. Digest COMPUTATION lives in
:func:`digest_epoch_window`, fed by ``LocalExecutor.epoch_window`` — the
single extraction path shared by the live seal (ClusterRunner.run_epoch)
and the recovery-time recompute (causal/recovery.AuditValidator), which
is what makes the chain's chunk boundaries identical on both sides.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from clonos_tpu.obs.digest import EpochDigest

#: accepted divergence policies (config validator + CLI share this)
DIVERGENCE_POLICIES = ("warn", "abort")


class NullAuditor:
    """Audit disabled: every operation is a no-op. The default."""

    enabled = False
    on_divergence = "warn"

    def seal(self, digest: EpochDigest) -> None:
        pass

    def adopt(self, entries) -> None:
        pass

    def ledger(self) -> List[dict]:
        return []

    @property
    def last_epoch(self) -> int:
        return -1

    @property
    def epochs_sealed(self) -> int:
        return 0

    def close(self) -> None:
        pass


class Auditor(NullAuditor):
    """Live auditor: records sealed digests and carries the divergence
    policy. One per runner (sealing is a main-thread fence action), but
    also installable process-globally via :func:`configure` so remote
    workers inherit the JobMaster's audit stance (transport.adopt_audit)."""

    enabled = True

    def __init__(self, on_divergence: str = "warn"):
        if on_divergence not in DIVERGENCE_POLICIES:
            raise ValueError(
                f"on_divergence must be one of {DIVERGENCE_POLICIES}, "
                f"got {on_divergence!r}")
        self.on_divergence = on_divergence
        self._sealed: Dict[int, dict] = {}
        self._lock = threading.Lock()

    def seal(self, digest: EpochDigest) -> None:
        with self._lock:
            self._sealed[digest.epoch] = digest.to_entry()

    def adopt(self, entries) -> None:
        """Carry a predecessor incarnation's sealed entries forward
        (live re-cut: the new runner's ledger must span the handoff so
        cross-re-cut diffs see one continuous chain). Existing seals
        win — an epoch this incarnation sealed itself is authoritative."""
        with self._lock:
            for e in entries:
                self._sealed.setdefault(int(e["epoch"]), dict(e))

    def ledger(self) -> List[dict]:
        with self._lock:
            return [self._sealed[e] for e in sorted(self._sealed)]

    @property
    def last_epoch(self) -> int:
        with self._lock:
            return max(self._sealed) if self._sealed else -1

    @property
    def epochs_sealed(self) -> int:
        with self._lock:
            return len(self._sealed)


# --- digest extraction -------------------------------------------------------

#: 2^64 wrap for the order-insensitive content sums
_SUM_MASK = (1 << 64) - 1


def _content_sum(keys, values, timestamps) -> int:
    """Order- and lane-layout-insensitive content accumulator: the sum
    mod 2^64 of a 64-bit avalanche hash per (key, value, timestamp)
    record. A SUM (not XOR) so duplicated records shift the value — the
    exactly-once hazard the repartition invariant is about. Pure
    function of the record multiset: two runs of the same job cut to
    different parallelism fold the same per-vertex value."""
    import numpy as np
    k = np.ascontiguousarray(keys, np.int32).astype(np.uint64)
    v = np.ascontiguousarray(values, np.int32).astype(np.uint64)
    t = np.ascontiguousarray(timestamps, np.int32).astype(np.uint64)
    x = (k * np.uint64(0x9E3779B97F4A7C15)
         + v * np.uint64(0xC2B2AE3D27D4EB4F)
         + t * np.uint64(0x165667B19E3779F9))
    # splitmix64 finalizer
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return int(np.sum(x, dtype=np.uint64)) & _SUM_MASK


def digest_epoch_window(epoch: int, window: dict,
                        layout=None) -> EpochDigest:
    """Fold one epoch's causal surface (``LocalExecutor.epoch_window``
    output) into an :class:`EpochDigest`.

    Chunk-boundary contract (chain folds are order-sensitive): each
    ``log/<flat>`` channel is folded as ONE chunk — the epoch's
    determinant-row window in log order; each ``ring/v<vid>`` channel is
    folded ONE chunk PER STEP — the step's valid (key, value, timestamp)
    records flattened in (lane, slot) order. Live seal and recovery
    recompute both call this function, so the boundaries always agree.

    Alongside each layout-dependent ``ring/v<vid>`` chain, a
    partition-INVARIANT ``ringsum/v<vid>`` channel folds the epoch's
    order-insensitive record-content sum (:func:`_content_sum`) — the
    channel ``diff_ledgers_cross`` compares when two ledgers were
    sealed under different cuts of the same job. ``layout`` stamps the
    partition shape (``((vertex_id, parallelism), ...)``) into the
    digest so the diff can tell which regime applies.
    """
    import numpy as np
    from clonos_tpu.causal import determinant as det

    dg = EpochDigest(epoch, layout=layout)
    for flat, rows in sorted(window.get("logs", {}).items()):
        rows = np.ascontiguousarray(rows, np.int32)
        dg.fold(f"log/{flat}", det.to_bytes(rows), count=rows.shape[0])
        if rows.shape[0]:
            counts = np.bincount(rows[:, det.LANE_TAG],
                                 minlength=det.NUM_TAGS)
            for tag in range(det.NUM_TAGS):
                dg.count_det(det.TAG_NAMES[tag], int(counts[tag]))
    for vid, steps in sorted(window.get("rings", {}).items()):
        chan = f"ring/v{vid}"
        total = 0
        csum = 0
        for keys, values, timestamps in steps:
            data = (np.ascontiguousarray(keys, np.int32).tobytes()
                    + np.ascontiguousarray(values, np.int32).tobytes()
                    + np.ascontiguousarray(timestamps, np.int32).tobytes())
            n = int(np.asarray(keys).shape[0])
            dg.fold(chan, data, count=n)
            total += n
            csum = (csum + _content_sum(keys, values, timestamps)) \
                & _SUM_MASK
        if steps:
            dg.fold(f"ringsum/v{vid}", csum.to_bytes(8, "little"),
                    count=total)
    return dg


# --- cross-partition ledger mapping ------------------------------------------


def key_group_directory(old_parallelism: int, new_parallelism: int,
                        num_key_groups: int
                        ) -> tuple:
    """The old↔new group directory of a re-cut: for every key group,
    ``(kg, old_subtask, new_subtask)`` under the reference range
    assignment (``kg * parallelism // num_key_groups`` —
    parallel/routing.subtask_for_key_group). Built HERE, once, and
    reused by both consumers: ``ClusterRunner.rescale_live`` walks it
    to migrate ownership, and :func:`diff_ledgers_cross` uses the same
    assignment to know two differently-cut ledgers describe one job."""
    old_p, new_p, g = (int(old_parallelism), int(new_parallelism),
                       int(num_key_groups))
    if min(old_p, new_p, g) < 1:
        raise ValueError(
            f"key_group_directory: positive sizes required, got "
            f"old={old_p} new={new_p} groups={g}")
    return tuple((kg, (kg * old_p) // g, (kg * new_p) // g)
                 for kg in range(g))


def moved_key_groups(directory) -> tuple:
    """Key groups whose owner changes across the re-cut."""
    return tuple(kg for kg, old_s, new_s in directory if old_s != new_s)


def channel_directory(layout_a, layout_b) -> Dict[int, dict]:
    """Map two partition layouts of the SAME topology onto each other:
    ``{vertex_id: {"parallelism": (pa, pb), "log_flats": (range_a,
    range_b)}}`` where ``log_flats`` are the ``log/<flat>`` channel id
    ranges each side's vertex occupies in the stacked-log layout
    (JobGraph.subtask_base). Raises if the layouts disagree on the
    vertex set — that is a different job, not a re-cut."""
    la = {int(v): int(p) for v, p in layout_a}
    lb = {int(v): int(p) for v, p in layout_b}
    if sorted(la) != sorted(lb):
        raise ValueError(
            f"channel_directory: vertex sets differ "
            f"({sorted(la)} vs {sorted(lb)}) — not two cuts of one job")
    out: Dict[int, dict] = {}
    base_a = base_b = 0
    for vid in sorted(la):
        pa, pb = la[vid], lb[vid]
        out[vid] = {
            "parallelism": (pa, pb),
            "log_flats": (range(base_a, base_a + pa),
                          range(base_b, base_b + pb)),
        }
        base_a += pa
        base_b += pb
    return out


def _diff_entry_mapped(ea: dict, eb: dict) -> List[str]:
    """Layout-invariant comparison of two ledger entries sealed under
    DIFFERENT cuts: per-vertex ring record counts and ``ringsum``
    content fingerprints must match exactly (the record streams are
    partition-independent); ``log/<flat>`` channels are structural
    per-lane surfaces — their flat ids are checked against the stamped
    layouts via the channel directory, their content is not comparable
    across cuts."""
    ep = int(ea["epoch"])
    out: List[str] = []
    dirmap = channel_directory(ea["layout"], eb["layout"])
    ca = ea.get("channels") or {}
    cb = eb.get("channels") or {}
    for side, chans, idx in (("first", ca, 0), ("second", cb, 1)):
        flats = {int(name[len("log/"):]) for name in chans
                 if name.startswith("log/")}
        legal = {f for v in dirmap.values()
                 for f in v["log_flats"][idx]}
        stray = sorted(flats - legal)
        if stray:
            out.append(
                f"epoch {ep}: {side} ledger has log channel(s) for "
                f"flat(s) {stray} outside its stamped layout")
    for name in sorted(set(ca) | set(cb)):
        if not name.startswith(("ring/", "ringsum/")):
            continue
        a, b = ca.get(name), cb.get(name)
        if a is None or b is None:
            missing = "first" if a is None else "second"
            out.append(f"epoch {ep} channel {name}: missing from "
                       f"{missing} ledger")
            continue
        if int(a["count"]) != int(b["count"]):
            out.append(
                f"epoch {ep} channel {name}: record count "
                f"{b['count']} != expected {a['count']}")
        elif name.startswith("ringsum/") and a["fp"] != b["fp"]:
            out.append(
                f"epoch {ep} channel {name}: content sum {b['fp']} != "
                f"expected {a['fp']} (count matches: a record was "
                f"lost AND another duplicated, or content changed)")
    return out


def diff_ledgers_cross(expected: List[dict],
                       actual: List[dict]) -> List[str]:
    """Ledger diff that survives a re-cut: epochs whose entries carry
    the SAME partition layout (or none — pre-layout ledgers) compare
    exactly (obs/digest.diff — every channel, bit for bit); epochs
    sealed under DIFFERENT cuts of the same topology compare through
    the group directory on the layout-invariant channels. The
    ``clonos_tpu audit A --diff B`` surface, and the post-re-cut
    acceptance check of ``bench --rescale``."""
    from clonos_tpu.obs import digest as _digest

    ea = {int(e["epoch"]): e for e in expected}
    aa = {int(e["epoch"]): e for e in actual}
    out: List[str] = []
    for ep in sorted(set(ea) | set(aa)):
        if ep not in aa:
            out.append(f"epoch {ep}: missing from second ledger")
            continue
        if ep not in ea:
            out.append(f"epoch {ep}: missing from first ledger")
            continue
        la = ea[ep].get("layout")
        lb = aa[ep].get("layout")
        if la == lb:
            d = _digest.diff(_digest.EpochDigest.from_entry(ea[ep]),
                             _digest.EpochDigest.from_entry(aa[ep]))
            if d is not None:
                out.append(f"epoch {ep} channel {d[0]}: {d[1]}")
        elif la is None or lb is None:
            out.append(
                f"epoch {ep}: one ledger is layout-stamped and the "
                f"other is not — cannot choose exact vs mapped diff")
        else:
            try:
                out.extend(_diff_entry_mapped(ea[ep], aa[ep]))
            except ValueError as e:
                out.append(f"epoch {ep}: {e}")
    return out


# --- process-global auditor (obs/trace.py convention) ------------------------

_global_auditor: NullAuditor = NullAuditor()
_global_lock = threading.Lock()


def get_auditor() -> NullAuditor:
    return _global_auditor


def configure_audit(on_divergence: str = "warn") -> Auditor:
    """Install a process-global live auditor (the default a ClusterRunner
    built with ``audit=None`` inherits)."""
    global _global_auditor
    with _global_lock:
        old = _global_auditor
        _global_auditor = Auditor(on_divergence=on_divergence)
        old.close()
        return _global_auditor


def reset_audit() -> None:
    global _global_auditor
    with _global_lock:
        old = _global_auditor
        _global_auditor = NullAuditor()
        old.close()
