"""Exactly-once auditing: per-epoch digest seal + process-global switch.

Mirrors obs/trace.py's shape exactly: a zero-overhead :class:`NullAuditor`
is the process default (``enabled`` is a class attribute, so the hot
``if auditor.enabled`` check costs one attribute load and audit-off runs
do no per-record host work and add no wire fields), and
:func:`configure` swaps in a live :class:`Auditor` under a lock.

The Auditor itself is thin: policy (warn vs abort on divergence) plus an
in-memory ledger of sealed digests. Digest COMPUTATION lives in
:func:`digest_epoch_window`, fed by ``LocalExecutor.epoch_window`` — the
single extraction path shared by the live seal (ClusterRunner.run_epoch)
and the recovery-time recompute (causal/recovery.AuditValidator), which
is what makes the chain's chunk boundaries identical on both sides.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from clonos_tpu.obs.digest import EpochDigest

#: accepted divergence policies (config validator + CLI share this)
DIVERGENCE_POLICIES = ("warn", "abort")


class NullAuditor:
    """Audit disabled: every operation is a no-op. The default."""

    enabled = False
    on_divergence = "warn"

    def seal(self, digest: EpochDigest) -> None:
        pass

    def ledger(self) -> List[dict]:
        return []

    @property
    def last_epoch(self) -> int:
        return -1

    @property
    def epochs_sealed(self) -> int:
        return 0

    def close(self) -> None:
        pass


class Auditor(NullAuditor):
    """Live auditor: records sealed digests and carries the divergence
    policy. One per runner (sealing is a main-thread fence action), but
    also installable process-globally via :func:`configure` so remote
    workers inherit the JobMaster's audit stance (transport.adopt_audit)."""

    enabled = True

    def __init__(self, on_divergence: str = "warn"):
        if on_divergence not in DIVERGENCE_POLICIES:
            raise ValueError(
                f"on_divergence must be one of {DIVERGENCE_POLICIES}, "
                f"got {on_divergence!r}")
        self.on_divergence = on_divergence
        self._sealed: Dict[int, dict] = {}
        self._lock = threading.Lock()

    def seal(self, digest: EpochDigest) -> None:
        with self._lock:
            self._sealed[digest.epoch] = digest.to_entry()

    def ledger(self) -> List[dict]:
        with self._lock:
            return [self._sealed[e] for e in sorted(self._sealed)]

    @property
    def last_epoch(self) -> int:
        with self._lock:
            return max(self._sealed) if self._sealed else -1

    @property
    def epochs_sealed(self) -> int:
        with self._lock:
            return len(self._sealed)


# --- digest extraction -------------------------------------------------------


def digest_epoch_window(epoch: int, window: dict) -> EpochDigest:
    """Fold one epoch's causal surface (``LocalExecutor.epoch_window``
    output) into an :class:`EpochDigest`.

    Chunk-boundary contract (chain folds are order-sensitive): each
    ``log/<flat>`` channel is folded as ONE chunk — the epoch's
    determinant-row window in log order; each ``ring/v<vid>`` channel is
    folded ONE chunk PER STEP — the step's valid (key, value, timestamp)
    records flattened in (lane, slot) order. Live seal and recovery
    recompute both call this function, so the boundaries always agree.
    """
    import numpy as np
    from clonos_tpu.causal import determinant as det

    dg = EpochDigest(epoch)
    for flat, rows in sorted(window.get("logs", {}).items()):
        rows = np.ascontiguousarray(rows, np.int32)
        dg.fold(f"log/{flat}", det.to_bytes(rows), count=rows.shape[0])
        if rows.shape[0]:
            counts = np.bincount(rows[:, det.LANE_TAG],
                                 minlength=det.NUM_TAGS)
            for tag in range(det.NUM_TAGS):
                dg.count_det(det.TAG_NAMES[tag], int(counts[tag]))
    for vid, steps in sorted(window.get("rings", {}).items()):
        chan = f"ring/v{vid}"
        for keys, values, timestamps in steps:
            data = (np.ascontiguousarray(keys, np.int32).tobytes()
                    + np.ascontiguousarray(values, np.int32).tobytes()
                    + np.ascontiguousarray(timestamps, np.int32).tobytes())
            dg.fold(chan, data, count=int(np.asarray(keys).shape[0]))
    return dg


# --- process-global auditor (obs/trace.py convention) ------------------------

_global_auditor: NullAuditor = NullAuditor()
_global_lock = threading.Lock()


def get_auditor() -> NullAuditor:
    return _global_auditor


def configure_audit(on_divergence: str = "warn") -> Auditor:
    """Install a process-global live auditor (the default a ClusterRunner
    built with ``audit=None`` inherits)."""
    global _global_auditor
    with _global_lock:
        old = _global_auditor
        _global_auditor = Auditor(on_divergence=on_divergence)
        old.close()
        return _global_auditor


def reset_audit() -> None:
    global _global_auditor
    with _global_lock:
        old = _global_auditor
        _global_auditor = NullAuditor()
        old.close()
