"""Chrome ``trace_event`` conversion for flight-recorder JSON-lines.

The tracer's native record (see obs/trace.py) keeps wall-clock seconds
and trace/span/parent ids. Chrome's `Trace Event Format` (the JSON
Perfetto and ``about:tracing`` load) wants microseconds, a ``ph`` phase
letter and pid/tid lanes. :func:`to_chrome` maps

- ``ph: "X"`` records → complete events (``ts`` + ``dur`` in µs),
- ``ph: "i"`` records → instant events (process scope),
- each distinct (pid, service) → a ``process_name`` metadata event so
  the viewer labels lanes ``jm`` / ``worker a`` / … instead of bare
  pids,

and stashes trace/span ids under ``args`` so nothing is lost.
:func:`validate_chrome` is the validity check the tests (and
``tools/trace2chrome.py --check``) run over emitted files.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

_KNOWN_PH = {"X", "i", "B", "E", "M", "C"}


def load_jsonl(paths) -> List[dict]:
    """Read tracer records from one path or a list of paths (blank
    lines skipped), sorted by timestamp.

    A SIGKILLed process leaves its final JSONL line torn mid-record;
    that truncated tail is expected debris, not corruption, so it is
    dropped silently. A decode failure on any EARLIER line still
    raises — that means the file really is damaged."""
    from clonos_tpu.utils.jsonl import parse_jsonl_lines
    if isinstance(paths, (str, bytes)):
        paths = [paths]
    records: List[dict] = []
    for path in paths:
        with open(path) as f:
            lines = f.read().splitlines()
        records.extend(parse_jsonl_lines(lines, label=str(path)))
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


def to_chrome(records: Iterable[dict],
              trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Convert tracer records to a Chrome trace document, optionally
    keeping only one trace id."""
    events: List[dict] = []
    named_procs = set()
    for rec in records:
        if trace_id is not None and rec.get("trace") != trace_id:
            continue
        pid = int(rec.get("pid", 0))
        tid = int(rec.get("tid", 0))
        service = rec.get("service") or f"pid {pid}"
        if (pid, service) not in named_procs:
            named_procs.add((pid, service))
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": str(service)}})
        ev = {"name": str(rec.get("name", "?")),
              "cat": str(rec.get("service", "clonos")),
              "pid": pid, "tid": tid,
              "ts": float(rec.get("ts", 0.0)) * 1e6,
              "args": dict(rec.get("args") or {},
                           trace=rec.get("trace"), span=rec.get("span"),
                           parent=rec.get("parent"))}
        if rec.get("ph") == "X":
            ev["ph"] = "X"
            ev["dur"] = max(0.0, float(rec.get("dur", 0.0))) * 1e6
        else:
            ev["ph"] = "i"
            ev["s"] = "p"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome(doc: Dict[str, Any]) -> int:
    """Check ``doc`` is a well-formed Chrome trace (JSON-serializable,
    ``traceEvents`` list, each event carrying the fields its phase
    requires). Returns the event count; raises ValueError otherwise."""
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("not a Chrome trace: missing traceEvents list")
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if ph not in _KNOWN_PH:
            raise ValueError(f"traceEvents[{i}]: unknown ph {ph!r}")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"traceEvents[{i}]: missing name")
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                raise ValueError(f"traceEvents[{i}]: missing numeric ts")
            if not isinstance(ev.get("pid"), int) or not isinstance(
                    ev.get("tid"), int):
                raise ValueError(f"traceEvents[{i}]: missing pid/tid")
        if ph == "X" and (not isinstance(ev.get("dur"), (int, float))
                          or ev["dur"] < 0):
            raise ValueError(
                f"traceEvents[{i}]: complete event needs dur >= 0")
    json.dumps(doc)
    return len(doc["traceEvents"])


def summarize(records: List[dict]) -> Dict[str, Any]:
    """Digest for ``clonos_tpu trace``: traces present, per-name
    span counts/total durations, and the ordered event timeline of the
    dominant trace."""
    traces: Dict[str, int] = {}
    by_name: Dict[str, Dict[str, float]] = {}
    for rec in records:
        tr = str(rec.get("trace"))
        traces[tr] = traces.get(tr, 0) + 1
        st = by_name.setdefault(str(rec.get("name")),
                                {"count": 0, "total_s": 0.0})
        st["count"] += 1
        if rec.get("ph") == "X":
            st["total_s"] += float(rec.get("dur", 0.0))
    main = max(traces, key=traces.get) if traces else None
    timeline = [
        {"ts": rec.get("ts"), "ph": rec.get("ph"),
         "service": rec.get("service"), "name": rec.get("name"),
         "dur": rec.get("dur")}
        for rec in records if str(rec.get("trace")) == main]
    return {"records": len(records), "traces": traces,
            "main_trace": main, "names": by_name, "timeline": timeline}
