"""Record-level lineage plane: explain any output record end to end.

The audit plane proves *epochs* are exactly-once, the timeline orders
*events*, incident forensics localizes a divergence to a first
determinant row — but none of them answers the operator's first
question: "where did THIS record come from, and why does it have THIS
value?". The paper's premise makes that answerable: every
nondeterministic influence on a record is already a determinant row,
so a record's causal derivation is latently recorded. This module
materializes it:

- a **deterministic dye sampler**: ``k`` records per epoch are marked
  at the source *by key hash* (:func:`select_dyed` — a pure function
  of the epoch's key set, so the soak control twin dyes the SAME
  records with zero coordination and zero wire fields);
- :class:`LineagePlane` — at every epoch seal it scans the sealed
  determinant window (the in-flight ring steps, the sink transaction
  shards, the ORDER/TIMESTAMP/RNG determinant rows) for dyed keys and
  appends compact **tag observations** to a per-process lineage JSONL
  (``utils/jsonl`` discipline: torn-tail tolerant, one writer rule);
- a **pure reconstructor** (:func:`reconstruct`) that joins
  observations from any number of processes into one per-record causal
  path — source offset → every vertex/step it touched (with the
  determinant rows that influenced it) → sink part file or serve read
  — rendered byte-identically across processes
  (:func:`render_trace`, the rootcause.py convention).

Zero overhead off: :class:`NullLineage` is the process default — no
wire fields, no per-record work, no seal-time scan (the NullTracer
convention). Enabling is the explicit :func:`configure_lineage`
opt-in; ``clonos_tpu lineage`` is the CLI over the files.

The observation format is pinned: :data:`LINEAGE_SCHEMA` has one
canonical fingerprint (:func:`lineage_schema_fingerprint`) checked
against ``.clonos-lineage-schema`` in conftest, so silent format
drift fails the session like census/bundle drift does.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence

from clonos_tpu.obs.incident import canonical_json
from clonos_tpu.utils.jsonl import JsonlAppender, read_jsonl

#: Observation kinds one lineage JSONL may carry (anything else is a
#: typo'd dead observation and raises).
OBSERVATION_KINDS = (
    "dye",      # dye decision: key marked at its source offset
    "hop",      # dyed key seen in a vertex's in-flight ring step
    "det",      # ORDER/TIMESTAMP/RNG determinant rows for one epoch
    "sink",     # dyed key landed in a sink transaction part
    "serve",    # dyed key read through the serve tier
)

#: The pinned observation/report format. PURE data — any change here
#: changes :func:`lineage_schema_fingerprint` and must be re-pinned in
#: ``.clonos-lineage-schema`` (conftest enforces).
LINEAGE_SCHEMA = {
    "format": "clonos-lineage",
    "version": 1,
    "kinds": {
        "dye": "key/epoch/vertex/step/pos — the source offset",
        "hop": "key/epoch/vertex/step/pos/value/timestamp/"
               "key_group/subtask",
        "det": "epoch/flat/rows (ORDER|TIMESTAMP|RNG lanes)/truncated",
        "sink": "key/epoch/vertex/subtask/part/value/timestamp",
        "serve": "key/epoch/replica/rerouted",
    },
    "path": "dyed_at -> hops[] (+determinants[]) -> sinks[]/serves[]",
}


def lineage_schema_fingerprint() -> str:
    """Fingerprint of :data:`LINEAGE_SCHEMA` (the
    ``.clonos-lineage-schema`` pin)."""
    return hashlib.blake2b(canonical_json(LINEAGE_SCHEMA).encode(),
                           digest_size=8).hexdigest()


# --- the dye sampler ---------------------------------------------------------

_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: the one stateless hash under the dye."""
    x &= _M64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _M64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _M64
    x ^= x >> 33
    return x


def dye_hash(key: int, epoch: int, salt: int) -> int:
    """Per-(key, epoch) dye rank — a pure function, so every process
    (and the soak control twin) ranks identically."""
    return _mix64((int(key) & _M64)
                  ^ _mix64((int(epoch) * 0x9E3779B97F4A7C15
                            + int(salt)) & _M64))


def select_dyed(keys: Iterable[int], epoch: int, *, salt: int,
                k: int) -> List[int]:
    """The ``k`` dyed keys of one epoch: the distinct keys with the
    smallest dye hash (ties by key). A pure function of the SET of
    keys — scan order, duplicates, and process boundaries cannot
    change the selection."""
    distinct = {int(x) for x in keys}
    ranked = sorted(distinct,
                    key=lambda x: (dye_hash(x, epoch, salt), x))
    return ranked[:max(0, int(k))]


# --- the disabled plane ------------------------------------------------------


class NullLineage:
    """The disabled plane: every hook is a constant no-op — zero wire
    fields, zero per-record work, no seal-time window scan (the
    NullTracer convention)."""

    enabled = False
    k = 0
    salt = 0
    dyed = 0
    observations = 0
    epochs_observed = 0
    serve_hits = 0

    def observe_epoch(self, epoch: int, window, **ctx) -> int:
        return 0

    def observe_serve(self, key: int, **fields) -> bool:
        return False

    def is_dyed(self, key: int) -> bool:
        return False

    def wire_config(self) -> Optional[dict]:
        return None

    def register_gauges(self, registry) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


# --- the live plane ----------------------------------------------------------


class LineagePlane:
    """One process's lineage writer: dye selection + seal-time
    observation capture into ``lineage-<service>.jsonl``.

    All capture runs at epoch *seal* on the host — the per-step/
    per-record hot path is untouched even when enabled; the dye needs
    no stored bit because it is a pure key-hash function. Observation
    files from any number of planes (workers, the soak twins) feed one
    :func:`reconstruct` join.
    """

    enabled = True

    def __init__(self, root: str, *, service: Optional[str] = None,
                 k: int = 4, salt: int = 0xC109_0519,
                 det_rows: int = 64, dyed_cache: int = 4096,
                 fsync_every: int = 0):
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.service = service
        self.k = int(k)
        self.salt = int(salt)
        self.det_rows = int(det_rows)
        self.dyed_cache = int(dyed_cache)
        # clonos: allow(entropy) — the pid only names this process's
        # observation FILE; it never enters an observation record, and
        # the reconstructor joins by content (service/seq excluded), so
        # a restarted writer under a new pid changes nothing replayed.
        name = f"lineage-{service or f'pid{os.getpid()}'}.jsonl"
        self.path = os.path.join(root, name)
        self._app = JsonlAppender(self.path, sort_keys=True,
                                  default=str,
                                  fsync_every=int(fsync_every))
        self._lock = threading.Lock()
        self._observed: set = set()       # epochs already captured
        self._dyed_recent: Dict[int, None] = {}   # insertion-ordered
        self.dyed = 0
        self.observations = 0
        self.epochs_observed = 0
        self.serve_hits = 0

    # --- wire convention (parallel/transport.attach_lineage) ----------------

    def wire_config(self) -> Optional[dict]:
        """The dye config a JobMaster stamps on DEPLOY headers so every
        worker dyes the SAME records — the multi-host down-payment for
        per-record tag piggybacking (causal/serde lineage tag codec)."""
        return {"root": self.root, "k": self.k, "salt": self.salt}

    # --- capture -------------------------------------------------------------

    def _append(self, rec: Dict[str, Any]) -> None:
        if rec["kind"] not in OBSERVATION_KINDS:
            raise ValueError(
                f"unknown lineage observation kind {rec['kind']!r} "
                f"(kinds: {', '.join(OBSERVATION_KINDS)})")
        rec["service"] = self.service
        rec["seq"] = self.observations
        self._app.append(rec)
        self.observations += 1

    def _remember_dyed(self, keys: Sequence[int]) -> None:
        for key in keys:
            self._dyed_recent[int(key)] = None
        while len(self._dyed_recent) > self.dyed_cache:
            self._dyed_recent.pop(next(iter(self._dyed_recent)))

    def is_dyed(self, key: int) -> bool:
        """Whether ``key`` was dyed in a recently observed epoch (the
        serve-read terminus test)."""
        return int(key) in self._dyed_recent

    def observe_epoch(self, epoch: int, window: Dict[str, Any], *,
                      num_key_groups: Optional[int] = None,
                      topology: Optional[Dict[int, int]] = None,
                      parts: Optional[Dict[int, Dict[int, Any]]] = None,
                      ) -> int:
        """Capture one sealed epoch: select the dye set over the
        window's ring keys, then append dye/hop/det/sink observations
        for every dyed key. ``window`` is one
        ``LocalExecutor.epoch_window`` snapshot (live or from
        ``FenceHandles.window()``); ``topology`` maps vertex id →
        parallelism so hops carry key-group/subtask; ``parts`` maps
        sink vertex id → per-subtask ``[n, 3]`` pending records.
        Idempotent per epoch (a recovery replay re-seals bit-identical
        windows; capturing them twice would only duplicate rows the
        reconstructor dedups anyway). Returns observations appended."""
        import numpy as np

        from clonos_tpu.runtime.executor import iter_ring_steps

        epoch = int(epoch)
        with self._lock:
            if epoch in self._observed:
                return 0
            self._observed.add(epoch)
            before = self.observations

            steps = [(vid, seq,
                      np.asarray(keys, np.int64).reshape(-1),
                      np.asarray(values, np.int64).reshape(-1),
                      np.asarray(stamps, np.int64).reshape(-1))
                     for vid, seq, keys, values, stamps
                     in iter_ring_steps(window)]
            union: set = set()
            for _, _, keys, _, _ in steps:
                union.update(int(x) for x in keys.tolist())
            dyed = select_dyed(union, epoch, salt=self.salt, k=self.k)
            if dyed:
                self._remember_dyed(dyed)
                self.dyed += len(dyed)
                dyed_arr = np.asarray(sorted(dyed), np.int64)

                # Hop rows, and the source offset: the first (vertex,
                # step, pos) occurrence in deterministic scan order.
                src: Dict[int, tuple] = {}
                for vid, seq, keys, values, stamps in steps:
                    hit = np.nonzero(np.isin(keys, dyed_arr))[0]
                    if hit.size == 0:
                        continue
                    kg = sub = None
                    par = (topology or {}).get(vid)
                    if par and num_key_groups:
                        from clonos_tpu.runtime.query import \
                            owner_subtask_np
                        kg, sub = owner_subtask_np(
                            keys[hit].astype(np.int32), int(par),
                            int(num_key_groups))
                    for i, pos in enumerate(hit.tolist()):
                        key = int(keys[pos])
                        src.setdefault(key, (vid, seq, pos))
                        rec = {"kind": "hop", "key": key,
                               "epoch": epoch, "vertex": int(vid),
                               "step": int(seq), "pos": int(pos),
                               "value": int(values[pos]),
                               "timestamp": int(stamps[pos])}
                        if kg is not None:
                            rec["key_group"] = int(kg[i])
                            rec["subtask"] = int(sub[i])
                        self._append(rec)
                for key in dyed:
                    vid, seq, pos = src.get(key, (-1, -1, -1))
                    self._append({"kind": "dye", "key": int(key),
                                  "epoch": epoch, "vertex": int(vid),
                                  "step": int(seq), "pos": int(pos)})

                # The determinant rows that influenced this epoch —
                # ORDER/TIMESTAMP/RNG lanes only (the nondeterminism
                # the paper logs; checkpoint/fence bookkeeping rows
                # are not record influences).
                from clonos_tpu.causal.determinant import (LANE_TAG,
                                                           ORDER, RNG,
                                                           TIMESTAMP)
                for flat in sorted(window.get("logs", {}), key=int):
                    rows = np.asarray(window["logs"][flat],
                                      np.int64).reshape(-1, 8)
                    m = np.isin(rows[:, LANE_TAG],
                                [ORDER, TIMESTAMP, RNG])
                    sel = rows[m]
                    if sel.shape[0] == 0:
                        continue
                    self._append({
                        "kind": "det", "epoch": epoch,
                        "flat": int(flat),
                        "rows": sel[:self.det_rows].tolist(),
                        "truncated": bool(sel.shape[0]
                                          > self.det_rows)})

                # Sink termini: dyed keys inside the epoch's sealed
                # transaction shards. The part name is the stable
                # ``part-<epoch>-<sub>`` prefix (the filesink token
                # suffix is attempt-scoped, not record identity).
                for vid in sorted(parts or {}):
                    for sub in sorted(parts[vid]):
                        recs = np.asarray(parts[vid][sub],
                                          np.int64).reshape(-1, 3)
                        hit = np.nonzero(
                            np.isin(recs[:, 0], dyed_arr))[0]
                        for pos in hit.tolist():
                            self._append({
                                "kind": "sink",
                                "key": int(recs[pos, 0]),
                                "epoch": epoch, "vertex": int(vid),
                                "subtask": int(sub),
                                "part": f"part-{epoch}-{int(sub)}",
                                "value": int(recs[pos, 1]),
                                "timestamp": int(recs[pos, 2])})
            self.epochs_observed += 1
            self._app.sync()
            return self.observations - before

    def observe_serve(self, key: int, *, epoch: int, replica: str,
                      rerouted: bool = False) -> bool:
        """Serve-read terminus: append an observation when ``key`` is
        dyed (the ``ServeRouter`` provenance-stamp hook). Returns
        whether the read was recorded."""
        with self._lock:
            if not self.is_dyed(key):
                return False
            self._append({"kind": "serve", "key": int(key),
                          "epoch": int(epoch), "replica": str(replica),
                          "rerouted": bool(rerouted)})
            self.serve_hits += 1
            return True

    # --- plumbing ------------------------------------------------------------

    def register_gauges(self, registry) -> None:
        """``lineage.*`` gauges — registered into a runner's
        MetricRegistry they ride the HEARTBEAT piggyback; ``clonos_tpu
        top`` renders the lineage: row from them."""
        g = registry.group("lineage")
        g.gauge("dyed", lambda: self.dyed)
        g.gauge("observations", lambda: self.observations)
        g.gauge("epochs-observed", lambda: self.epochs_observed)
        g.gauge("serve-hits", lambda: self.serve_hits)
        g.gauge("k", lambda: self.k)

    def sync(self) -> None:
        self._app.sync()

    def close(self) -> None:
        self._app.close()


# --- reading + reconstruction (pure) -----------------------------------------


def read_observations(paths) -> List[dict]:
    """Read lineage observations from one path or a list of paths,
    torn-tail tolerantly (a SIGKILLed writer leaves at most one torn
    final line; utils/jsonl drops it)."""
    if isinstance(paths, (str, bytes)):
        paths = [paths]
    out: List[dict] = []
    for path in paths:
        out.extend(read_jsonl(path, label=str(path)))
    return out


def _hop_key(h: dict) -> tuple:
    return (h["epoch"], h["vertex"], h["step"], h["pos"])


def reconstruct(observations: Iterable[dict]) -> Dict[str, Any]:
    """Join observations (from any number of processes) into
    per-record causal paths. PURE: a function of the observation
    CONTENT only — per-process ``service``/``seq`` fields and file
    order never reach the report, so two processes reconstructing the
    same observations render byte-identical traces
    (:func:`render_trace`).

    A path is **broken** when (a) hops exist with no dye decision
    (``no-dye`` — a partial file set), or (b) the dyed record never
    reaches a terminus while other records did (``no-terminus`` — the
    record was lost in flight)."""
    dyes: Dict[int, List[dict]] = {}
    hops: Dict[int, Dict[tuple, dict]] = {}
    sinks: Dict[int, Dict[tuple, dict]] = {}
    serves: Dict[int, Dict[tuple, dict]] = {}
    dets: Dict[tuple, dict] = {}
    total = 0
    for rec in observations:
        total += 1
        kind = rec.get("kind")
        if kind == "dye":
            dyes.setdefault(int(rec["key"]), []).append(
                {"epoch": int(rec["epoch"]),
                 "vertex": int(rec["vertex"]),
                 "step": int(rec["step"]), "pos": int(rec["pos"])})
        elif kind == "hop":
            h = {"epoch": int(rec["epoch"]),
                 "vertex": int(rec["vertex"]),
                 "step": int(rec["step"]), "pos": int(rec["pos"]),
                 "value": int(rec["value"]),
                 "timestamp": int(rec["timestamp"])}
            if "key_group" in rec:
                h["key_group"] = int(rec["key_group"])
                h["subtask"] = int(rec["subtask"])
            hops.setdefault(int(rec["key"]), {})[_hop_key(h)] = h
        elif kind == "sink":
            s = {"epoch": int(rec["epoch"]),
                 "vertex": int(rec["vertex"]),
                 "subtask": int(rec["subtask"]),
                 "part": str(rec["part"]),
                 "value": int(rec["value"]),
                 "timestamp": int(rec["timestamp"])}
            sinks.setdefault(int(rec["key"]), {})[
                (s["epoch"], s["part"], s["value"],
                 s["timestamp"])] = s
        elif kind == "serve":
            v = {"epoch": int(rec["epoch"]),
                 "replica": str(rec["replica"]),
                 "rerouted": bool(rec["rerouted"])}
            serves.setdefault(int(rec["key"]), {})[
                (v["epoch"], v["replica"], v["rerouted"])] = v
        elif kind == "det":
            d = {"epoch": int(rec["epoch"]), "flat": int(rec["flat"]),
                 "rows": [[int(x) for x in row]
                          for row in rec["rows"]],
                 "truncated": bool(rec["truncated"])}
            dets[(d["epoch"], d["flat"],
                  canonical_json(d["rows"]))] = d

    any_terminus = bool(sinks) or bool(serves)
    keys = sorted(set(dyes) | set(hops) | set(sinks) | set(serves))
    paths: Dict[str, Any] = {}
    broken_keys: List[int] = []
    for key in keys:
        dye_list = sorted(
            dyes.get(key, []),
            key=lambda d: (d["epoch"], d["vertex"], d["step"],
                           d["pos"]))
        path: Dict[str, Any] = {
            "key": key,
            "dyed_at": dye_list[0] if dye_list else None,
            "hops": [hops[key][hk]
                     for hk in sorted(hops.get(key, {}))],
            "sinks": [sinks[key][sk]
                      for sk in sorted(sinks.get(key, {}))],
            "serves": [serves[key][vk]
                       for vk in sorted(serves.get(key, {}))],
        }
        touched = {h["epoch"] for h in path["hops"]}
        if path["dyed_at"] is not None:
            touched.add(path["dyed_at"]["epoch"])
        path["determinants"] = [
            dets[dk] for dk in sorted(dets)
            if dets[dk]["epoch"] in touched]
        broken: List[str] = []
        if not dye_list:
            broken.append("no-dye")
        elif (any_terminus and not path["sinks"]
                and not path["serves"]):
            broken.append("no-terminus")
        path["broken"] = broken
        if broken:
            broken_keys.append(key)
        paths[str(key)] = path
    return {
        "format": (f"{LINEAGE_SCHEMA['format']}"
                   f"/v{LINEAGE_SCHEMA['version']}"),
        "schema_fingerprint": lineage_schema_fingerprint(),
        "observations": total,
        "keys": paths,
        "broken_keys": broken_keys,
        "ok": not broken_keys,
    }


def trace_key(observations: Iterable[dict], key: int) -> Dict[str, Any]:
    """One record's reconstructed causal path (the ``lineage --key``
    view): the full join, narrowed to ``key``."""
    report = reconstruct(observations)
    path = report["keys"].get(str(int(key)))
    return {
        "format": report["format"],
        "schema_fingerprint": report["schema_fingerprint"],
        "key": int(key),
        "path": path,
        "ok": bool(path) and not path["broken"],
    }


def render_trace(report: Dict[str, Any]) -> str:
    """The byte encoding two processes must agree on: canonical JSON +
    newline (the rootcause.py convention)."""
    return canonical_json(report) + "\n"


def format_trace(report: Dict[str, Any]) -> str:
    """Human-readable rendering of a :func:`reconstruct` report."""
    lines = [f"lineage {report['format']} — "
             f"{report['observations']} observations, "
             f"{len(report['keys'])} dyed records, "
             f"{'OK' if report['ok'] else 'BROKEN paths'}"]
    for key in sorted(report["keys"], key=int):
        p = report["keys"][key]
        d = p["dyed_at"]
        srcs = (f"v{d['vertex']} step {d['step']} pos {d['pos']} "
                f"@ epoch {d['epoch']}" if d else "UNKNOWN SOURCE")
        lines.append(f"  key {key}: dyed at {srcs}")
        for h in p["hops"]:
            where = (f" -> sub {h['subtask']} (kg {h['key_group']})"
                     if "subtask" in h else "")
            lines.append(
                f"    hop   epoch {h['epoch']} v{h['vertex']} "
                f"step {h['step']} pos {h['pos']} "
                f"value={h['value']} ts={h['timestamp']}{where}")
        for s in p["sinks"]:
            lines.append(
                f"    sink  epoch {s['epoch']} v{s['vertex']} "
                f"{s['part']} value={s['value']} "
                f"ts={s['timestamp']}")
        for v in p["serves"]:
            rr = " (rerouted)" if v["rerouted"] else ""
            lines.append(f"    serve epoch {v['epoch']} "
                         f"replica {v['replica']}{rr}")
        ndet = sum(len(d["rows"]) for d in p["determinants"])
        if ndet:
            lines.append(f"    dets  {ndet} ORDER/TIMESTAMP/RNG rows "
                         f"across {len(p['determinants'])} windows")
        if p["broken"]:
            lines.append(f"    BROKEN: {', '.join(p['broken'])}")
    return "\n".join(lines) + "\n"


def to_trace_records(report: Dict[str, Any]) -> List[dict]:
    """Paths as tracer-style records for the validated Chrome export
    path (obs/chrome.to_chrome): one instant event per hop/terminus,
    pid = vertex, tid = subtask, logical ts = epoch + step/1000."""
    out: List[dict] = []
    for key in sorted(report["keys"], key=int):
        p = report["keys"][key]
        for h in p["hops"]:
            out.append({"name": f"key {key} hop",
                        "service": f"vertex-{h['vertex']}",
                        "pid": int(h["vertex"]),
                        "tid": int(h.get("subtask", 0)),
                        "ts": h["epoch"] + h["step"] / 1000.0,
                        "args": {"key": key, "value": h["value"],
                                 "pos": h["pos"]}})
        for s in p["sinks"]:
            out.append({"name": f"key {key} sink",
                        "service": f"vertex-{s['vertex']}",
                        "pid": int(s["vertex"]),
                        "tid": int(s["subtask"]),
                        "ts": s["epoch"] + 0.999,
                        "args": {"key": key, "part": s["part"]}})
        for v in p["serves"]:
            out.append({"name": f"key {key} serve",
                        "service": str(v["replica"]),
                        "pid": 0, "tid": 0,
                        "ts": v["epoch"] + 0.999,
                        "args": {"key": key,
                                 "rerouted": v["rerouted"]}})
    return out


# --- process-global plane ----------------------------------------------------

_global_lineage = NullLineage()
_global_lock = threading.Lock()


def get_lineage():
    """The process lineage plane (Null unless configured)."""
    return _global_lineage


def configure_lineage(root: str, **kw) -> LineagePlane:
    """Install a live lineage plane (the opt-in gate)."""
    global _global_lineage
    with _global_lock:
        _global_lineage = LineagePlane(root, **kw)
        return _global_lineage


def reset_lineage() -> None:
    """Back to the disabled NullLineage (tests)."""
    global _global_lineage
    with _global_lock:
        _global_lineage = NullLineage()


# --- self-check --------------------------------------------------------------


def _synthetic_observations() -> List[dict]:
    """A three-record observation set covering the reconstruction
    regimes: key 7 has a complete source → hops → sink path (with
    determinant context), key 9 was dyed but never reached a terminus
    (a lost record: ``no-terminus``), key 11 has a hop with no dye
    decision (a partial file set: ``no-dye``). ``service``/``seq``
    vary to prove they never reach the report."""
    import json as _json
    obs = [
        {"kind": "dye", "key": 7, "epoch": 1, "vertex": 0, "step": 0,
         "pos": 2, "service": "a", "seq": 0},
        {"kind": "hop", "key": 7, "epoch": 1, "vertex": 0, "step": 0,
         "pos": 2, "value": 70, "timestamp": 1000, "key_group": 3,
         "subtask": 1, "service": "a", "seq": 1},
        {"kind": "hop", "key": 7, "epoch": 1, "vertex": 1, "step": 2,
         "pos": 0, "value": 71, "timestamp": 1002, "key_group": 3,
         "subtask": 0, "service": "b", "seq": 0},
        {"kind": "det", "epoch": 1, "flat": 0, "truncated": False,
         "rows": [[1, 0, 1000, 0, 0, 0, 0, 0],
                  [2, 0, 42, 0, 0, 0, 0, 0]],
         "service": "a", "seq": 2},
        {"kind": "sink", "key": 7, "epoch": 1, "vertex": 2,
         "subtask": 0, "part": "part-1-0", "value": 71,
         "timestamp": 1002, "service": "b", "seq": 1},
        {"kind": "dye", "key": 9, "epoch": 1, "vertex": 0, "step": 1,
         "pos": 0, "service": "a", "seq": 3},
        {"kind": "hop", "key": 9, "epoch": 1, "vertex": 0, "step": 1,
         "pos": 0, "value": 90, "timestamp": 1001, "service": "a",
         "seq": 4},
        {"kind": "hop", "key": 11, "epoch": 2, "vertex": 1, "step": 0,
         "pos": 1, "value": 110, "timestamp": 2000, "service": "b",
         "seq": 2},
    ]
    # the JSON round-trip below mirrors two fresh processes
    return _json.loads(_json.dumps(obs))


def lineage_self_check() -> List[dict]:
    """Deterministic in-memory lineage self-check (the conftest /
    ``clonos_tpu lineage --self-check`` gate): reconstruct the
    synthetic observation set twice — once as-built, once through a
    JSON round-trip (the two-fresh-process equivalence) — and demand
    byte-identical traces that join and break paths exactly. Pure: no
    files, no wall clock, no jax. Returns findings (empty == sound)."""
    import json as _json

    findings: List[dict] = []

    def check(rule: str, ok: bool, detail: str) -> None:
        if not ok:
            findings.append({"rule": rule, "detail": detail})

    obs = _synthetic_observations()
    rep = reconstruct(obs)
    text = render_trace(rep)
    text2 = render_trace(
        reconstruct(_json.loads(canonical_json(obs))))
    check("deterministic", text == text2,
          "trace not byte-identical across a JSON round-trip")
    # shuffled observation order (another process's file interleaving)
    # must not change a single byte either
    text3 = render_trace(reconstruct(list(reversed(obs))))
    check("order-free", text == text3,
          "trace depends on observation file order")

    p7 = rep["keys"].get("7") or {}
    check("join", p7.get("dyed_at") == {"epoch": 1, "vertex": 0,
                                        "step": 0, "pos": 2}
          and len(p7.get("hops", [])) == 2
          and p7.get("hops", [{}])[-1].get("vertex") == 1
          and len(p7.get("sinks", [])) == 1
          and p7.get("sinks", [{}])[0].get("part") == "part-1-0",
          f"key 7 path mis-joined: {p7}")
    check("determinants", len(p7.get("determinants", [])) == 1
          and len(p7["determinants"][0]["rows"]) == 2,
          "key 7 must carry its epoch's ORDER/TIMESTAMP/RNG rows")
    check("complete", not p7.get("broken", ["missing"]),
          f"key 7 must be unbroken, got {p7.get('broken')}")
    p9 = rep["keys"].get("9") or {}
    check("lost", p9.get("broken") == ["no-terminus"],
          f"key 9 must break as no-terminus, got {p9.get('broken')}")
    p11 = rep["keys"].get("11") or {}
    check("orphan", p11.get("broken") == ["no-dye"],
          f"key 11 must break as no-dye, got {p11.get('broken')}")
    check("verdict", rep["ok"] is False
          and rep["broken_keys"] == [9, 11],
          f"expected broken keys [9, 11], got {rep['broken_keys']}")

    # A clean subset must report ok (the --report json exit-0 path).
    clean = reconstruct([r for r in obs if r.get("key") == 7
                         or r["kind"] == "det"])
    check("clean-ok", clean["ok"] is True, "key-7-only set must be ok")

    # Dye selection: pure in the key SET — permutation/duplication
    # invariant, bounded by k, ties broken deterministically.
    a = select_dyed([5, 3, 9, 3, 5, 12], 4, salt=17, k=2)
    b = select_dyed([12, 9, 5, 3], 4, salt=17, k=2)
    check("dye-pure", a == b and len(a) == 2,
          f"dye selection not a pure set function: {a} vs {b}")
    check("schema", lineage_schema_fingerprint()
          == lineage_schema_fingerprint(),
          "schema fingerprint not stable")
    return findings
