"""Bounded metrics history: the time axis /metrics.json lacks.

One scrape of the JobMaster's endpoint answers "what is the cluster
doing NOW"; diagnosing a slow drift (ring occupancy creeping toward
overwrite, overhead fraction rising after a redeploy) needs *history*.
This module keeps a bounded ring of periodic snapshots:

- :class:`MetricsHistory` runs a daemon sampler thread calling a
  zero-arg ``sample_fn`` (the endpoint's merged cluster view) every
  ``interval_s`` seconds. Samples land in (a) an in-memory ring
  (``deque(maxlen=window)``) and (b) optionally a JSON-lines file —
  one flushed append per sample, so a SIGKILLed process loses at most
  the line being written, and a reader tolerates that torn tail
  exactly like the checkpoint ledger. When the file outgrows
  ``2*window`` lines it is compacted from the ring via an atomic
  tmp+``os.replace`` rewrite, so a long run's history file stays
  bounded like the ring.
- :meth:`MetricsHistory.query` serves windowed reads (``since`` a
  wall-clock timestamp, ``last`` N samples) — the payload behind the
  endpoint's ``/metrics/history.json?since=TS&last=N``.

Sampling touches only host data (snapshot dicts), never the device:
safe from a thread while the main loop dispatches programs.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional


def read_history_file(path: str) -> List[dict]:
    """Read a history JSONL, tolerating a torn final line (SIGKILL mid
    append); a decode failure on any earlier line still raises."""
    from clonos_tpu.utils.jsonl import read_jsonl
    return read_jsonl(path)


class MetricsHistory:
    """Ring-bounded periodic snapshots of a metrics view."""

    def __init__(self, sample_fn: Optional[Callable[[], Dict[str, Any]]]
                 = None, path: Optional[str] = None,
                 interval_s: float = 2.0, window: int = 512,
                 # clonos: allow(wallclock): sample timestamps, obs-only
                 clock=time.time):
        self.sample_fn = sample_fn
        self._path = path
        self.interval_s = float(interval_s)
        self.window = int(window)
        self._clock = clock
        self._ring: Deque[dict] = collections.deque(maxlen=self.window)
        #: sampling slots skipped because a sample overran its whole
        #: interval (the loop re-anchors instead of bursting catch-up
        #: samples with bogus spacing)
        self.missed_slots = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._writer = None
        self._file_lines = 0
        if path is not None:
            from clonos_tpu.utils.jsonl import JsonlAppender
            self._writer = JsonlAppender(path, default=str)
            # A restarted process resumes its ring from the surviving
            # file tail (torn final line tolerated).
            for rec in read_history_file(path)[-self.window:]:
                self._ring.append(rec)
            self._file_lines = len(self._ring)

    # --- sampling ------------------------------------------------------------

    def sample_once(self) -> dict:
        """Take one sample now (also what the thread loop calls)."""
        try:
            metrics = self.sample_fn() if self.sample_fn else {}
        except Exception as e:       # sampler must outlive a bad gauge
            metrics = {"history-error": repr(e)}
        rec = {"ts": self._clock(), "metrics": metrics}
        with self._lock:
            self._ring.append(rec)
            if self._writer is not None:
                self._writer.append(rec)
                self._file_lines += 1
                if self._file_lines > 2 * self.window:
                    self._compact_locked()
        return rec

    def _compact_locked(self) -> None:
        # Atomic rewrite from the ring (utils/jsonl): the file never
        # exceeds 2*window lines for long, and a crash mid-compaction
        # leaves either the old file or the new one, never a mix.
        from clonos_tpu.utils.jsonl import atomic_rewrite_jsonl
        self._writer.close()     # os.replace swaps the inode under us
        self._file_lines = atomic_rewrite_jsonl(
            self._path, list(self._ring), default=str)

    def _loop(self) -> None:
        # Absolute-deadline pacing: ``wait(interval)`` THEN sample would
        # stretch every period by the sample's own duration (a slow
        # cluster-view merge under load turns a 2s interval into 3s+,
        # silently squeezing the ring's time span). Each deadline is
        # interval_s after the previous DEADLINE, not after the sample
        # finished; a sample that overruns whole intervals skips the
        # missed slots (counted) rather than firing a catch-up burst.
        next_due = time.monotonic() + self.interval_s
        while not self._stop.wait(max(next_due - time.monotonic(), 0.0)):
            self.sample_once()
            next_due += self.interval_s
            now = time.monotonic()
            if next_due <= now:
                missed = int((now - next_due) / self.interval_s) + 1
                self.missed_slots += missed
                next_due += missed * self.interval_s

    def start(self) -> "MetricsHistory":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()
        return self

    @property
    def started(self) -> bool:
        return self._thread is not None

    # --- queries -------------------------------------------------------------

    def query(self, since: Optional[float] = None,
              last: Optional[int] = None) -> List[dict]:
        """Samples with ``ts >= since`` (then) trimmed to the ``last``
        N, oldest first — ring order, so timestamps are monotone."""
        with self._lock:
            out = list(self._ring)
        if since is not None:
            out = [r for r in out if r.get("ts", 0) >= since]
        if last is not None and last >= 0:
            out = out[-last:]
        return out

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            if self._writer is not None:
                self._writer.close()
