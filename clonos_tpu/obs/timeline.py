"""The unified causal timeline: every evidence plane, one HLC order.

The repo's incident evidence is scattered across trace JSONL, audit
ledgers, decision logs, chaos schedules and SLO windows — diagnosing a
kill mid-fence-tail means hand-joining them by wall clock, which stops
working across processes. This module gives every plane ONE sink:

- :class:`TimelineStore` appends typed records (``kind`` + fields),
  each stamped with the process HLC (obs/hlc.py) so records from
  different processes merge into a causally-consistent order. Same
  file discipline as the tracer: bounded in-memory ring + JSONL file,
  one append handle, flushed per record (SIGKILL loses at most the
  record being written).
- The emitting call sites are the planes themselves: epoch seals
  (``epoch.seal`` — runtime/cluster.py), recovery FSM transitions
  (``recovery.fsm`` — causal/recovery.py), SCALE decisions
  (``scale.decision`` — autoscale/controller.py), chaos injections
  (``chaos`` — soak/driver.py), SLO breaches (``slo.breach`` —
  soak/slo.py), gray-failure suspicion (``health.gray-suspect`` —
  obs/detect.py), and every cross-process message send/receive
  (``msg.send`` / ``msg.recv`` — parallel/transport.py attach_hlc /
  adopt_hlc, which echo the sender's stamp into the receive record so
  causality is checkable per record).
- Reading is tail-tolerant via utils/jsonl; :func:`merge_records`
  sorts by HLC stamp (wall-clock fallback for un-stamped records) and
  :func:`causality_inversions` proves the merged order sound: a
  receive whose stamp does not order strictly after its send is an
  inversion, and so is a recv/send pair the merge laid out backwards.

``clonos_tpu timeline`` is the CLI: filter by job/worker/epoch/kind,
``--diff`` two timelines, ``--report json`` (exit 0/1 on inversions),
``--chrome`` via obs/chrome.py.

Zero overhead off: the process-global store starts as
:class:`NullTimeline` (every ``record()`` a no-op); enabling is the
explicit :func:`configure_timeline` opt-in, the NullTracer convention.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from clonos_tpu.obs.hlc import HybridLogicalClock, get_hlc, stamp_key

#: record fields owned by the store; everything else is caller payload
_RESERVED = ("kind", "ts", "hlc", "service", "pid")


class NullTimeline:
    """The disabled store: ``record()`` is a no-op, call sites pay
    nothing (the NullTracer convention)."""

    enabled = False
    service = None

    def record(self, kind: str, hlc=None, **fields) -> None:
        pass

    def records(self) -> List[dict]:
        return []

    def close(self) -> None:
        pass


class TimelineStore:
    """Process timeline sink: bounded ring + optional JSONL file,
    every record stamped with the process HLC."""

    enabled = True

    def __init__(self, service: str, path: Optional[str] = None,
                 # clonos: allow(wallclock): record timestamps are
                 # observability metadata, never operator state.
                 clock=time.time, buffer: int = 8192):
        from clonos_tpu.utils.jsonl import JsonlAppender
        self.service = service
        self._path = path
        self._clock = clock
        self._writer = (JsonlAppender(path, default=str)
                        if path is not None else None)
        self._lock = threading.Lock()
        self._ring: Deque[dict] = collections.deque(maxlen=buffer)
        # clonos: allow(entropy): pid tags records, never replayed data
        self._pid = os.getpid()

    def record(self, kind: str, hlc=None, **fields) -> None:
        """Append one typed record. ``hlc`` is normally None — the
        process clock is ticked here — but attach/adopt pass the stamp
        they already minted for the wire so record and header agree."""
        if hlc is None:
            hlc = get_hlc().tick()
        rec = {"kind": str(kind), "ts": self._clock(),
               "hlc": list(hlc) if hlc is not None else None,
               "service": self.service, "pid": self._pid}
        for k, v in fields.items():
            if k not in _RESERVED:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)
            if self._writer is not None:
                self._writer.append(rec)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()


# --- process-global store ----------------------------------------------------

_global_timeline = NullTimeline()
_global_lock = threading.Lock()


def get_timeline():
    """The process timeline (NullTimeline unless configured)."""
    return _global_timeline


def configure_timeline(service: str, path: Optional[str] = None,
                       **kw) -> TimelineStore:
    """Install a real timeline store (replacing and closing the old
    one). Also installs a process HLC if none is configured yet — a
    timeline without causal stamps cannot be merged across processes."""
    from clonos_tpu.obs.hlc import configure_hlc
    global _global_timeline
    with _global_lock:
        old = _global_timeline
        if not get_hlc().enabled:
            configure_hlc(node=service)
        _global_timeline = TimelineStore(service, path=path, **kw)
        old.close()
        return _global_timeline


def reset_timeline() -> None:
    """Back to the disabled NullTimeline (tests; closes the file)."""
    global _global_timeline
    with _global_lock:
        _global_timeline.close()
        _global_timeline = NullTimeline()


# --- reading / merging -------------------------------------------------------

def read_timeline(paths) -> List[dict]:
    """Read timeline records from one path or many, torn-tail
    tolerantly (utils/jsonl: a SIGKILLed writer's torn final line is
    dropped; mid-file junk raises naming file:line)."""
    from clonos_tpu.utils.jsonl import read_jsonl
    if isinstance(paths, (str, bytes)):
        paths = [paths]
    records: List[dict] = []
    for path in paths:
        records.extend(read_jsonl(str(path), label=str(path)))
    return records


def record_key(rec: dict) -> Tuple[int, int, str]:
    """The merge key: the HLC stamp when present, a wall-clock-derived
    stand-in otherwise (c = -1 keeps unstamped records sorting before
    any stamped record sharing the same microsecond)."""
    hlc = rec.get("hlc")
    if hlc:
        return stamp_key(hlc)
    return (int(float(rec.get("ts", 0.0)) * 1e6), -1,
            str(rec.get("service") or ""))


def merge_records(records: Sequence[dict]) -> List[dict]:
    """One HLC-ordered timeline from any number of processes' records
    (a stable sort: same-stamp records keep their input order)."""
    return sorted(records, key=record_key)


def iter_merged(paths):
    """Stream the HLC-merged timeline of many per-process files with
    **O(open files)** memory: a k-way ``heapq.merge`` over per-file
    streaming cursors (utils/jsonl.iter_jsonl, torn tails dropped).
    Sound because each per-process file is appended in stamp order —
    the process HLC only ticks forward, and the unstamped fallback key
    (record ``ts``) is the same monotone append clock — so every
    cursor is already sorted by :func:`record_key`. Merging a long
    soak's files stays flat in memory instead of O(total events)."""
    import heapq
    from clonos_tpu.utils.jsonl import iter_jsonl
    if isinstance(paths, (str, bytes)):
        paths = [paths]
    cursors = [iter_jsonl(str(p), label=str(p)) for p in paths]
    return heapq.merge(*cursors, key=record_key)


def causality_inversions_stream(merged) -> List[dict]:
    """:func:`causality_inversions` over an already-merged streaming
    iterator (:func:`iter_merged`), single pass: memory is the live
    send-stamp set plus receives still awaiting their send — stamp
    keys, not records. A recv seen before its send in merged order is
    a merge inversion; a recv whose send never appears at all (file
    not collected) is not."""
    findings: List[dict] = []
    open_sends: set = set()
    pending: Dict[Tuple[int, int, str], dict] = {}
    for rec in merged:
        kind = rec.get("kind")
        if kind == "msg.send" and rec.get("hlc"):
            k = stamp_key(rec["hlc"])
            open_sends.add(k)
            recv = pending.pop(k, None)
            if recv is not None:
                findings.append(
                    {"rule": "merge", "recv": recv.get("hlc"),
                     "sent": recv.get("sent"),
                     "verb": recv.get("verb"),
                     "detail": "merged order lays the receive out "
                               "before its send"})
            continue
        if kind != "msg.recv":
            continue
        sent, own = rec.get("sent"), rec.get("hlc")
        if not sent or not own:
            continue
        sent_k, own_k = stamp_key(sent), stamp_key(own)
        if own_k <= sent_k:
            findings.append({"rule": "stamp", "recv": own,
                             "sent": sent, "verb": rec.get("verb"),
                             "detail": "receive stamp does not order "
                                       "after its send stamp"})
        if sent_k not in open_sends:
            pending[sent_k] = {"hlc": own, "sent": sent,
                               "verb": rec.get("verb")}
    return findings


def from_trace_records(trace_records: Sequence[dict]) -> List[dict]:
    """Normalize tracer JSONL records (obs/trace.py shape) into
    timeline shape so trace spans/instants merge into the same order.
    Trace records carry no HLC stamp — they order by wall clock, which
    is exact within one process and approximate across."""
    out = []
    for r in trace_records:
        rec = {"kind": f"trace.{r.get('name', '?')}",
               "ts": float(r.get("ts", 0.0)), "hlc": None,
               "service": r.get("service"), "pid": r.get("pid")}
        if r.get("ph") == "X":
            rec["dur"] = r.get("dur")
        args = r.get("args")
        if isinstance(args, dict):
            for k, v in args.items():
                rec.setdefault(k, v)
        out.append(rec)
    return out


def to_trace_records(records: Sequence[dict]) -> List[dict]:
    """Timeline records in tracer-record shape, for the Chrome export
    path (obs/chrome.to_chrome): every record an instant, HLC stamp
    preserved under args."""
    out = []
    for r in records:
        args = {k: v for k, v in r.items() if k not in _RESERVED}
        if r.get("hlc"):
            args["hlc"] = r["hlc"]
        out.append({"ts": float(r.get("ts", 0.0)),
                    "name": str(r.get("kind", "?")), "ph": "i",
                    "trace": "timeline",
                    "service": r.get("service"),
                    "pid": int(r.get("pid") or 0), "tid": 0,
                    "span": None, "parent": None, "args": args})
    return out


def causality_inversions(records: Sequence[dict]) -> List[dict]:
    """Prove the merged order causally sound. Two checks:

    - **stamp rule**: every ``msg.recv`` record echoes the sender's
      stamp (``sent``); its own stamp must order strictly after it —
      the HLC receive rule guarantees this, so a violation means a
      record was forged, torn or mis-merged;
    - **merge rule**: for every send/recv pair (matched by the sent
      stamp, which is unique per send — the HLC ticks), the merged
      order must lay the send out first.

    Returns one finding dict per violation (empty == sound).
    """
    merged = merge_records(records)
    findings: List[dict] = []
    send_pos: Dict[Tuple[int, int, str], int] = {}
    for i, rec in enumerate(merged):
        if rec.get("kind") == "msg.send" and rec.get("hlc"):
            send_pos.setdefault(stamp_key(rec["hlc"]), i)
    for i, rec in enumerate(merged):
        if rec.get("kind") != "msg.recv":
            continue
        sent, own = rec.get("sent"), rec.get("hlc")
        if not sent or not own:
            continue
        sent_k, own_k = stamp_key(sent), stamp_key(own)
        if own_k <= sent_k:
            findings.append({"rule": "stamp", "recv": own, "sent": sent,
                             "verb": rec.get("verb"),
                             "detail": "receive stamp does not order "
                                       "after its send stamp"})
        pos = send_pos.get(sent_k)
        if pos is not None and pos >= i:
            findings.append({"rule": "merge", "recv": own, "sent": sent,
                             "verb": rec.get("verb"),
                             "detail": "merged order lays the receive "
                                       "out before its send"})
    return findings


def diff_timelines(a: Sequence[dict], b: Sequence[dict],
                   ignore: Sequence[str] = ("ts", "hlc", "pid",
                                            "service", "sent")
                   ) -> List[dict]:
    """Structural diff of two timelines: records are compared as
    (kind + payload fields) multisets, ignoring the per-process /
    per-run volatile fields. Returns findings ``{"only": "a"|"b",
    "record": ..., "count": n}`` — empty means the runs emitted the
    same events."""
    def keyed(recs):
        counts: Dict[str, int] = {}
        for r in recs:
            k = json.dumps(
                {k: v for k, v in sorted(r.items()) if k not in ignore},
                sort_keys=True, default=str)
            counts[k] = counts.get(k, 0) + 1
        return counts

    ca, cb = keyed(a), keyed(b)
    out = []
    for k in sorted(set(ca) | set(cb)):
        d = ca.get(k, 0) - cb.get(k, 0)
        if d > 0:
            out.append({"only": "a", "record": json.loads(k), "count": d})
        elif d < 0:
            out.append({"only": "b", "record": json.loads(k),
                        "count": -d})
    return out


# --- self-check --------------------------------------------------------------

def timeline_self_check() -> List[dict]:
    """Deterministic in-memory causality self-check (the conftest /
    ``clonos_tpu timeline --self-check`` gate): two simulated processes
    with SKEWED logical wall clocks exchange messages both ways; the
    merged stream must show zero inversions even though process B's
    clock runs behind A's by more than the message interval. Pure —
    fake counters for clocks, no wall time, no files."""
    clocks = {"a": [1_000_000.0], "b": [0.5]}    # b skewed far behind

    def mk(node):
        def clock():
            clocks[node][0] += 0.001
            return clocks[node][0]
        return HybridLogicalClock(node=node, clock=clock)

    ha, hb = mk("a"), mk("b")
    records: List[dict] = []

    def send(src, h_src, dst, h_dst, verb, ts):
        stamp = h_src.tick()
        records.append({"kind": "msg.send", "ts": ts, "verb": verb,
                        "hlc": list(stamp), "service": src})
        recv = h_dst.observe(stamp)
        records.append({"kind": "msg.recv", "ts": ts, "verb": verb,
                        "hlc": list(recv), "sent": list(stamp),
                        "service": dst})

    for i in range(16):
        send("a", ha, "b", hb, "DEPLOY", float(i))
        send("b", hb, "a", ha, "HEARTBEAT", float(i))
    return causality_inversions(records)
