"""Hybrid logical clocks: a causally-consistent order for cross-process
events without trusting wall clocks.

The repo now emits evidence from several processes (trace JSONL per
service, audit ledgers, decision logs) and joining them by wall clock
breaks the moment a second host is involved — two hosts' clocks can
disagree by more than a control round-trip, so a DEPLOY can appear to
be *received* before it was *sent*. The paper's causal-logging core is
exactly about ordering cross-worker events without that trust; the HLC
(Kulkarni et al., "Logical Physical Clocks") is the standard fix:

- a timestamp is ``(l, c, node)`` — ``l`` tracks the largest physical
  time witnessed (µs), ``c`` breaks ties among events sharing one
  ``l``, ``node`` breaks ties among processes;
- every *send* ticks the local clock and stamps the outgoing header;
- every *receive* folds the sender's stamp in (``l' >= l_sender``, and
  ``c' > c_sender`` when the physical components tie), so a receive
  ALWAYS orders after its send regardless of clock skew;
- ``l`` stays within one clock-uncertainty bound of real time, so
  HLC order is still human-readable as "roughly wall order".

Convention (matching NullTracer / NullAuditor / NullProfiler): the
process-global clock starts as :class:`NullHLC` — ``wire_stamp()`` is
None so senders add NO wire field and the wire bytes stay identical to
a pre-HLC build. :func:`configure_hlc` is the explicit opt-in;
``parallel/transport.py``'s ``attach_hlc`` / ``adopt_hlc`` ride the
same header path as ``attach_trace`` (DEPLOY / HEARTBEAT / FETCH_EDGE /
DETERMINANT_REQUEST / serve verbs).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple

#: one HLC timestamp: (l: µs physical witness, c: logical tiebreak,
#: node: process tiebreak). Tuple compare IS the total order.
Stamp = Tuple[int, int, str]


def stamp_key(stamp) -> Stamp:
    """Normalize a wire/JSONL-shaped stamp (list or tuple) into the
    comparable (l, c, node) tuple."""
    return (int(stamp[0]), int(stamp[1]), str(stamp[2]))


class NullHLC:
    """The disabled clock: no state, no wire field, zero overhead."""

    enabled = False
    node = None

    def tick(self) -> None:
        return None

    def observe(self, remote) -> None:
        return None

    def wire_stamp(self) -> None:
        return None


class HybridLogicalClock:
    """One process's hybrid logical clock. Thread-safe: ticks happen on
    the main loop, server threads and heartbeat threads alike."""

    enabled = True

    def __init__(self, node: Optional[str] = None,
                 # clonos: allow(wallclock): the physical component of
                 # the HLC — correlation metadata, never operator state.
                 clock=time.time):
        # clonos: allow(entropy): pid is a per-process tiebreaker in
        # ordering metadata, never replayed data.
        self.node = str(node) if node is not None else f"pid{os.getpid()}"
        self._clock = clock
        self._l = 0
        self._c = 0
        self._lock = threading.Lock()

    def _pt(self) -> int:
        return int(self._clock() * 1e6)

    def tick(self) -> Stamp:
        """Advance for a local or send event; returns the new stamp."""
        with self._lock:
            pt = self._pt()
            if pt > self._l:
                self._l, self._c = pt, 0
            else:
                self._c += 1
            return (self._l, self._c, self.node)

    def observe(self, remote) -> Stamp:
        """Fold a received stamp in (the receive rule): the result is
        strictly greater than BOTH the sender's stamp and this clock's
        previous stamp, whatever the wall clocks said."""
        l_m, c_m, _ = stamp_key(remote)
        with self._lock:
            pt = self._pt()
            l = max(self._l, l_m, pt)
            if l == self._l and l == l_m:
                c = max(self._c, c_m) + 1
            elif l == self._l:
                c = self._c + 1
            elif l == l_m:
                c = c_m + 1
            else:
                c = 0
            self._l, self._c = l, c
            return (l, c, self.node)

    def wire_stamp(self) -> Stamp:
        """Tick and return the stamp a control-wire header carries."""
        return self.tick()


# --- process-global clock ----------------------------------------------------

_global_hlc = NullHLC()
_global_lock = threading.Lock()


def get_hlc():
    """The process clock (NullHLC unless :func:`configure_hlc` ran)."""
    return _global_hlc


def configure_hlc(node: Optional[str] = None,
                  **kw) -> HybridLogicalClock:
    """Install a real process clock (the opt-in gate, like
    ``obs.configure`` for tracing)."""
    global _global_hlc
    with _global_lock:
        _global_hlc = HybridLogicalClock(node, **kw)
        return _global_hlc


def reset_hlc() -> None:
    """Back to the disabled NullHLC (tests)."""
    global _global_hlc
    with _global_lock:
        _global_hlc = NullHLC()
