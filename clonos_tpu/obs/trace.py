"""Distributed tracing + recovery flight recorder.

The reference's observability story is metric scopes that follow
job→task→operator (MetricRegistryImpl + ScopeFormats) and ad-hoc log
lines around the recovery path (RecoveryManager.java state transitions,
JobCausalLogImpl.java:268-298 occupancy logging). Since the slot-pool
scheduler (runtime/scheduler.py) one job spans multiple worker OS
processes, and the question the paper's headline claim hangs on —
*where does the time go during an epoch and during a recovery?* — has
no single-process answer anymore. This module gives the framework spans
that follow a job across process boundaries:

- :class:`Tracer` mints trace/span ids, records **complete spans**
  (``ph: "X"``: wall ``ts`` + ``dur``) and **instant events**
  (``ph: "i"``), each tagged with the trace id, the emitting service
  (``jm``, a worker id, …) and pid. Records go to (a) a bounded
  in-memory ring — the flight recorder, dumpable after the fact and
  served on ``MetricsEndpoint``'s ``/trace`` — and (b) optionally a
  JSON-lines file (one handle, append mode, flushed per record so a
  SIGKILLed worker's trace survives it).
- **Context propagation**: :meth:`Tracer.wire_context` returns a small
  dict (``{"trace_id", "span"}``) that control-wire JSON headers carry
  as a ``trace`` field (DEPLOY / TRIGGER_CHECKPOINT /
  DETERMINANT_REQUEST / FETCH_EDGE — parallel/transport.py); the
  receiving process calls :meth:`Tracer.adopt` and its subsequent spans
  land under the SAME trace id, so one recovery reconstructs from the
  JobMaster's and every worker's files together.
- **Zero overhead by default**: the process-global tracer starts as
  :class:`NullTracer` (``enabled`` False, every method a no-op,
  ``wire_context()`` → None so senders add no wire field). Enabling is
  an explicit opt-in (:func:`configure`, the ``--trace-dir`` CLI flags,
  or the ``observability.tracing.enabled`` config option).

Convert a recorded file with ``clonos_tpu trace run.jsonl --chrome
out.json`` (tools/trace2chrome.py) and load it in Perfetto / Chrome
``about:tracing``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Any, Deque, Dict, List, Optional


def _new_id() -> str:
    # clonos: allow(entropy) — trace/span ids are correlation metadata;
    # they never feed operator state and are not expected to replay.
    return uuid.uuid4().hex[:16]


class _NullSpan:
    """No-op context manager handed out by the disabled tracer."""

    __slots__ = ()
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The disabled tracer: every operation is a no-op and
    ``wire_context()`` is None, so instrumented call sites add neither
    wire fields nor per-record work to the hot path."""

    enabled = False
    trace_id = None
    service = None
    dropped = 0

    def span(self, name: str, **args) -> _NullSpan:
        return _NULL_SPAN

    def event(self, name: str, **args) -> None:
        pass

    def complete(self, name: str, dur_s: float, **args) -> None:
        pass

    def wire_context(self) -> None:
        return None

    def adopt(self, ctx) -> None:
        pass

    def records(self) -> List[dict]:
        return []

    def close(self) -> None:
        pass


class _Span:
    """A live span: context manager that emits one complete record on
    exit. Exceptions propagate; the span still closes (its ``error``
    arg records the fact)."""

    __slots__ = ("_tracer", "name", "span_id", "parent", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[str], args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.span_id = _new_id()
        self.parent = parent
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = self._tracer._clock()
        self._tracer._push(self.span_id)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer._pop()
        if exc_type is not None:
            self.args = dict(self.args, error=repr(exc))
        self._tracer._emit(
            self.name, "X", self._t0,
            dur=self._tracer._clock() - self._t0,
            span=self.span_id, parent=self.parent, args=self.args)
        return False


class Tracer:
    """Process tracer: one trace id (minted or adopted), a bounded
    flight-recorder ring, and an optional JSON-lines file sink.

    Thread-safe: spans/events may be emitted from server threads (the
    control-plane handlers) as well as the main loop; the parent-span
    stack is thread-local so concurrent spans nest correctly per
    thread."""

    enabled = True

    def __init__(self, service: str, path: Optional[str] = None,
                 # clonos: allow(wallclock): span timestamps, obs-only
                 trace_id: Optional[str] = None, clock=time.time,
                 buffer: int = 8192):
        self.service = service
        self.trace_id = trace_id or _new_id()
        self._path = path
        self._clock = clock
        self._file = None
        self._lock = threading.Lock()
        self._local = threading.local()
        #: the flight recorder: most recent records, bounded
        self._ring: Deque[dict] = collections.deque(maxlen=buffer)
        #: records evicted from the ring at overflow — a nonzero count
        #: means the in-memory timeline is TRUNCATED (the file sink, if
        #: any, still has everything). Surfaced as the
        #: ``trace.dropped-records`` counter in /metrics.json and top.
        self.dropped = 0
        # clonos: allow(entropy): trace metadata, never replayed data
        self._pid = os.getpid()

    # --- span stack (thread-local parents) -----------------------------------

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span_id: str) -> None:
        self._stack().append(span_id)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current_span(self) -> Optional[str]:
        st = self._stack()
        return st[-1] if st else None

    # --- recording -----------------------------------------------------------

    def _emit(self, name: str, ph: str, ts: float, dur: float = 0.0,
              span: Optional[str] = None, parent: Optional[str] = None,
              args: Optional[Dict[str, Any]] = None) -> None:
        rec = {"ts": ts, "name": name, "ph": ph,
               "trace": self.trace_id, "service": self.service,
               "pid": self._pid, "tid": threading.get_ident() & 0xFFFF,
               "span": span or _new_id(),
               "parent": parent if parent is not None
               else self.current_span()}
        if ph == "X":
            rec["dur"] = dur
        if args:
            rec["args"] = args
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1      # eviction, not silence
            self._ring.append(rec)
            if self._path is not None:
                # One append-mode handle for the tracer's lifetime,
                # flushed per record: a SIGKILL loses at most the record
                # being written, never the buffered history.
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(json.dumps(rec, default=str) + "\n")
                self._file.flush()

    def span(self, name: str, **args) -> _Span:
        """Context manager: records a complete span over the ``with``
        body, parented to the enclosing span of this thread."""
        return _Span(self, name, self.current_span(), args)

    def event(self, name: str, **args) -> None:
        """Instant event at now."""
        self._emit(name, "i", self._clock(), args=args)

    def complete(self, name: str, dur_s: float, **args) -> None:
        """Record an already-measured span ending now (the caller timed
        it; ``ts`` is back-dated so the timeline lays out correctly)."""
        self._emit(name, "X", self._clock() - dur_s, dur=dur_s, args=args)

    # --- context propagation -------------------------------------------------

    def wire_context(self) -> Dict[str, Any]:
        """The ``trace`` field control-wire JSON headers carry."""
        return {"trace_id": self.trace_id, "span": self.current_span()}

    def adopt(self, ctx: Optional[Dict[str, Any]]) -> None:
        """Join the sender's trace: subsequent spans/events from this
        process land under the sender's trace id (idempotent)."""
        if ctx and ctx.get("trace_id"):
            self.trace_id = str(ctx["trace_id"])

    # --- flight recorder -----------------------------------------------------

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# --- process-global tracer ---------------------------------------------------

_global_tracer = NullTracer()
_global_lock = threading.Lock()


def get_tracer():
    """The process tracer (NullTracer unless :func:`configure` ran)."""
    return _global_tracer


def configure(service: str, path: Optional[str] = None,
              trace_id: Optional[str] = None, **kw) -> Tracer:
    """Install a real process tracer (replacing the previous one, which
    is closed). The opt-in gate for all instrumentation."""
    global _global_tracer
    with _global_lock:
        old = _global_tracer
        _global_tracer = Tracer(service, path=path, trace_id=trace_id,
                                **kw)
        old.close()
        return _global_tracer


def reset() -> None:
    """Back to the disabled NullTracer (tests; also closes the file)."""
    global _global_tracer
    with _global_lock:
        _global_tracer.close()
        _global_tracer = NullTracer()
