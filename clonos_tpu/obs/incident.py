"""Incident forensics plane: durable flight-recorder bundles.

Every detector in the repo can now *raise an alarm* — audit ledger
divergence (obs/audit.py), SLO breach windows (soak/slo.py), HLC
causality inversions (obs/timeline.py), sustained gray suspects
(obs/detect.py), conformance/replay mismatches (autoscale, verify),
recovery failures (causal/recovery.py) — but an alarm is only a
pointer. Diagnosis needs the *evidence* those planes held at the
moment of the alarm, and in a crashing or flapping process that
evidence is gone by the time a human asks for it. This module is the
flight recorder:

- :class:`IncidentManager` — on any failure ``signal()``, snapshots
  one **incident bundle**: the HLC timeline slice around the trigger,
  the suspect ledger epochs ±k (with their partition-invariant
  ``ringsum`` channels), the determinant-window rows for those epochs
  pulled from whichever tier still holds them (live executor window or
  TieredEpochStore), the metrics-history window, the decision-log
  slice, the active chaos schedule, and the config + census
  fingerprint. Bundles are size-bounded (per-section caps), landed
  atomically (tmp + fsync + ``os.replace`` — a crash never leaves a
  half bundle), deduplicated by trigger fingerprint and rate-limited
  per kind, so a flapping fault cannot fill the disk.
- :mod:`clonos_tpu.obs.rootcause` — the deterministic analyzer that
  turns a bundle into a byte-identical explanation (first divergent
  epoch/channel, first divergent determinant row, ranked causal
  chain). ``clonos_tpu incident`` is the CLI over both.

Zero overhead off: :class:`NullIncidentManager` is the process default
(``signal()`` a constant no-op, no gauges, no wire fields), the
NullTracer convention. Enabling is the explicit
:func:`configure_incidents` opt-in.

The bundle format itself is pinned: :data:`BUNDLE_SCHEMA` has one
canonical fingerprint (:func:`bundle_schema_fingerprint`) checked
against ``.clonos-incident-schema`` in conftest, so silent
bundle-format drift fails the session like census drift does.
"""

from __future__ import annotations

import json
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: Failure-signal kinds the manager accepts (anything else raises —
#: a typo'd kind is a silent dead trigger otherwise).
TRIGGER_KINDS = (
    "audit.divergence",        # ledger diff found content divergence
    "slo.breach",              # a closed SLO window breached
    "timeline.inversion",      # merged HLC order causally unsound
    "health.gray-suspect",     # sustained gray-failure suspect
    "conformance.mismatch",    # replay disagreed with the decision log
    "recovery.failure",        # a recovery attempt itself failed
    "job.failure",             # dispatcher saw a job die
)

#: The pinned bundle format. PURE data — version, section names, and
#: the per-section shape notes. Any change here changes
#: :func:`bundle_schema_fingerprint` and must be re-pinned in
#: ``.clonos-incident-schema`` (conftest enforces).
BUNDLE_SCHEMA = {
    "format": "clonos-incident-bundle",
    "version": 1,
    "sections": {
        "bundle": "schema/fingerprint/kind/seq/service/ts",
        "trigger": "kind + caller fields, the dedup identity",
        "timeline": "HLC timeline slice around the trigger",
        "ledgers": "audit ledger entries, trigger epoch +/- k, per side",
        "determinants": "per-epoch determinant window summaries per side",
        "metrics": "metrics-history window (last N samples)",
        "decisions": "decision-log slice (last N records)",
        "chaos": "active chaos schedule text",
        "config": "caller-provided run config",
        "census": "pinned FT call-site census fingerprint",
    },
}


def canonical_json(obj: Any) -> str:
    """The one bundle/report encoding: sorted keys, tight separators,
    ``default=str`` for stray numpy scalars. Equal content must encode
    to equal bytes — both the dedup fingerprint and the byte-identical
    report guarantee hang off this."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)


def bundle_schema_fingerprint() -> str:
    """Fingerprint of :data:`BUNDLE_SCHEMA` (the ``.clonos-incident-
    schema`` pin)."""
    return hashlib.blake2b(canonical_json(BUNDLE_SCHEMA).encode(),
                           digest_size=8).hexdigest()


def bundle_fingerprint(trigger: Dict[str, Any]) -> str:
    """Dedup identity of one trigger: kind + caller fields. Two signals
    describing the same fault (same divergence line, same breach
    window) fingerprint equal and capture once."""
    return hashlib.blake2b(canonical_json(trigger).encode(),
                           digest_size=8).hexdigest()


# --- determinant-window summarization ---------------------------------------


def _digest8(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=8).hexdigest()


def summarize_window(window: Dict[str, Any], *,
                     max_rows: int = 256) -> Dict[str, Any]:
    """Bound one ``LocalExecutor.epoch_window`` snapshot to bundle
    size: ``log/<flat>`` rows verbatim up to ``max_rows`` (they are the
    rows rootcause descends into), ring steps as per-step
    (count, key/value/timestamp digest) summaries — enough to name the
    first divergent step without shipping the records."""
    import numpy as np

    logs: Dict[str, Any] = {}
    for flat, rows in sorted(window.get("logs", {}).items(),
                             key=lambda kv: int(kv[0])):
        arr = np.ascontiguousarray(np.asarray(rows), np.int32)
        n = int(arr.shape[0]) if arr.ndim else 0
        logs[str(flat)] = {
            "count": n,
            "rows": arr[:max_rows].tolist(),
            "truncated": bool(n > max_rows),
        }
    rings: Dict[str, Any] = {}
    for vid, steps in sorted(window.get("rings", {}).items(),
                             key=lambda kv: int(kv[0])):
        out = []
        for keys, values, timestamps in steps:
            k = np.ascontiguousarray(np.asarray(keys), np.int32)
            v = np.ascontiguousarray(np.asarray(values), np.int32)
            t = np.ascontiguousarray(np.asarray(timestamps), np.int32)
            out.append({"n": int(k.shape[0]),
                        "kdig": _digest8(k.tobytes()),
                        "vdig": _digest8(v.tobytes()),
                        "tdig": _digest8(t.tobytes())})
        rings[str(vid)] = out
    return {"logs": logs, "rings": rings}


def capture_epoch_window(executor, epoch: int, *,
                         max_rows: int = 256) -> Dict[str, Any]:
    """One epoch's determinant window from whichever tier holds it:
    the live executor window when the epoch is still retained,
    otherwise the spill/determinant tiers (TieredEpochStore — array
    digests only; the segments themselves stay on disk), otherwise an
    explicit unavailable marker. Never raises — a bundle must land
    even when the evidence is partial."""
    try:
        win = executor.epoch_window(int(epoch))
        out = summarize_window(win, max_rows=max_rows)
        out["source"] = "live"
        return out
    except Exception as live_err:
        note = repr(live_err)
    try:
        for store in executor._tier_stores():
            if int(epoch) not in store.retained_epochs():
                continue
            start, arrays = store.load_epoch(int(epoch))
            return {"source": "tier", "start": int(start),
                    "arrays": {str(k): {"shape": list(v.shape),
                                        "dig": _digest8(v.tobytes())}
                               for k, v in sorted(arrays.items())}}
    except Exception as tier_err:
        note = f"{note}; tier: {tier_err!r}"
    return {"source": "unavailable", "note": note}


# --- the manager -------------------------------------------------------------


class NullIncidentManager:
    """The disabled plane: ``signal()`` is a constant no-op — zero
    wire fields, zero per-record work (the NullTracer convention)."""

    enabled = False
    captured = 0
    deduped = 0
    suppressed = 0
    signals = 0

    def signal(self, kind: str, **fields) -> Optional[str]:
        return None

    def attach(self, **providers) -> None:
        pass

    def bundles(self) -> List[str]:
        return []

    def register_gauges(self, registry) -> None:
        pass


#: provider slots ``attach()`` accepts; anything else is a typo'd
#: dead provider and raises.
_PROVIDER_SLOTS = ("ledgers", "det_window", "metrics", "decisions",
                   "chaos", "config", "census")


class IncidentManager:
    """The flight recorder: one durable bundle per novel failure
    signal.

    Context arrives through named **providers** (:meth:`attach`):
    zero-arg callables for ``ledgers`` (``{"expected": [...entries],
    "actual": [...]}``), ``metrics``, ``decisions``, ``chaos``,
    ``config``, ``census``, and a one-arg ``det_window(epoch)``
    returning per-side ``epoch_window`` snapshots. Every provider call
    is fenced with try/except — a broken provider degrades its section
    to an error marker, it never loses the bundle.
    """

    enabled = True

    def __init__(self, root: str, *, service: Optional[str] = None,
                 epoch_radius: int = 2, timeline_window: int = 256,
                 metrics_window: int = 64, decisions_window: int = 32,
                 max_rows: int = 256, max_bundles: int = 32,
                 min_interval_s: float = 5.0,
                 # clonos: allow(wallclock): rate-limit pacing and
                 # bundle timestamps are observability metadata, never
                 # operator state.
                 clock=time.time):
        self.dir = os.path.join(root, "incidents")
        os.makedirs(self.dir, exist_ok=True)
        self.service = service
        self.epoch_radius = int(epoch_radius)
        self.timeline_window = int(timeline_window)
        self.metrics_window = int(metrics_window)
        self.decisions_window = int(decisions_window)
        self.max_rows = int(max_rows)
        self.max_bundles = int(max_bundles)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._providers: Dict[str, Callable] = {}
        self._last_capture: Dict[str, float] = {}
        self.captured = 0
        self.deduped = 0
        self.suppressed = 0
        self.signals = 0
        # A restarted process resumes dedup + numbering from the
        # bundles that survived on disk.
        self._seen: set = set()
        self._seq = 0
        for path in self.bundles():
            base = os.path.basename(path)
            try:
                self._seq = max(self._seq,
                                int(base.split("-")[1].split(".")[0]))
            except (IndexError, ValueError):
                pass
            try:
                with open(path) as f:
                    self._seen.add(
                        json.load(f)["bundle"]["fingerprint"])
            except Exception:
                continue          # a foreign file dedups nothing

    # --- context providers ---------------------------------------------------

    def attach(self, **providers) -> None:
        """Register context providers (later wins per slot)."""
        for name, fn in providers.items():
            if name not in _PROVIDER_SLOTS:
                raise ValueError(
                    f"unknown incident provider {name!r} "
                    f"(slots: {', '.join(_PROVIDER_SLOTS)})")
            if fn is None:
                self._providers.pop(name, None)
            else:
                self._providers[name] = fn

    def _call(self, name: str, *args):
        fn = self._providers.get(name)
        if fn is None:
            return None
        try:
            return fn(*args)
        except Exception as e:   # a broken provider must not lose the bundle
            return {"provider-error": repr(e)}

    # --- capture -------------------------------------------------------------

    def signal(self, kind: str, *, epoch: Optional[int] = None,
               **fields) -> Optional[str]:
        """One failure signal. Returns the landed bundle path, or None
        when the signal was deduplicated, rate-limited, or over the
        bundle cap."""
        if kind not in TRIGGER_KINDS:
            raise ValueError(f"unknown incident kind {kind!r} "
                             f"(kinds: {', '.join(TRIGGER_KINDS)})")
        trigger: Dict[str, Any] = {"kind": kind}
        if epoch is not None:
            trigger["epoch"] = int(epoch)
        trigger.update(fields)
        fp = bundle_fingerprint(trigger)
        now = self._clock()
        with self._lock:
            self.signals += 1
            if fp in self._seen:
                self.deduped += 1
                return None
            last = self._last_capture.get(kind)
            if last is not None and now - last < self.min_interval_s:
                self.suppressed += 1
                return None
            if self._seq >= self.max_bundles:
                self.suppressed += 1
                return None
            # Claim the slot under the lock; build outside it.
            self._seen.add(fp)
            self._last_capture[kind] = now
            self._seq += 1
            seq = self._seq
        path = self._capture(seq, fp, trigger, now)
        with self._lock:
            self.captured += 1
        from clonos_tpu.obs.timeline import get_timeline
        tl = get_timeline()
        if tl.enabled:
            tl.record("incident.captured", trigger_kind=kind,
                      fingerprint=fp, bundle=os.path.basename(path))
        return path

    def _epoch_span(self, epoch: Optional[int]) -> Optional[range]:
        if epoch is None:
            return None
        k = self.epoch_radius
        return range(max(0, int(epoch) - k), int(epoch) + k + 1)

    def _capture(self, seq: int, fp: str, trigger: Dict[str, Any],
                 now: float) -> str:
        from clonos_tpu.obs.timeline import get_timeline
        epoch = trigger.get("epoch")
        span = self._epoch_span(epoch)

        ledgers = self._call("ledgers")
        if isinstance(ledgers, dict) and span is not None:
            ledgers = {
                side: ([e for e in entries
                        if int(e.get("epoch", -1)) in span]
                       if isinstance(entries, list) else entries)
                for side, entries in ledgers.items()}
        elif isinstance(ledgers, dict):
            width = 2 * self.epoch_radius + 1
            ledgers = {side: (entries[-width:]
                              if isinstance(entries, list) else entries)
                       for side, entries in ledgers.items()}

        determinants: Dict[str, Any] = {}
        if span is not None and "det_window" in self._providers:
            for ep in span:
                win = self._call("det_window", ep)
                if win is not None:
                    determinants[str(ep)] = win

        metrics = self._call("metrics")
        if isinstance(metrics, list):
            metrics = metrics[-self.metrics_window:]
        decisions = self._call("decisions")
        if isinstance(decisions, list):
            decisions = decisions[-self.decisions_window:]

        bundle = {
            "bundle": {"schema": (f"{BUNDLE_SCHEMA['format']}"
                                  f"/v{BUNDLE_SCHEMA['version']}"),
                       "schema_fingerprint": bundle_schema_fingerprint(),
                       "fingerprint": fp, "kind": trigger["kind"],
                       "seq": seq, "service": self.service,
                       "ts": now},
            "trigger": trigger,
            "timeline": get_timeline().records()[-self.timeline_window:],
            "ledgers": ledgers,
            "determinants": determinants,
            "metrics": metrics,
            "decisions": decisions,
            "chaos": self._call("chaos"),
            "config": self._call("config"),
            "census": self._call("census") or _pinned_census(),
        }
        slug = trigger["kind"].replace("/", "_")
        path = os.path.join(self.dir, f"incident-{seq:04d}-{slug}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(canonical_json(bundle) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    # --- reading -------------------------------------------------------------

    def bundles(self) -> List[str]:
        """Landed bundle paths, capture order."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("incident-")
                           and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def register_gauges(self, registry) -> None:
        """``incident.*`` gauges — registered into the runner's
        MetricRegistry they ride the HEARTBEAT piggyback like every
        other plane; ``clonos_tpu top`` renders the incidents: row
        from them."""
        g = registry.group("incident")
        g.gauge("captured", lambda: self.captured)
        g.gauge("deduped", lambda: self.deduped)
        g.gauge("suppressed", lambda: self.suppressed)
        g.gauge("signals", lambda: self.signals)


def _pinned_census() -> str:
    """The pinned FT call-site census fingerprint (``.clonos-census``),
    empty when unpinned — config drift context for the bundle."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, ".clonos-census")
    try:
        with open(path) as f:
            toks = f.read().split()
        return toks[0] if toks else ""
    except OSError:
        return ""


def load_bundle(path: str) -> dict:
    """Read one landed bundle back."""
    with open(path) as f:
        return json.load(f)


# --- process-global manager --------------------------------------------------

_global_incidents = NullIncidentManager()
_global_lock = threading.Lock()


def get_incidents():
    """The process incident manager (Null unless configured)."""
    return _global_incidents


def configure_incidents(root: str, **kw) -> IncidentManager:
    """Install a real incident manager (the opt-in gate)."""
    global _global_incidents
    with _global_lock:
        _global_incidents = IncidentManager(root, **kw)
        return _global_incidents


def reset_incidents() -> None:
    """Back to the disabled NullIncidentManager (tests)."""
    global _global_incidents
    with _global_lock:
        _global_incidents = NullIncidentManager()


# --- self-check --------------------------------------------------------------


def _entry(epoch: int, channels: Dict[str, tuple]) -> dict:
    """Hand-built ledger entry (obs/digest.EpochDigest.to_entry shape)
    for the synthetic self-check bundles."""
    return {"epoch": int(epoch),
            "channels": {name: {"count": int(c), "fp": fp}
                         for name, (c, fp) in sorted(channels.items())},
            "det_counts": {}}


def _synthetic_bundles() -> Dict[str, dict]:
    """Two in-memory bundles covering both localization regimes:

    - ``unlogged-ring``: determinant log rows identical, ring VALUES
      salted from epoch 2 step 1 on — the examples/audit_nondet.py
      fault class; the analyzer must name ``ring/v1`` step 1 and the
      injecting worker from the chaos record.
    - ``log-row``: a determinant log row itself diverges at epoch 1
      row 1 — the analyzer must name the lane tag / subtask / seq.
    """
    fp_same, fp_a, fp_b = "11" * 8, "aa" * 8, "bb" * 8
    rows_same = [[3, 1, 7, 0, 0, 0, 0, 0], [4, 1, 9, 0, 0, 0, 0, 0]]
    timeline = [
        {"kind": "chaos", "ts": 1.0, "hlc": [10, 0, "soak"],
         "service": "soak", "pid": 1, "chaos_kind": "nondet",
         "targets": ["w0"]},
        {"kind": "scale.decision", "ts": 1.5, "hlc": [15, 0, "soak"],
         "service": "soak", "pid": 1, "action": "hold", "epoch": 2},
        {"kind": "epoch.seal", "ts": 2.0, "hlc": [20, 0, "soak"],
         "service": "soak", "pid": 1, "epoch": 2, "audited": True},
        {"kind": "slo.breach", "ts": 3.0, "hlc": [30, 0, "soak"],
         "service": "soak", "pid": 1, "window": 1},
    ]
    ring_bundle = {
        "bundle": {"fingerprint": "f" * 16, "kind": "audit.divergence",
                   "schema_fingerprint": bundle_schema_fingerprint()},
        "trigger": {"kind": "audit.divergence", "epoch": 2},
        "timeline": timeline,
        "ledgers": {
            "expected": [
                _entry(1, {"log/0": (2, fp_same),
                           "ring/v1": (4, fp_same),
                           "ringsum/v1": (4, fp_same)}),
                _entry(2, {"log/0": (2, fp_same),
                           "ring/v1": (4, fp_a),
                           "ringsum/v1": (4, fp_a)}),
            ],
            "actual": [
                _entry(1, {"log/0": (2, fp_same),
                           "ring/v1": (4, fp_same),
                           "ringsum/v1": (4, fp_same)}),
                _entry(2, {"log/0": (2, fp_same),
                           "ring/v1": (4, fp_b),
                           "ringsum/v1": (4, fp_b)}),
            ],
        },
        "determinants": {
            "2": {"expected": {
                      "logs": {"0": {"count": 2, "rows": rows_same,
                                     "truncated": False}},
                      "rings": {"1": [
                          {"n": 2, "kdig": fp_same, "vdig": fp_same,
                           "tdig": fp_same},
                          {"n": 2, "kdig": fp_same, "vdig": fp_a,
                           "tdig": fp_same}]}},
                  "actual": {
                      "logs": {"0": {"count": 2, "rows": rows_same,
                                     "truncated": False}},
                      "rings": {"1": [
                          {"n": 2, "kdig": fp_same, "vdig": fp_same,
                           "tdig": fp_same},
                          {"n": 2, "kdig": fp_same, "vdig": fp_b,
                           "tdig": fp_same}]}}},
        },
        "metrics": [], "decisions": [], "chaos": None,
        "config": None, "census": "",
    }
    rows_b = [rows_same[0], [5, 1, 9, 0, 0, 0, 0, 0]]
    log_bundle = {
        "bundle": {"fingerprint": "e" * 16, "kind": "recovery.failure",
                   "schema_fingerprint": bundle_schema_fingerprint()},
        "trigger": {"kind": "recovery.failure", "epoch": 1},
        "timeline": [
            {"kind": "recovery.fsm", "ts": 0.5, "hlc": [5, 0, "jm"],
             "service": "jm", "pid": 2, "state": "REDEPLOYING"},
            {"kind": "epoch.seal", "ts": 1.0, "hlc": [9, 0, "jm"],
             "service": "jm", "pid": 2, "epoch": 1, "audited": True},
        ],
        "ledgers": {
            "expected": [_entry(1, {"log/0": (2, fp_a)})],
            "actual": [_entry(1, {"log/0": (2, fp_b)})],
        },
        "determinants": {
            "1": {"expected": {"logs": {"0": {"count": 2,
                                              "rows": rows_same,
                                              "truncated": False}},
                               "rings": {}},
                  "actual": {"logs": {"0": {"count": 2,
                                            "rows": rows_b,
                                            "truncated": False}},
                             "rings": {}}},
        },
        "metrics": [], "decisions": [], "chaos": None,
        "config": None, "census": "",
    }
    return {"unlogged-ring": ring_bundle, "log-row": log_bundle}


def incident_self_check() -> List[dict]:
    """Deterministic in-memory forensics self-check (the conftest /
    ``clonos_tpu incident --self-check`` gate): analyze each synthetic
    bundle twice — once as-built, once through a JSON round-trip (the
    two-fresh-process equivalence) — and demand byte-identical reports
    that localize the planted fault exactly. Pure: no files, no wall
    clock, no jax. Returns findings (empty == sound)."""
    from clonos_tpu.obs.rootcause import analyze_bundle, render_report

    findings: List[dict] = []

    def check(rule: str, ok: bool, detail: str) -> None:
        if not ok:
            findings.append({"rule": rule, "detail": detail})

    bundles = _synthetic_bundles()

    rep = analyze_bundle(bundles["unlogged-ring"])
    text = render_report(rep)
    roundtrip = json.loads(canonical_json(bundles["unlogged-ring"]))
    text2 = render_report(analyze_bundle(roundtrip))
    check("deterministic", text == text2,
          "unlogged-ring report not byte-identical across a JSON "
          "round-trip")
    check("epoch", rep.get("first_divergent_epoch") == 2,
          f"expected first divergent epoch 2, got "
          f"{rep.get('first_divergent_epoch')}")
    check("channel", rep.get("first_divergent_channel") == "ring/v1",
          f"expected channel ring/v1, got "
          f"{rep.get('first_divergent_channel')}")
    d = rep.get("determinant") or {}
    check("determinant", d.get("kind") == "ring-step"
          and d.get("seq") == 1 and d.get("field") == "values",
          f"expected ring-step seq 1 values divergence, got {d}")
    check("injector", rep.get("injected_by") == "w0",
          f"expected injector w0, got {rep.get('injected_by')}")
    check("chain", bool(rep.get("causal_chain"))
          and rep["causal_chain"][0].get("kind") == "chaos",
          "causal chain must lead with the chaos record")

    rep = analyze_bundle(bundles["log-row"])
    text = render_report(rep)
    roundtrip = json.loads(canonical_json(bundles["log-row"]))
    text2 = render_report(analyze_bundle(roundtrip))
    check("deterministic", text == text2,
          "log-row report not byte-identical across a JSON round-trip")
    check("epoch", rep.get("first_divergent_epoch") == 1,
          f"expected first divergent epoch 1, got "
          f"{rep.get('first_divergent_epoch')}")
    check("channel", rep.get("first_divergent_channel") == "log/0",
          f"expected channel log/0, got "
          f"{rep.get('first_divergent_channel')}")
    d = rep.get("determinant") or {}
    check("determinant", d.get("kind") == "log-row"
          and d.get("seq") == 1 and d.get("subtask") == "0",
          f"expected log-row subtask 0 seq 1, got {d}")

    # The schema fingerprint must be stable across processes too — it
    # is a pure function of BUNDLE_SCHEMA.
    check("schema", bundle_schema_fingerprint()
          == bundle_schema_fingerprint(),
          "schema fingerprint not stable")
    return findings
