"""Observability: distributed tracing + recovery flight recorder +
exactly-once auditing.

See obs/trace.py for the tracing design, obs/audit.py + obs/digest.py
for the epoch audit ledger. Typical use::

    from clonos_tpu import obs

    obs.configure("jm", path="traces/trace-jm.jsonl")
    with obs.get_tracer().span("recovery.redeploy", worker="b"):
        ...

    obs.configure_audit(on_divergence="abort")   # audit every runner
"""

from .trace import (NullTracer, Tracer, configure, get_tracer,  # noqa: F401
                    reset)
from .chrome import (load_jsonl, summarize, to_chrome,  # noqa: F401
                     validate_chrome)
from .digest import EpochDigest, diff, diff_ledgers  # noqa: F401
from .audit import (Auditor, NullAuditor, configure_audit,  # noqa: F401
                    digest_epoch_window, get_auditor, reset_audit)
from .profile import (NullProfiler, Profiler, configure_profile,  # noqa: F401
                      get_profiler, reset_profile)
from .history import MetricsHistory, read_history_file  # noqa: F401

__all__ = ["Tracer", "NullTracer", "get_tracer", "configure", "reset",
           "load_jsonl", "to_chrome", "validate_chrome", "summarize",
           "EpochDigest", "diff", "diff_ledgers",
           "Auditor", "NullAuditor", "get_auditor", "configure_audit",
           "reset_audit", "digest_epoch_window",
           "Profiler", "NullProfiler", "get_profiler",
           "configure_profile", "reset_profile",
           "MetricsHistory", "read_history_file"]
