"""Observability: distributed tracing + recovery flight recorder.

See obs/trace.py for the design. Typical use::

    from clonos_tpu import obs

    obs.configure("jm", path="traces/trace-jm.jsonl")
    with obs.get_tracer().span("recovery.redeploy", worker="b"):
        ...
"""

from .trace import (NullTracer, Tracer, configure, get_tracer,  # noqa: F401
                    reset)
from .chrome import (load_jsonl, summarize, to_chrome,  # noqa: F401
                     validate_chrome)

__all__ = ["Tracer", "NullTracer", "get_tracer", "configure", "reset",
           "load_jsonl", "to_chrome", "validate_chrome", "summarize"]
