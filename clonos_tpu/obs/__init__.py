"""Observability: distributed tracing + recovery flight recorder +
exactly-once auditing.

See obs/trace.py for the tracing design, obs/audit.py + obs/digest.py
for the epoch audit ledger. Typical use::

    from clonos_tpu import obs

    obs.configure("jm", path="traces/trace-jm.jsonl")
    with obs.get_tracer().span("recovery.redeploy", worker="b"):
        ...

    obs.configure_audit(on_divergence="abort")   # audit every runner
"""

from .trace import (NullTracer, Tracer, configure, get_tracer,  # noqa: F401
                    reset)
from .chrome import (load_jsonl, summarize, to_chrome,  # noqa: F401
                     validate_chrome)
from .digest import EpochDigest, diff, diff_ledgers  # noqa: F401
from .audit import (Auditor, NullAuditor, configure_audit,  # noqa: F401
                    digest_epoch_window, get_auditor, reset_audit)
from .profile import (NullProfiler, Profiler, configure_profile,  # noqa: F401
                      get_profiler, reset_profile)
from .history import MetricsHistory, read_history_file  # noqa: F401
from .hlc import (HybridLogicalClock, NullHLC, configure_hlc,  # noqa: F401
                  get_hlc, reset_hlc, stamp_key)
from .timeline import (NullTimeline, TimelineStore,  # noqa: F401
                       causality_inversions,
                       causality_inversions_stream, configure_timeline,
                       diff_timelines, from_trace_records, get_timeline,
                       iter_merged, merge_records, read_timeline,
                       reset_timeline, timeline_self_check,
                       to_trace_records)
from .detect import (DetectorConfig, DetectorState,  # noqa: F401
                     GrayFailureDetector, GraySnapshot, GrayVerdict,
                     NullDetector, configure_detector, detect_gray,
                     get_detector, reset_detector, score_gray)
from .incident import (IncidentManager, NullIncidentManager,  # noqa: F401
                       bundle_fingerprint, bundle_schema_fingerprint,
                       capture_epoch_window, configure_incidents,
                       get_incidents, incident_self_check, load_bundle,
                       reset_incidents, summarize_window)
from .rootcause import (RootCauseAnalyzer, analyze_bundle,  # noqa: F401
                        format_report, render_report)
from .lineage import (LineagePlane, NullLineage,  # noqa: F401
                      configure_lineage, dye_hash, format_trace,
                      get_lineage, lineage_schema_fingerprint,
                      lineage_self_check, read_observations,
                      reconstruct, render_trace, reset_lineage,
                      select_dyed, trace_key)

__all__ = ["Tracer", "NullTracer", "get_tracer", "configure", "reset",
           "load_jsonl", "to_chrome", "validate_chrome", "summarize",
           "EpochDigest", "diff", "diff_ledgers",
           "Auditor", "NullAuditor", "get_auditor", "configure_audit",
           "reset_audit", "digest_epoch_window",
           "Profiler", "NullProfiler", "get_profiler",
           "configure_profile", "reset_profile",
           "MetricsHistory", "read_history_file",
           "HybridLogicalClock", "NullHLC", "get_hlc", "configure_hlc",
           "reset_hlc", "stamp_key",
           "TimelineStore", "NullTimeline", "get_timeline",
           "configure_timeline", "reset_timeline", "read_timeline",
           "merge_records", "causality_inversions", "diff_timelines",
           "from_trace_records", "to_trace_records",
           "timeline_self_check",
           "GraySnapshot", "GrayVerdict", "DetectorConfig",
           "DetectorState", "GrayFailureDetector", "NullDetector",
           "detect_gray", "score_gray", "get_detector",
           "configure_detector", "reset_detector",
           "iter_merged", "causality_inversions_stream",
           "IncidentManager", "NullIncidentManager", "get_incidents",
           "configure_incidents", "reset_incidents", "load_bundle",
           "bundle_fingerprint", "bundle_schema_fingerprint",
           "capture_epoch_window", "summarize_window",
           "incident_self_check",
           "RootCauseAnalyzer", "analyze_bundle", "render_report",
           "format_report",
           "LineagePlane", "NullLineage", "get_lineage",
           "configure_lineage", "reset_lineage", "select_dyed",
           "dye_hash", "read_observations", "reconstruct",
           "trace_key", "render_trace", "format_trace",
           "lineage_schema_fingerprint", "lineage_self_check"]
