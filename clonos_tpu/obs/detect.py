"""Gray-failure detection: a deterministic straggler scorer.

Gray failure — a worker that is alive but slow — has existed in this
repo only as an *injection* hook (``HeartbeatMonitor.lag``, the soak
``gray`` chaos event). Nothing detected it: the death timeout never
fires (the worker beats, late), the audit stays clean (the work is
correct, just slow), and the only witness is the paced load's latency
— by which time the SLO is already breached. This module closes that
gap with the same discipline as the ScalePolicy: a **pure scoring
function over pinnable snapshots**, so detection is deterministic,
unit-testable, and replayable bit-identically from logged inputs.

- :class:`GraySnapshot` — the per-fence evidence, fully quantized:
  peer-relative heartbeat ages (how far each worker's last beat lags
  the freshest peer — the gray signature; absolute age would flag the
  whole cluster between beat rounds), per-worker epoch-duration
  outliers, per-replica staleness, and the fence-stall delta. One
  canonical byte encoding, crc32-pinnable like ScaleSignals.
- :func:`detect_gray` — ``(snapshot, config, state) -> (verdict,
  state')``: score each worker (each threshold crossing is one
  reason), require the score to *sustain* ``sustain_fences``
  consecutive fences (one late beat is not a gray failure), emit the
  suspect set. No clocks, no I/O, no jax.
- :class:`GrayFailureDetector` — the stateful facade the soak driver
  calls once per completed fence: runs the pure step, logs every
  (snapshot, verdict) pair for replay, emits ``health.gray-suspect`` /
  ``health.gray-cleared`` timeline events on transitions, and serves
  the ``cluster.health.suspects`` gauge. The suspect count feeds
  ``autoscale/signals.py`` as a new unhealthy-arm input — a policy
  must not re-cut a cluster around a worker it has just diagnosed as
  limping.

Zero overhead off: :class:`NullDetector` is the process default
(``on_fence`` a no-op), matching the NullTracer convention.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _q(pairs, nd=1) -> Tuple[Tuple[str, float], ...]:
    """Quantize + sort (worker, value) pairs into the canonical tuple
    form — equal evidence must encode to equal bytes."""
    return tuple(sorted((str(k), round(float(v), nd))
                        for k, v in dict(pairs).items()))


@dataclasses.dataclass(frozen=True)
class GraySnapshot:
    """One fence's health evidence. Pure data, fully quantized."""

    epoch: int = 0
    #: (worker, ms its last beat lags the freshest peer's), sorted
    hb_age_ms: Tuple[Tuple[str, float], ...] = ()
    #: (worker, its last epoch duration ms), sorted
    epoch_ms: Tuple[Tuple[str, float], ...] = ()
    #: (replica, staleness in epochs), sorted
    staleness: Tuple[Tuple[str, float], ...] = ()
    #: fence-stall delta: ms the last fence tail exceeded the median
    fence_stall_ms: float = 0.0

    def canonical(self) -> bytes:
        """The one byte encoding (sorted-key JSON) the crc covers."""
        return json.dumps(dataclasses.asdict(self),
                          sort_keys=True).encode()

    def crc(self) -> int:
        return zlib.crc32(self.canonical())

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GraySnapshot":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        for name in ("hb_age_ms", "epoch_ms", "staleness"):
            if name in kw:
                kw[name] = tuple((str(a), float(b)) for a, b in kw[name])
        return cls(**kw)

    @classmethod
    def build(cls, *, epoch: int, hb_age_ms=None, epoch_ms=None,
              staleness=None, fence_stall_ms: float = 0.0
              ) -> "GraySnapshot":
        """Quantizing constructor from plain dicts."""
        return cls(epoch=int(epoch),
                   hb_age_ms=_q(hb_age_ms or {}),
                   epoch_ms=_q(epoch_ms or {}),
                   staleness=_q(staleness or {}),
                   fence_stall_ms=round(float(fence_stall_ms), 1))


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    #: a beat lagging the freshest peer by more than this is suspect
    hb_age_high_ms: float = 200.0
    #: an epoch slower than factor x the peer median is suspect
    epoch_outlier_factor: float = 3.0
    #: replica staleness (epochs) past this is suspect
    staleness_high: float = 2.0
    #: a fence stall past this corroborates an already-suspect worker
    fence_stall_high_ms: float = 500.0
    #: consecutive fences a nonzero score must persist
    sustain_fences: int = 2

    def __post_init__(self):
        if self.sustain_fences < 1:
            raise ValueError("sustain_fences must be >= 1")
        if self.epoch_outlier_factor <= 1.0:
            raise ValueError("epoch_outlier_factor must be > 1")


@dataclasses.dataclass(frozen=True)
class DetectorState:
    """Per-worker suspicion streaks, carried between fences
    (reconstructable by replaying the snapshot log — no hidden
    state)."""

    streaks: Tuple[Tuple[str, int], ...] = ()

    def as_dict(self) -> Dict[str, int]:
        return {k: v for k, v in self.streaks}


@dataclasses.dataclass(frozen=True)
class GrayVerdict:
    """What one fence's evidence says: the sustained suspects with
    their scores and reasons, pinned to the snapshot it was scored
    from."""

    epoch: int
    #: (worker, score, "reason+reason"), sorted by worker
    suspects: Tuple[Tuple[str, int, str], ...]
    #: all nonzero raw scores this fence (pre-sustain), sorted
    scores: Tuple[Tuple[str, int], ...]
    snapshot_crc: int

    def suspect_workers(self) -> List[str]:
        return [w for w, _, _ in self.suspects]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def score_gray(snap: GraySnapshot, cfg: DetectorConfig
               ) -> Dict[str, Tuple[int, Tuple[str, ...]]]:
    """The raw per-worker score: one point per threshold crossing.
    Peer-relative everywhere — a gray worker lags its *peers*, while a
    cluster-wide slowdown moves the median and scores nobody."""
    scores: Dict[str, List[str]] = {}

    def hit(worker: str, reason: str) -> None:
        scores.setdefault(str(worker), []).append(reason)

    for worker, age in snap.hb_age_ms:
        if age > cfg.hb_age_high_ms:
            hit(worker, "hb-lag")
    med = _median([v for _, v in snap.epoch_ms])
    if med > 0.0:
        for worker, ms in snap.epoch_ms:
            if ms > cfg.epoch_outlier_factor * med:
                hit(worker, "epoch-outlier")
    for replica, stal in snap.staleness:
        if stal > cfg.staleness_high:
            hit(replica, "replica-stale")
    if snap.fence_stall_ms > cfg.fence_stall_high_ms:
        # corroboration, not accusation: a stalled fence names no
        # worker by itself, it strengthens existing evidence
        for worker in list(scores):
            hit(worker, "fence-stall")
    return {w: (len(r), tuple(r)) for w, r in scores.items()}


def detect_gray(snap: GraySnapshot, cfg: DetectorConfig,
                state: DetectorState
                ) -> Tuple[GrayVerdict, DetectorState]:
    """One pure detection step: fold this fence's scores into the
    suspicion streaks; a worker is a suspect once its streak reaches
    ``sustain_fences``. Same (snapshot, config, state) always yields
    the same (verdict, state') — the replay property."""
    raw = score_gray(snap, cfg)
    prev = state.as_dict()
    streaks = {w: prev.get(w, 0) + 1 for w in raw}
    suspects = tuple(sorted(
        (w, raw[w][0], "+".join(raw[w][1]))
        for w, streak in streaks.items()
        if streak >= cfg.sustain_fences))
    verdict = GrayVerdict(
        epoch=snap.epoch, suspects=suspects,
        scores=tuple(sorted((w, s) for w, (s, _) in raw.items())),
        snapshot_crc=snap.crc())
    return verdict, DetectorState(streaks=tuple(sorted(streaks.items())))


class NullDetector:
    """The disabled detector: no scoring, no events, no gauge."""

    enabled = False

    def on_fence(self, snap) -> None:
        return None

    def register_gauges(self, registry) -> None:
        pass

    def suspects(self) -> List[str]:
        return []


class GrayFailureDetector:
    """Stateful facade over the pure step: one ``on_fence`` call per
    completed fence. Keeps the (snapshot, verdict) log replay needs,
    emits timeline events on suspect-set transitions, serves the
    ``cluster.health.suspects`` gauge."""

    enabled = True

    def __init__(self, config: Optional[DetectorConfig] = None):
        self.cfg = config or DetectorConfig()
        self.state = DetectorState()
        #: the replay log: one {"snapshot":…, "crc":…, "verdict":…}
        #: per fence, in order
        self.log: List[dict] = []
        self._current: Dict[str, Tuple[int, str]] = {}
        self.events_emitted = 0

    def on_fence(self, snap: GraySnapshot) -> GrayVerdict:
        from clonos_tpu.obs.timeline import get_timeline
        verdict, self.state = detect_gray(snap, self.cfg, self.state)
        self.log.append({"snapshot": json.loads(snap.canonical()),
                         "crc": snap.crc(),
                         "verdict": verdict.to_dict()})
        now = {w: (s, r) for w, s, r in verdict.suspects}
        tl = get_timeline()
        for w in sorted(set(now) - set(self._current)):
            self.events_emitted += 1
            if tl.enabled:
                tl.record("health.gray-suspect", worker=w,
                          epoch=snap.epoch, score=now[w][0],
                          reasons=now[w][1],
                          snapshot_crc=snap.crc())
            # A sustained suspect is a confirmed failure signal: hand
            # the flight recorder a bundle trigger (no-op when the
            # incident plane is disabled).
            from clonos_tpu.obs.incident import get_incidents
            get_incidents().signal(
                "health.gray-suspect", epoch=snap.epoch, worker=w,
                score=now[w][0], reasons=now[w][1],
                snapshot_crc=snap.crc())
        for w in sorted(set(self._current) - set(now)):
            self.events_emitted += 1
            if tl.enabled:
                tl.record("health.gray-cleared", worker=w,
                          epoch=snap.epoch)
        self._current = now
        return verdict

    def suspects(self) -> List[str]:
        return sorted(self._current)

    def replay(self) -> List[GrayVerdict]:
        """Re-run the pure step over the logged snapshots and prove
        each verdict reproduces bit-identically (crc pin + verdict
        equality) — the autoscale DecisionLog discipline."""
        st = DetectorState()
        out = []
        for i, rec in enumerate(self.log):
            snap = GraySnapshot.from_dict(rec["snapshot"])
            if snap.crc() != rec["crc"]:
                raise ValueError(
                    f"detector log entry {i}: snapshot fails its crc "
                    f"pin ({snap.crc():#x} != {rec['crc']:#x})")
            v, st = detect_gray(snap, self.cfg, st)
            if v.to_dict() != rec["verdict"]:
                from clonos_tpu.obs.incident import get_incidents
                get_incidents().signal(
                    "conformance.mismatch", epoch=snap.epoch,
                    source="detector-replay", entry=i)
                raise ValueError(
                    f"detector log entry {i} does not replay "
                    f"bit-identically: {v.to_dict()}")
            out.append(v)
        return out

    def register_gauges(self, registry) -> None:
        """``cluster.health.*`` gauges — ride the same rollup every
        other observer reads; ``clonos_tpu top`` renders the health:
        row from them."""
        g = registry.group("cluster.health")
        g.gauge("suspects", lambda: len(self._current))
        g.gauge("gray-events", lambda: self.events_emitted)
        g.gauge("fences-scored", lambda: len(self.log))


# --- process-global detector -------------------------------------------------

_global_detector = NullDetector()
_global_lock = threading.Lock()


def get_detector():
    """The process detector (NullDetector unless configured)."""
    return _global_detector


def configure_detector(config: Optional[DetectorConfig] = None
                       ) -> GrayFailureDetector:
    """Install a real gray-failure detector (the opt-in gate)."""
    global _global_detector
    with _global_lock:
        _global_detector = GrayFailureDetector(config)
        return _global_detector


def reset_detector() -> None:
    """Back to the disabled NullDetector (tests)."""
    global _global_detector
    with _global_lock:
        _global_detector = NullDetector()
