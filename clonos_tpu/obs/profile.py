"""Overhead attribution profiler: what does fault tolerance cost?

The paper's headline claim is that causal logging adds negligible
overhead to the steady-state pipeline (Clonos §6.2 measures it as
end-to-end throughput deltas). Tracing (obs/trace.py) shows *when*
things happen; this module answers *what fraction of a superstep the
fault-tolerance machinery costs*, continuously, on a live job:

- :class:`Profiler` hands out **section timers** (context managers) the
  hot paths wrap around their FT work — causal-log/ring appends ride
  inside the fused block program, so the host-side attributable
  sections are the block dispatch itself (user compute + fused FT),
  the epoch roll, in-flight truncation, async determinant appends, the
  lean snapshot, digest sealing, ledger writes, spill (the fence-side
  staging; the tiered stores' writer threads report their own
  ``spill-write`` and recovery its ``refill`` — storage/tiered.py),
  timer advancement, and control-transport send/recv. Each section
  feeds an ``overhead.<section>-ms`` histogram in the bound metric
  group.
- Sections are tagged ``kind="ft"`` (fault-tolerance overhead) or
  ``kind="compute"`` (user work). :meth:`Profiler.rollup` — called at
  each epoch fence — derives the **``overhead.ft-fraction``** gauge:
  FT seconds / total attributed seconds over the window since the last
  rollup. That gauge piggybacks the heartbeat like every other worker
  metric, so the JobMaster's ``/metrics.json`` (and ``clonos_tpu
  top``) shows the paper's headline number per worker, live.
- **Device fencing**: wall-clocking an async dispatch measures nothing.
  :meth:`Profiler.fence` calls ``jax.block_until_ready`` on the
  section's result — but ONLY on an enabled profiler, because the
  fence itself serializes the pipeline. The disabled
  :class:`NullProfiler` returns the value untouched, so default runs
  keep their async dispatch exactly as before.
- **Zero overhead by default**, like NullTracer/NullAuditor: the
  process-global profiler starts as :class:`NullProfiler` (every
  method a no-op returning neutral values); enabling is an explicit
  opt-in (:func:`configure_profile`, ``--profile`` CLI flags, or the
  ``observability.profile.enabled`` config option). Disabled, no wire
  fields and no per-step host work are added anywhere.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

#: section kinds
FT = "ft"              # fault-tolerance machinery (the overhead)
COMPUTE = "compute"    # user work (the denominator's other half)


class _NullSection:
    """No-op context manager handed out by the disabled profiler."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SECTION = _NullSection()


class NullProfiler:
    """The disabled profiler: every operation is a no-op, ``fence``
    passes values through untouched, so instrumented call sites add no
    per-step host work (and no device synchronization) to the hot
    path."""

    enabled = False

    def section(self, name: str, kind: str = FT) -> _NullSection:
        return _NULL_SECTION

    def observe(self, name: str, dur_s: float, kind: str = FT) -> None:
        pass

    def fence(self, value):
        return value

    def bind(self, group) -> None:
        pass

    def rollup(self) -> float:
        return 0.0

    def ft_fraction(self) -> float:
        return 0.0

    def lifetime_ft_fraction(self) -> float:
        return 0.0

    def lifetime(self) -> Dict[str, float]:
        return {}

    def snapshot(self) -> Dict[str, Any]:
        return {"enabled": False, "ft_fraction": 0.0,
                "lifetime_ft_fraction": 0.0, "sections": {}}

    def close(self) -> None:
        pass


class _Section:
    """A live section timer: context manager that attributes the wall
    time of its body to one named section. Exceptions propagate; the
    time is still attributed (failed work costs too)."""

    __slots__ = ("_profiler", "name", "kind", "_t0")

    def __init__(self, profiler: "Profiler", name: str, kind: str):
        self._profiler = profiler
        self.name = name
        self.kind = kind
        self._t0 = 0.0

    def __enter__(self) -> "_Section":
        self._t0 = self._profiler._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._profiler.observe(
            self.name, self._profiler._clock() - self._t0, self.kind)
        return False


class Profiler:
    """Process profiler: per-section cumulative timers with an epoch
    rollup into the paper's headline overhead fraction.

    Thread-safe: transport sections run on control-plane server
    threads concurrently with the main loop's epoch sections. A
    section's histogram update goes to the bound :class:`MetricGroup`
    (``bind`` is called by the runner that owns the process registry);
    an unbound profiler (e.g. on the JobMaster) still accumulates, so
    ``ft_fraction``/``lifetime`` work everywhere."""

    enabled = True

    def __init__(self, clock=time.monotonic, fence_device: bool = True):
        self._clock = clock
        self._fence_device = fence_device
        self._lock = threading.Lock()
        self._group = None
        self._cum: Dict[str, float] = {}      # window since last rollup
        self._kind: Dict[str, str] = {}
        self._life: Dict[str, float] = {}     # process lifetime
        self._last_fraction = 0.0

    # --- section timing ------------------------------------------------------

    def section(self, name: str, kind: str = FT) -> _Section:
        """Context manager attributing its body's wall time to
        ``name``. Wrap the body's device result in :meth:`fence` or
        the timer only measures dispatch."""
        return _Section(self, name, kind)

    def observe(self, name: str, dur_s: float, kind: str = FT) -> None:
        """Attribute an already-measured duration (the caller timed
        it). Durations derived by subtraction — the overlapped
        recovery tail attributes ``finalize`` as window wall minus
        audit, and overlap credits as span minus blocked-join — can go
        epsilon-negative on coarse monotonic clocks; clamp at zero so
        cumulative windows and histograms never run backwards."""
        if dur_s < 0.0:
            dur_s = 0.0
        group = None
        with self._lock:
            self._cum[name] = self._cum.get(name, 0.0) + dur_s
            self._life[name] = self._life.get(name, 0.0) + dur_s
            self._kind[name] = kind
            group = self._group
        if group is not None:
            group.histogram(f"overhead.{name}-ms").update(dur_s * 1e3)

    def fence(self, value):
        """Block until ``value``'s device computation is done, so the
        enclosing section measures execution, not dispatch. Returns
        the value."""
        if self._fence_device and value is not None:
            import jax
            jax.block_until_ready(value)
        return value

    # --- metrics binding -----------------------------------------------------

    def bind(self, group) -> None:
        """Attach the metric group that receives the
        ``overhead.<section>-ms`` histograms and the
        ``overhead.ft-fraction`` gauge (the runner's process
        registry, so the values ride the heartbeat piggyback)."""
        with self._lock:
            self._group = group
        group.gauge("overhead.ft-fraction", self.ft_fraction)

    # --- rollup --------------------------------------------------------------

    def rollup(self) -> float:
        """Close the attribution window (call at each epoch fence):
        derive FT seconds / total attributed seconds since the last
        rollup, reset the window, and return the fraction (also
        served by the ``overhead.ft-fraction`` gauge)."""
        with self._lock:
            ft = sum(v for n, v in self._cum.items()
                     if self._kind.get(n, FT) == FT)
            total = sum(self._cum.values())
            self._cum.clear()
            if total > 0.0:
                self._last_fraction = ft / total
        return self._last_fraction

    def ft_fraction(self) -> float:
        """The most recent rollup's overhead fraction."""
        return round(self._last_fraction, 6)

    def lifetime_ft_fraction(self) -> float:
        """FT / total over the whole process lifetime (bench
        reporting)."""
        with self._lock:
            ft = sum(v for n, v in self._life.items()
                     if self._kind.get(n, FT) == FT)
            total = sum(self._life.values())
        return ft / total if total > 0.0 else 0.0

    def lifetime(self) -> Dict[str, float]:
        """Cumulative seconds per section over the process lifetime."""
        with self._lock:
            return dict(self._life)

    def snapshot(self) -> Dict[str, Any]:
        """One structured view of the profiler's state: the gauge
        value, the lifetime fraction, and per-section lifetime seconds
        with kinds — what ``bench.py --ablate`` records as the runtime
        side of the FT-cost cross-check."""
        with self._lock:
            sections = {n: {"seconds": round(v, 6),
                            "kind": self._kind.get(n, FT)}
                        for n, v in sorted(self._life.items())}
        return {
            "enabled": True,
            "ft_fraction": self.ft_fraction(),
            "lifetime_ft_fraction": round(
                self.lifetime_ft_fraction(), 6),
            "sections": sections,
        }

    def close(self) -> None:
        pass


# --- process-global profiler -------------------------------------------------

_global_profiler: Any = NullProfiler()
_global_lock = threading.Lock()


def get_profiler():
    """The process profiler (NullProfiler unless
    :func:`configure_profile` ran)."""
    return _global_profiler


def configure_profile(**kw) -> Profiler:
    """Install a real process profiler (the opt-in gate for all
    overhead instrumentation)."""
    global _global_profiler
    with _global_lock:
        _global_profiler = Profiler(**kw)
        return _global_profiler


def reset_profile() -> None:
    """Back to the disabled NullProfiler (tests)."""
    global _global_profiler
    with _global_lock:
        _global_profiler = NullProfiler()
