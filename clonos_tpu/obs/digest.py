"""Per-epoch audit digests: the unit of exactly-once evidence.

A digest summarizes one closed epoch's causal surface as a set of named
**channels** (``log/<flat>`` for a subtask's determinant-log window,
``ring/v<vid>`` for a vertex's in-flight output ring window) plus a
determinant count per type. Each channel carries an ordered blake2b hash
chain over the bytes folded into it; the epoch's combined fingerprint is
the XOR of the per-channel finals, so channels may be folded in ANY
interleaving (and partial digests from disjoint channel sets merged in
any association) without changing the result — the property the unit
tests pin.

The chain is NOT associative over arbitrary chunk splits of one channel:
the live seal and the recovery-time recompute must fold identical chunk
boundaries, which is why both go through the same extraction helper
(``LocalExecutor.epoch_window`` + :func:`digest_epoch_window` in
obs/audit.py).

Only the standard library is used (``hashlib.blake2b``): the audit layer
must not pull optional native deps into the failure path.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

#: fingerprint width; 8 bytes keeps ledger entries and wire frames small
#: while collisions stay irrelevant for divergence *detection* (an audit
#: alarm triggers investigation, not an automated rollback).
DIGEST_BYTES = 8

#: every chain starts from a versioned seed so a format change can never
#: silently compare as equal against an old ledger.
_SEED = b"clonos-audit-v1"


def _init(channel: str) -> bytes:
    """Chain seed for one channel (bound to the channel name, so two
    channels with identical payload bytes still combine distinctly)."""
    return hashlib.blake2b(_SEED + channel.encode(),
                           digest_size=DIGEST_BYTES).digest()


def chain(state: bytes, data: bytes) -> bytes:
    """One fold step of a channel's ordered hash chain."""
    return hashlib.blake2b(state + data, digest_size=DIGEST_BYTES).digest()


class EpochDigest:
    """Digest of one epoch: per-channel (count, chain fingerprint) plus
    determinant counts per tag name. Mutable while folding; sealed form
    is the JSON-able dict from :meth:`to_entry`.

    ``layout`` optionally stamps the partition shape the digest was
    sealed under — ``((vertex_id, parallelism), ...)`` — so a ledger
    diff can tell "same job, different cut" from "different content"
    and fall back to the layout-invariant channels
    (obs/audit.py ``diff_ledgers_cross``). It is metadata, not
    content: equality, :func:`diff` and :meth:`combined` all ignore
    it."""

    __slots__ = ("epoch", "channels", "det_counts", "layout")

    def __init__(self, epoch: int,
                 channels: Optional[Dict[str, Tuple[int, bytes]]] = None,
                 det_counts: Optional[Dict[str, int]] = None,
                 layout: Optional[Tuple[Tuple[int, int], ...]] = None):
        self.epoch = int(epoch)
        #: channel name -> (records folded, current chain state)
        self.channels: Dict[str, Tuple[int, bytes]] = dict(channels or {})
        self.det_counts: Dict[str, int] = dict(det_counts or {})
        self.layout = (tuple((int(v), int(p)) for v, p in layout)
                       if layout else None)

    # --- folding -------------------------------------------------------------

    def fold(self, channel: str, data: bytes, count: int = 1) -> None:
        """Fold one chunk of ``data`` (covering ``count`` records) into
        ``channel``'s ordered chain."""
        cnt, state = self.channels.get(channel, (0, _init(channel)))
        self.channels[channel] = (cnt + int(count), chain(state, data))

    def count_det(self, tag_name: str, n: int = 1) -> None:
        if n:
            self.det_counts[tag_name] = self.det_counts.get(tag_name, 0) + n

    # --- combination ---------------------------------------------------------

    def record_count(self) -> int:
        return sum(c for c, _ in self.channels.values())

    def combined(self) -> str:
        """Order-insensitive epoch fingerprint: XOR over each channel's
        H(name || final || count). Channel-interleaving invariant."""
        acc = 0
        for name, (cnt, state) in self.channels.items():
            h = hashlib.blake2b(
                name.encode() + b"\x00" + state + cnt.to_bytes(8, "little"),
                digest_size=DIGEST_BYTES).digest()
            acc ^= int.from_bytes(h, "little")
        return acc.to_bytes(DIGEST_BYTES, "little").hex()

    def merge(self, other: "EpochDigest") -> "EpochDigest":
        """Combine two partial digests of the SAME epoch over disjoint
        channel sets (e.g. folded by different host threads). Associative
        and commutative; overlapping channels are a caller bug."""
        if other.epoch != self.epoch:
            raise ValueError(
                f"cannot merge digests of epochs {self.epoch} and "
                f"{other.epoch}")
        overlap = set(self.channels) & set(other.channels)
        if overlap:
            raise ValueError(
                f"cannot merge digests sharing channels {sorted(overlap)}: "
                f"a channel's chain is ordered and owned by one folder")
        out = EpochDigest(self.epoch, self.channels, self.det_counts,
                          layout=self.layout or other.layout)
        out.channels.update(other.channels)
        for tag, n in other.det_counts.items():
            out.det_counts[tag] = out.det_counts.get(tag, 0) + n
        return out

    # --- serialization -------------------------------------------------------

    def to_entry(self) -> dict:
        """Ledger-entry form (plain JSON-able dict). ``layout`` is
        emitted only when stamped, so unstamped entries keep the exact
        pre-layout byte format."""
        out = {
            "epoch": self.epoch,
            "combined": self.combined(),
            "records": self.record_count(),
            "channels": {name: {"count": cnt, "fp": state.hex()}
                         for name, (cnt, state)
                         in sorted(self.channels.items())},
            "det_counts": dict(sorted(self.det_counts.items())),
        }
        if self.layout is not None:
            out["layout"] = [[v, p] for v, p in self.layout]
        return out

    @classmethod
    def from_entry(cls, entry: dict) -> "EpochDigest":
        chans = {name: (int(c["count"]), bytes.fromhex(c["fp"]))
                 for name, c in (entry.get("channels") or {}).items()}
        return cls(int(entry["epoch"]), chans,
                   {k: int(v)
                    for k, v in (entry.get("det_counts") or {}).items()},
                   layout=entry.get("layout"))

    def __eq__(self, other) -> bool:
        return (isinstance(other, EpochDigest)
                and self.epoch == other.epoch
                and self.channels == other.channels
                and self.det_counts == other.det_counts)

    def __repr__(self) -> str:
        return (f"EpochDigest(epoch={self.epoch}, "
                f"channels={len(self.channels)}, "
                f"records={self.record_count()}, "
                f"combined={self.combined()})")


def diff(expected: EpochDigest, actual: EpochDigest
         ) -> Optional[Tuple[str, str]]:
    """First divergence between two digests of the same epoch, or None.

    Returns ``(channel, reason)`` naming the first diverging channel in
    sorted order — the audit alarm's blast-radius pointer (which
    subtask's log or which vertex's output stream went off-script).
    Determinant-count skew with identical channels reports as channel
    ``"det_counts"``.
    """
    for name in sorted(set(expected.channels) | set(actual.channels)):
        e = expected.channels.get(name)
        a = actual.channels.get(name)
        if e is None:
            return name, f"unexpected channel (folded {a[0]} records)"
        if a is None:
            return name, f"channel missing (expected {e[0]} records)"
        if e[0] != a[0]:
            return name, f"record count {a[0]} != expected {e[0]}"
        if e[1] != a[1]:
            return (name, f"fingerprint {a[1].hex()} != expected "
                          f"{e[1].hex()} (count {e[0]} matches: "
                          f"content divergence)")
    if expected.det_counts != actual.det_counts:
        return "det_counts", (f"determinant counts {actual.det_counts} "
                              f"!= expected {expected.det_counts}")
    return None


def diff_ledgers(expected: List[dict], actual: List[dict]) -> List[str]:
    """Human-readable first-divergence report between two ledgers (lists
    of ledger entries), one line per diverging/missing epoch — the
    ``clonos_tpu audit --diff`` surface."""
    ea = {int(e["epoch"]): e for e in expected}
    aa = {int(e["epoch"]): e for e in actual}
    out = []
    for ep in sorted(set(ea) | set(aa)):
        if ep not in aa:
            out.append(f"epoch {ep}: missing from second ledger")
            continue
        if ep not in ea:
            out.append(f"epoch {ep}: missing from first ledger")
            continue
        d = diff(EpochDigest.from_entry(ea[ep]),
                 EpochDigest.from_entry(aa[ep]))
        if d is not None:
            out.append(f"epoch {ep} channel {d[0]}: {d[1]}")
    return out
