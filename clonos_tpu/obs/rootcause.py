"""Deterministic root-cause localization over an incident bundle.

The paper's premise (PAPER.md core idea 2) is that every
nondeterministic decision is a *recorded determinant* — which means
"what diverged first, and what caused it" is a pure computation over
the recorded evidence, not on-call archaeology. This module is that
computation, with the same discipline as the rest of the repo's
decision machinery (ScalePolicy, detect_gray): a **pure function of
the bundle**, no clocks, no filesystem, no ambient state — the same
bundle in any process produces a byte-identical report
(:func:`render_report`), so the explanation itself is auditable.

Three descents, coarse to fine:

1. **Epoch bisection** — walk the bundle's ledger pair epoch by epoch
   through ``diff_ledgers_cross`` (obs/audit.py — exact diff under one
   layout, group-directory mapped across a re-cut) to the FIRST
   divergent epoch, then sort its divergent channels into natural
   order to name the first divergent channel.
2. **Determinant descent** — inside that epoch's determinant-window
   summaries, name the first divergent row: a ``log/<flat>`` channel
   names (lane tag, subtask, seq) from the verbatim rows; a ``ring``
   channel names the first step whose key/value/timestamp digest
   flipped — identical log rows with salted ring values is the
   *unlogged nondeterminism* signature (examples/audit_nondet.py).
3. **Causal chain** — walk the HLC timeline backward from the seal of
   the divergent epoch, ranking what preceded it (chaos injections,
   recovery transitions, scale decisions, gray suspicions, SLO
   breaches, message receives) into the ordered chain the report
   emits; the nearest chaos record names the injecting worker.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

#: causal-chain kind priorities: lower ranks closer to "cause".
_CHAIN_RANK = {"chaos": 0, "recovery.fsm": 1, "scale.decision": 2,
               "health.gray-suspect": 3, "slo.breach": 4,
               "msg.recv": 5}
#: payload fields worth carrying into a chain entry (bounded: a chain
#: is a pointer into the bundle, not a second copy of it)
_CHAIN_FIELDS = ("epoch", "chaos_kind", "targets", "worker", "state",
                 "action", "verb", "window", "reasons", "audited")
_CHAIN_LIMIT = 16


def _channel_key(name: str) -> Tuple[str, int]:
    """Natural channel order: ``ring/v2`` before ``ring/v10`` (string
    sort would not), prefix first — deterministic and human-sane."""
    m = re.match(r"^(.*?)(\d+)$", str(name))
    if m:
        return (m.group(1), int(m.group(2)))
    return (str(name), -1)


def _ledger_sides(bundle: dict) -> Optional[Tuple[List[dict],
                                                  List[dict]]]:
    """The (expected, actual) entry lists, or None when the bundle
    holds fewer than two comparable ledgers."""
    ledgers = bundle.get("ledgers")
    if not isinstance(ledgers, dict):
        return None
    sides = {k: v for k, v in ledgers.items() if isinstance(v, list)}
    if "expected" in sides and "actual" in sides:
        return sides["expected"], sides["actual"]
    if len(sides) == 2:
        a, b = sorted(sides)
        return sides[a], sides[b]
    return None


def _first_divergent_epoch(expected: List[dict], actual: List[dict]
                           ) -> Tuple[Optional[int], List[str]]:
    """Bisect to the first epoch whose single-entry cross-diff is
    non-empty; evidence is that epoch's findings verbatim."""
    from clonos_tpu.obs.audit import diff_ledgers_cross
    ea = {int(e["epoch"]): e for e in expected if "epoch" in e}
    aa = {int(e["epoch"]): e for e in actual if "epoch" in e}
    for ep in sorted(set(ea) | set(aa)):
        pair_e = [ea[ep]] if ep in ea else []
        pair_a = [aa[ep]] if ep in aa else []
        findings = diff_ledgers_cross(pair_e, pair_a)
        if findings:
            return ep, list(findings)
    return None, []


def _divergent_channels(expected: List[dict], actual: List[dict],
                        epoch: int) -> List[str]:
    """Every channel whose (count, fp) differs at ``epoch``, natural
    order. Exact comparison — cross-layout epochs fall back to the
    channel named in the diff findings instead."""
    ea = {int(e["epoch"]): e for e in expected if "epoch" in e}
    aa = {int(e["epoch"]): e for e in actual if "epoch" in e}
    ce = (ea.get(epoch) or {}).get("channels") or {}
    ca = (aa.get(epoch) or {}).get("channels") or {}
    if (ea.get(epoch) or {}).get("layout") \
            != (aa.get(epoch) or {}).get("layout"):
        return []
    out = [name for name in set(ce) | set(ca)
           if ce.get(name) != ca.get(name)]
    return sorted(out, key=_channel_key)


_CHANNEL_IN_FINDING = re.compile(r"channel (\S+?):")


def _channel_from_findings(findings: List[str]) -> Optional[str]:
    for line in findings:
        m = _CHANNEL_IN_FINDING.search(line)
        if m:
            return m.group(1)
    return None


# --- determinant descent -----------------------------------------------------


def _det_sides(bundle: dict, epoch: int
               ) -> Optional[Tuple[dict, dict]]:
    dets = bundle.get("determinants")
    if not isinstance(dets, dict):
        return None
    entry = dets.get(str(epoch))
    if not isinstance(entry, dict):
        return None
    e, a = entry.get("expected"), entry.get("actual")
    if isinstance(e, dict) and isinstance(a, dict) \
            and "logs" in e and "logs" in a:
        return e, a
    return None


def _first_divergent_log_row(e: dict, a: dict, flat: str
                             ) -> Optional[dict]:
    """First differing verbatim determinant row of one subtask's log
    window — named as (lane tag, subtask, seq)."""
    from clonos_tpu.causal import determinant as det
    rows_e = ((e.get("logs") or {}).get(flat) or {}).get("rows")
    rows_a = ((a.get("logs") or {}).get(flat) or {}).get("rows")
    if rows_e is None or rows_a is None:
        return None
    n = max(len(rows_e), len(rows_a))
    for i in range(n):
        re_i = rows_e[i] if i < len(rows_e) else None
        ra_i = rows_a[i] if i < len(rows_a) else None
        if re_i != ra_i:
            row = ra_i if ra_i is not None else re_i
            tag = int(row[det.LANE_TAG]) if row else -1
            return {"kind": "log-row", "subtask": str(flat),
                    "seq": i, "lane_tag": tag,
                    "tag": (det.TAG_NAMES[tag]
                            if 0 <= tag < det.NUM_TAGS else "?"),
                    "missing_side": ("actual" if ra_i is None else
                                     "expected" if re_i is None
                                     else None)}
    return None


def _first_divergent_ring_step(e: dict, a: dict, vid: str
                               ) -> Optional[dict]:
    """First ring step whose per-step summary flipped, and WHICH field
    flipped — values-only with keys/timestamps/counts intact is the
    unlogged-salt signature."""
    steps_e = (e.get("rings") or {}).get(vid)
    steps_a = (a.get("rings") or {}).get(vid)
    if steps_e is None or steps_a is None:
        return None
    n = max(len(steps_e), len(steps_a))
    for i in range(n):
        se = steps_e[i] if i < len(steps_e) else None
        sa = steps_a[i] if i < len(steps_a) else None
        if se == sa:
            continue
        if se is None or sa is None:
            field = "missing-step"
        elif se.get("n") != sa.get("n"):
            field = "count"
        elif se.get("kdig") != sa.get("kdig"):
            field = "keys"
        elif se.get("vdig") != sa.get("vdig"):
            field = "values"
        else:
            field = "timestamps"
        return {"kind": "ring-step", "vertex": str(vid), "seq": i,
                "field": field}
    return None


def _logs_identical(e: dict, a: dict) -> bool:
    return (e.get("logs") or {}) == (a.get("logs") or {})


def _descend_determinants(bundle: dict, epoch: int,
                          channel: Optional[str]) -> Optional[dict]:
    sides = _det_sides(bundle, epoch)
    if sides is None or channel is None:
        return None
    e, a = sides
    if channel.startswith("log/"):
        return _first_divergent_log_row(e, a, channel[len("log/"):])
    m = re.match(r"^ring(?:sum)?/v(\d+)$", channel)
    if m:
        hit = _first_divergent_ring_step(e, a, m.group(1))
        if hit is not None and _logs_identical(e, a):
            hit["note"] = ("determinant log rows identical — "
                           "unlogged nondeterminism "
                           "(the examples/audit_nondet.py class)")
        return hit
    return None


# --- causal chain ------------------------------------------------------------


def _timeline_merged(bundle: dict) -> List[dict]:
    from clonos_tpu.obs.timeline import merge_records
    tl = bundle.get("timeline")
    return merge_records([r for r in tl if isinstance(r, dict)]) \
        if isinstance(tl, list) else []


def _seal_position(merged: List[dict], epoch: Optional[int]) -> int:
    """Index just past the divergent epoch's seal record (the walk-back
    anchor); the whole timeline when no seal matches."""
    if epoch is not None:
        for i, rec in enumerate(merged):
            if rec.get("kind") == "epoch.seal" \
                    and rec.get("epoch") == epoch:
                return i + 1
    return len(merged)


def _causal_chain(merged: List[dict], anchor: int) -> List[dict]:
    """Walk backward from the anchor collecting rankable records; emit
    them ordered by (kind priority, proximity to the seal)."""
    hits: List[Tuple[int, int, dict]] = []
    for back, rec in enumerate(reversed(merged[:anchor])):
        kind = rec.get("kind")
        if kind not in _CHAIN_RANK:
            continue
        entry = {"kind": kind, "hlc": rec.get("hlc"),
                 "service": rec.get("service")}
        for field in _CHAIN_FIELDS:
            if field in rec:
                entry[field] = rec[field]
        hits.append((_CHAIN_RANK[kind], back, entry))
        if len(hits) >= 4 * _CHAIN_LIMIT:
            break
    hits.sort(key=lambda t: (t[0], t[1]))
    out = []
    for rank, (_, _, entry) in enumerate(hits[:_CHAIN_LIMIT]):
        entry["rank"] = rank
        out.append(entry)
    return out


def _injector(chain: List[dict]) -> Optional[str]:
    """The injecting worker: the highest-ranked chaos record's targets
    (or service when untargeted)."""
    for entry in chain:
        if entry.get("kind") != "chaos":
            continue
        targets = entry.get("targets")
        if isinstance(targets, list) and targets:
            return ",".join(str(t) for t in targets)
        return entry.get("service")
    return None


# --- the analyzer ------------------------------------------------------------


def analyze_bundle(bundle: dict) -> dict:
    """The pure localization: bundle in, report dict out. Every field
    derives only from bundle content — re-running in a fresh process
    reproduces the report byte for byte."""
    trigger = bundle.get("trigger") or {}
    info = bundle.get("bundle") or {}
    report: Dict[str, Any] = {
        "report": "clonos-incident-rootcause/v1",
        "bundle_fingerprint": info.get("fingerprint"),
        "schema_fingerprint": info.get("schema_fingerprint"),
        "trigger": trigger,
        "first_divergent_epoch": None,
        "first_divergent_channel": None,
        "divergent_channels": [],
        "evidence": [],
        "determinant": None,
        "injected_by": None,
        "causal_chain": [],
        "verdict": "insufficient-evidence",
    }

    sides = _ledger_sides(bundle)
    epoch: Optional[int] = None
    channel: Optional[str] = None
    if sides is not None:
        expected, actual = sides
        epoch, evidence = _first_divergent_epoch(expected, actual)
        report["evidence"] = evidence
        if epoch is None:
            report["verdict"] = "no-divergence"
        else:
            report["first_divergent_epoch"] = epoch
            chans = _divergent_channels(expected, actual, epoch)
            report["divergent_channels"] = chans
            channel = (chans[0] if chans
                       else _channel_from_findings(evidence))
            report["first_divergent_channel"] = channel

    if epoch is None and trigger.get("epoch") is not None:
        # No ledger pair (or none divergent): anchor the chain on the
        # trigger's epoch so the walk-back still explains *something*.
        epoch = int(trigger["epoch"])

    report["determinant"] = _descend_determinants(
        bundle, epoch, channel) if epoch is not None else None

    merged = _timeline_merged(bundle)
    chain = _causal_chain(merged, _seal_position(merged, epoch))
    report["causal_chain"] = chain
    report["injected_by"] = _injector(chain)

    if report["first_divergent_channel"] is not None:
        report["verdict"] = ("localized"
                             if report["determinant"] is not None
                             else "localized-channel")
    return report


def render_report(report: dict) -> str:
    """The one byte encoding of a report (canonical JSON + newline) —
    what ``incident explain --report json`` prints and what the
    byte-identity acceptance check compares."""
    from clonos_tpu.obs.incident import canonical_json
    return canonical_json(report) + "\n"


def format_report(report: dict) -> str:
    """Human-readable rendering of a report (the default ``incident
    explain`` output). Derived from the same report dict — the JSON
    form stays the auditable artifact."""
    lines = [f"verdict: {report.get('verdict')}",
             f"trigger: {(report.get('trigger') or {}).get('kind')}"
             f" (bundle {report.get('bundle_fingerprint')})"]
    ep = report.get("first_divergent_epoch")
    if ep is not None:
        lines.append(f"first divergent epoch: {ep}")
    ch = report.get("first_divergent_channel")
    if ch is not None:
        others = [c for c in report.get("divergent_channels", [])
                  if c != ch]
        suffix = f" (+{len(others)} more)" if others else ""
        lines.append(f"first divergent channel: {ch}{suffix}")
    det = report.get("determinant")
    if det:
        if det.get("kind") == "log-row":
            lines.append(
                f"first divergent determinant row: subtask "
                f"{det.get('subtask')} seq {det.get('seq')} "
                f"tag {det.get('tag')} (lane {det.get('lane_tag')})")
        else:
            lines.append(
                f"first divergent determinant row: ring v"
                f"{det.get('vertex')} step {det.get('seq')} "
                f"[{det.get('field')}]")
        if det.get("note"):
            lines.append(f"  note: {det['note']}")
    inj = report.get("injected_by")
    if inj is not None:
        lines.append(f"injected by: {inj}")
    for e in report.get("evidence", [])[:4]:
        lines.append(f"evidence: {e}")
    chain = report.get("causal_chain", [])
    if chain:
        lines.append("causal chain (ranked):")
        for entry in chain[:8]:
            extra = {k: v for k, v in entry.items()
                     if k not in ("rank", "kind", "hlc", "service")}
            lines.append(f"  #{entry.get('rank')} {entry.get('kind')}"
                         f" @{entry.get('service')} {extra}")
    return "\n".join(lines)


class RootCauseAnalyzer:
    """Thin object facade over :func:`analyze_bundle` (symmetry with
    Auditor/GrayFailureDetector; the function is the substance)."""

    def analyze(self, bundle: dict) -> dict:
        return analyze_bundle(bundle)

    def explain(self, path: str) -> dict:
        from clonos_tpu.obs.incident import load_bundle
        return analyze_bundle(load_bundle(path))
