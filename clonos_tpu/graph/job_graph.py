"""Job graph: the compiled topology handed to the executor.

Capability analog of the reference's two-stage graph translation
(StreamGraphGenerator.generate -> StreamingJobGraphGenerator.createJobGraph,
flink-streaming-java .../api/graph/StreamGraphGenerator.java:123 and
StreamingJobGraphGenerator.java:82). The TPU build needs only one graph
form: vertices are already "chained" at trace time (an operator's ``process``
is inlined into the superstep program, so Flink-style operator chaining is
what XLA fusion does for free); edges carry the partition strategy and the
receive capacity.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

from clonos_tpu.api.operators import Operator
from clonos_tpu.graph.vertex_info import VertexGraphInformation, compute_distances


class PartitionType(enum.Enum):
    FORWARD = "forward"      # 1:1, same parallelism
    HASH = "hash"            # keyBy: key-group routing
    REBALANCE = "rebalance"  # deterministic round-robin
    BROADCAST = "broadcast"  # every record to every subtask


@dataclasses.dataclass
class JobVertex:
    """One logical operator instance in the DAG."""

    vertex_id: int
    name: str
    operator: Operator
    parallelism: int


@dataclasses.dataclass
class JobEdge:
    """Directed edge with exchange semantics. ``capacity`` is the receive
    buffer size per downstream subtask per superstep (the credit-based
    receive window analog; overflow is counted as backpressure drops)."""

    src: int
    dst: int
    partition: PartitionType
    capacity: int


@dataclasses.dataclass
class JobGraph:
    """The deployable topology (reference JobGraph analog)."""

    vertices: List[JobVertex] = dataclasses.field(default_factory=list)
    edges: List[JobEdge] = dataclasses.field(default_factory=list)
    name: str = "job"
    num_key_groups: int = 128
    sharing_depth: int = -1

    def add_vertex(self, name: str, operator: Operator,
                   parallelism: int) -> JobVertex:
        v = JobVertex(len(self.vertices), name, operator, parallelism)
        self.vertices.append(v)
        return v

    def add_edge(self, src: JobVertex, dst: JobVertex,
                 partition: PartitionType, capacity: int) -> JobEdge:
        if partition == PartitionType.FORWARD and src.parallelism != dst.parallelism:
            raise ValueError(
                f"FORWARD edge requires equal parallelism: "
                f"{src.name}({src.parallelism}) -> {dst.name}({dst.parallelism})")
        e = JobEdge(src.vertex_id, dst.vertex_id, partition, capacity)
        self.edges.append(e)
        return e

    # --- topology queries (control plane only) ------------------------------

    def in_edges(self, vertex_id: int) -> List[int]:
        return [i for i, e in enumerate(self.edges) if e.dst == vertex_id]

    def out_edges(self, vertex_id: int) -> List[int]:
        return [i for i, e in enumerate(self.edges) if e.src == vertex_id]

    def topo_order(self) -> List[int]:
        """Topologically sorted vertex ids (the reference ships this list to
        every TM, taskmanager/Task.java:350)."""
        indeg = {v.vertex_id: 0 for v in self.vertices}
        for e in self.edges:
            indeg[e.dst] += 1
        ready = sorted(v for v, d in indeg.items() if d == 0)
        order: List[int] = []
        while ready:
            u = ready.pop(0)
            order.append(u)
            for i in self.out_edges(u):
                d = self.edges[i].dst
                indeg[d] -= 1
                if indeg[d] == 0:
                    ready.append(d)
            ready.sort()
        if len(order) != len(self.vertices):
            raise ValueError("job graph has a cycle")
        return order

    def graph_info(self, vertex_id: int) -> VertexGraphInformation:
        return VertexGraphInformation(
            vertex=vertex_id,
            num_vertices=len(self.vertices),
            edges=tuple((e.src, e.dst) for e in self.edges),
            parallelism=tuple(v.parallelism for v in self.vertices),
        )

    def total_subtasks(self) -> int:
        return sum(v.parallelism for v in self.vertices)

    def subtask_base(self, vertex_id: int) -> int:
        """Global flat index of (vertex, subtask 0) in the stacked-log
        layout: logs of all subtasks of all vertices stacked in vertex-id
        order."""
        return sum(v.parallelism for v in self.vertices[:vertex_id])

    def subgraph(self, vertex_ids: Sequence[int], feed_batch_size: int = 8
                 ) -> Tuple["JobGraph", Dict[int, int],
                            Dict[int, int], Dict[int, int]]:
        """Deployment slice over ``vertex_ids`` (runtime/scheduler.py's
        unit of placement — the per-TaskExecutor TaskDeploymentDescriptor
        analog). Cut edges become boundary vertices:

        - every in-cut edge (src outside the slice) is replaced by a
          ``HostFeedSource`` feeding the kept dst through the ORIGINAL
          partition/capacity — the records arrive from the upstream
          worker over the wire (a rewindable reader, api/feeds.py);
        - every out-cut edge (dst outside the slice) gets a terminal
          ``SinkOperator`` consumer on a FORWARD edge, which keeps the
          producer's in-flight out-ring in the slice — the ring is what
          the worker's edge export serves (and replays) to downstream
          workers.

        Returns ``(sub, vmap, feeds, exports)``: ``vmap`` maps original
        vertex id -> slice vertex id, ``feeds`` maps original in-cut
        edge index -> slice feed vertex id, ``exports`` maps original
        out-cut edge index -> slice vertex id of the producer (whose
        ring serves that edge). Structure depends only on
        ``(vertex_ids, feed_batch_size)``, so JobMaster and workers
        derive identical slices independently."""
        from clonos_tpu.api.operators import HostFeedSource, SinkOperator
        keep = set(vertex_ids)
        unknown = keep - {v.vertex_id for v in self.vertices}
        if unknown:
            raise ValueError(f"subgraph: unknown vertex ids {sorted(unknown)}")
        sub = JobGraph(name=f"{self.name}-slice",
                       num_key_groups=self.num_key_groups,
                       sharing_depth=self.sharing_depth)
        vmap: Dict[int, int] = {}
        for vid in self.topo_order():
            if vid in keep:
                v = self.vertices[vid]
                vmap[vid] = sub.add_vertex(v.name, v.operator,
                                           v.parallelism).vertex_id
        feeds: Dict[int, int] = {}
        exports: Dict[int, int] = {}
        for eidx, e in enumerate(self.edges):
            if e.src in keep and e.dst in keep:
                sub.add_edge(sub.vertices[vmap[e.src]],
                             sub.vertices[vmap[e.dst]],
                             e.partition, e.capacity)
            elif e.dst in keep:
                # The wire export flattens the producer's lanes into ONE
                # record stream, so only exchange edges (which re-route
                # through the partition anyway) can be cut; a FORWARD cut
                # would need per-lane streams to preserve lane affinity.
                if e.partition == PartitionType.FORWARD:
                    raise ValueError(
                        f"subgraph: cut crosses FORWARD edge {eidx} "
                        f"({self.vertices[e.src].name} -> "
                        f"{self.vertices[e.dst].name}); slice boundaries "
                        f"must land on exchange edges")
                fv = sub.add_vertex(f"feed-in-{eidx}",
                                    HostFeedSource(
                                        batch_size=feed_batch_size), 1)
                sub.add_edge(fv, sub.vertices[vmap[e.dst]],
                             e.partition, e.capacity)
                feeds[eidx] = fv.vertex_id
            elif e.src in keep:
                src = sub.vertices[vmap[e.src]]
                sv = sub.add_vertex(f"export-{eidx}", SinkOperator(),
                                    src.parallelism)
                sub.add_edge(src, sv, PartitionType.FORWARD, e.capacity)
                exports[eidx] = vmap[e.src]
        sub.validate()
        return sub, vmap, feeds, exports

    def validate(self) -> None:
        from clonos_tpu.api.operators import TwoInputOperator
        self.topo_order()
        for v in self.vertices:
            ins = self.in_edges(v.vertex_id)
            two = isinstance(v.operator, TwoInputOperator)
            if two and len(ins) != 2:
                raise ValueError(
                    f"vertex {v.name}: TwoInputOperator needs exactly 2 "
                    f"input edges, has {len(ins)}")
            if not two and len(ins) > 1:
                raise ValueError(
                    f"vertex {v.name}: single-input operator with "
                    f"{len(ins)} input edges (use a TwoInputOperator)")
