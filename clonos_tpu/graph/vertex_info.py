"""Per-task view of the job DAG and causal-log identity.

Capability parity with the reference's ``VertexGraphInformation``
(flink-runtime .../causal/VertexGraphInformation.java:63),
``CausalGraphUtils.computeDistances`` (CausalGraphUtils.java:41-108) and
``CausalLogID`` (causal/log/job/CausalLogID.java:38-44).

Vertex IDs are dense small ints assigned in topological order (the reference
ships the topologically-sorted JobVertex list to every task manager,
taskmanager/Task.java:350). Distances are directed downstream hop counts,
used to mask determinant replication by sharing depth: a task holds replicas
of the logs of every task at distance <= depth *upstream* of it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, FrozenSet, List, Sequence, Tuple

import numpy as np

UNREACHABLE = np.iinfo(np.int32).max


@dataclasses.dataclass(frozen=True, order=True)
class CausalLogID:
    """Identity of one thread causal log.

    ``subpartition == -1`` is the task's main-thread log; ``>= 0`` identifies
    an output-subpartition log (which records BUFFER_BUILT determinants for
    that outgoing edge partition).
    """

    vertex: int
    subtask: int
    subpartition: int = -1

    def is_main_thread(self) -> bool:
        return self.subpartition < 0

    def for_subpartition(self, idx: int) -> "CausalLogID":
        return CausalLogID(self.vertex, self.subtask, idx)


def compute_distances(
    num_vertices: int, edges: Sequence[Tuple[int, int]]
) -> np.ndarray:
    """All-pairs directed downstream hop distance.

    ``dist[u, v]`` = fewest edges on a directed path u -> v; 0 on the
    diagonal; UNREACHABLE where no path exists. BFS per source (DAGs are
    tiny: this is control-plane-only, never in the hot path).
    """
    adj: List[List[int]] = [[] for _ in range(num_vertices)]
    for src, dst in edges:
        adj[src].append(dst)
    dist = np.full((num_vertices, num_vertices), UNREACHABLE, dtype=np.int64)
    for s in range(num_vertices):
        dist[s, s] = 0
        frontier = [s]
        d = 0
        while frontier:
            d += 1
            nxt = []
            for u in frontier:
                for v in adj[u]:
                    if dist[s, v] == UNREACHABLE:
                        dist[s, v] = d
                        nxt.append(v)
            frontier = nxt
    return dist


@dataclasses.dataclass(frozen=True)
class VertexGraphInformation:
    """One task's view of the DAG (shipped to every executor)."""

    vertex: int
    num_vertices: int
    edges: Tuple[Tuple[int, int], ...]          # all DAG edges (vertex ids)
    parallelism: Tuple[int, ...]                # per-vertex subtask counts

    @property
    def upstream(self) -> Tuple[int, ...]:
        return tuple(sorted({s for s, d in self.edges if d == self.vertex}))

    @property
    def downstream(self) -> Tuple[int, ...]:
        return tuple(sorted({d for s, d in self.edges if s == self.vertex}))

    @functools.cached_property
    def _dist(self) -> np.ndarray:
        return compute_distances(self.num_vertices, self.edges)

    def distances(self) -> np.ndarray:
        return self._dist

    def sharing_mask(self, sharing_depth: int) -> np.ndarray:
        """bool[num_vertices, num_vertices]: mask[owner, holder] == True iff
        ``holder`` replicates ``owner``'s determinant log — holders are
        *downstream* of owners within the depth cut (reference
        JobCausalLogImpl.respondToDeterminantRequest:192 enforces the same
        cut on the response path). depth == -1 means full sharing (reference
        ExecutionConfig default). Used to mask the step-boundary replication
        collective."""
        dist = self._dist
        mask = dist != UNREACHABLE
        if sharing_depth >= 0:
            mask = mask & (dist <= sharing_depth)
        mask = mask.copy()
        np.fill_diagonal(mask, True)  # every task holds its own log
        return mask

    def logs_to_replicate(self, sharing_depth: int) -> FrozenSet[int]:
        """Vertices whose causal logs this vertex must hold replicas of:
        the owners column of :meth:`sharing_mask` for this vertex."""
        mask = self.sharing_mask(sharing_depth)
        return frozenset(
            o for o in range(self.num_vertices)
            if o != self.vertex and mask[o, self.vertex])
