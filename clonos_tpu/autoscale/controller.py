"""AutoscaleController: fence-aligned evaluation, determinant-logged
decisions, replay-not-re-decide recovery.

The paper's rule for every nondeterministic control event — log it as
a determinant so replay is bit-identical — is what makes an AUTONOMOUS
scaling decision safe inside an exactly-once system. The controller
therefore:

- evaluates ONLY at completed (and drained) fences, through the pure
  :class:`~clonos_tpu.autoscale.policy.ScalePolicy`;
- logs every decision — holds included — as a ``SCALE`` determinant
  row (causal/determinant.py) into its own host-side append log,
  alongside a JSONL sidecar carrying the full signal snapshot the
  policy saw (the row pins the snapshot by crc32, sidecar discipline
  borrowed from SERIALIZABLE);
- on recovery, REPLAYS the log instead of re-deciding: the policy is
  re-run over the logged snapshots and every reproduced decision must
  equal its logged row byte-for-byte (a divergence means the log or
  the policy changed underfoot — refuse loudly); fences already in the
  log return the logged decision without re-executing, so a worker
  kill mid-cooldown can never trigger a second re-cut.

The SCALE rows deliberately live in a controller-owned log, NOT in any
task's device determinant stream: epoch seal digests cover task
determinant windows, and rows only the autoscaled run has would make
the byte-exact audit diff against the fault-free control twin diverge
by construction.

Execution is delegated through injected callbacks (worker re-cuts ride
the PR 15 fence→drain→migrate→redirect path via
``ClusterRunner.rescale_live``; replica changes ride
``ServeTier.add_replica``/``drop_replica``) so the controller itself
stays jax-free and conformance can drive it with fakes.
``transition_observers`` fire ``fn(kind, **fields)`` on every
protocol-visible step (observe/fence/decide/log/execute/refuse),
the PR 10/PR 15 conformance pattern.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from clonos_tpu.autoscale.policy import (ACTION_CODES, HOLD,
                                         SCALE_REPLICAS, SCALE_WORKERS,
                                         PolicyState, ScaleDecision,
                                         ScalePolicy)
from clonos_tpu.autoscale.signals import ScaleSignals
from clonos_tpu.causal import determinant as det


def decision_row(decision: ScaleDecision) -> det.ScaleDeterminant:
    """The packed-row view of one decision. ``record_count`` carries
    the sequence number (nonzero — a SCALE row can never alias a sync
    anchor); the single target lane carries whichever dimension the
    action moves (workers for hold/scale-workers, replicas for
    scale-replicas)."""
    target = (decision.target_replicas
              if decision.action == SCALE_REPLICAS
              else decision.target_workers)
    return det.ScaleDeterminant(
        record_count=decision.seq, epoch=decision.epoch,
        action=ACTION_CODES[decision.action], delta=decision.delta,
        target=target, signal_crc=decision.signal_crc)


class DecisionLog:
    """Append-only SCALE determinant log + JSONL signal sidecar.

    ``path=None`` keeps both in memory (unit tests, conformance).
    On-disk layout: ``<path>`` holds contiguous packed rows
    (``determinant.to_bytes`` encoding — the byte-identity the tests
    compare), ``<path>.signals.jsonl`` one record per decision with the
    canonical signal snapshot and the decision dict. Both loads are
    tail-tolerant (a torn final row / line is dropped), the repo-wide
    append-log convention.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.rows: List[np.ndarray] = []
        self.records: List[Dict[str, Any]] = []   # {"signals":…, "decision":…}
        self._sidecar_writer = None
        if path is not None:
            from clonos_tpu.utils.jsonl import JsonlAppender
            self._sidecar_writer = JsonlAppender(self.sidecar_path,
                                                 sort_keys=True)
            if os.path.exists(path):
                self._load()

    @property
    def sidecar_path(self) -> Optional[str]:
        return None if self.path is None else self.path + ".signals.jsonl"

    def _load(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        whole = len(data) - len(data) % det.ROW_BYTES
        self.rows = list(det.from_bytes(data[:whole]))
        self.records = []
        if os.path.exists(self.sidecar_path):
            # Shared torn-tail discipline (utils/jsonl): a SIGKILLed
            # writer's torn final line is dropped; mid-file junk raises
            # naming file:line instead of silently truncating replay.
            from clonos_tpu.utils.jsonl import read_jsonl
            self.records = read_jsonl(self.sidecar_path,
                                      label=self.sidecar_path)
        if len(self.records) < len(self.rows):
            # a torn sidecar invalidates replay for the rows past it —
            # truncate to the shorter prefix, both views must agree.
            self.rows = self.rows[:len(self.records)]
        self.records = self.records[:len(self.rows)]

    def append_scale_determinant(self, row: det.ScaleDeterminant,
                                 signals: ScaleSignals,
                                 decision: ScaleDecision) -> None:
        packed = row.pack()
        rec = {"signals": json.loads(signals.canonical()),
               "decision": decision.to_dict()}
        self.rows.append(packed)
        self.records.append(rec)
        if self.path is not None:
            with open(self.path, "ab") as f:
                f.write(det.to_bytes(packed.reshape(1, -1)))
            # Sidecar rides the shared durable appender (utils/jsonl):
            # same flush-per-record policy as every other JSONL log.
            self._sidecar_writer.append(rec)

    def determinants(self) -> List[det.ScaleDeterminant]:
        return [det.Determinant.unpack(r) for r in self.rows]

    def to_bytes(self) -> bytes:
        if not self.rows:
            return b""
        return det.to_bytes(np.stack(self.rows))

    def digest(self) -> str:
        return hashlib.blake2b(self.to_bytes(), digest_size=8).hexdigest()

    def __len__(self) -> int:
        return len(self.rows)


class AutoscaleController:
    """Closes the loop: signals in, logged decision out, re-cut at the
    fence. See module docstring for the protocol; the step methods
    (``observe`` → ``note_fence`` → ``decide`` → ``execute``) mirror
    the ScalePolicyModel actions one-to-one for conformance, and
    ``on_fence`` bundles them for the soak driver."""

    def __init__(self, policy: Optional[ScalePolicy] = None, *,
                 log: Optional[DecisionLog] = None,
                 execute_workers: Optional[Callable[[int], Any]] = None,
                 add_replica: Optional[Callable[[], Any]] = None,
                 drop_replica: Optional[Callable[[], Any]] = None,
                 healthy: Optional[Callable[[], bool]] = None):
        self.policy = policy if policy is not None else ScalePolicy()
        # identity check, not truthiness: an empty DecisionLog is falsy
        self.log = log if log is not None else DecisionLog()
        self._execute_workers = execute_workers
        self._add_replica = add_replica
        self._drop_replica = drop_replica
        self._healthy = healthy or (lambda: True)
        self.transition_observers: List[Callable[..., None]] = []
        self.state = PolicyState()
        self.pending: Optional[ScaleDecision] = None
        self._signals: Optional[ScaleSignals] = None
        self._fence: int = -1
        self._logged_by_epoch: Dict[int, ScaleDecision] = {}
        # counters surfaced as autoscale.* gauges
        self.decisions_total = 0
        self.rescales_executed = 0
        self.replicas_added = 0
        self.replicas_dropped = 0
        self.replayed_decisions = 0
        self.refusals = 0
        if len(self.log):
            self._replay_log()

    def bind(self, *, execute_workers=None, add_replica=None,
             drop_replica=None, healthy=None) -> "AutoscaleController":
        """Late-bind execution callbacks (the soak driver builds its
        harness after the controller exists). Only non-None arguments
        replace the current binding; returns self for chaining."""
        if execute_workers is not None:
            self._execute_workers = execute_workers
        if add_replica is not None:
            self._add_replica = add_replica
        if drop_replica is not None:
            self._drop_replica = drop_replica
        if healthy is not None:
            self._healthy = healthy
        return self

    # --- recovery: replay the log, never re-decide ---------------------------

    def _replay_log(self) -> None:
        """Rebuild PolicyState by re-running the pure policy over the
        logged signal snapshots, proving each reproduced decision equals
        its logged row bit-for-bit along the way."""
        st = PolicyState()
        for i, (row, rec) in enumerate(zip(self.log.rows,
                                           self.log.records)):
            logged = det.Determinant.unpack(row)
            sig = ScaleSignals.from_dict(rec["signals"])
            if sig.crc() != logged.signal_crc:
                self._signal_conformance(i, "signal-crc-pin")
                raise ValueError(
                    f"decision log entry {i}: signal sidecar fails its "
                    f"crc pin (crc {sig.crc():#x} != logged "
                    f"{logged.signal_crc:#x})")
            dec, st = self.policy.decide(sig, st)
            if not np.array_equal(decision_row(dec).pack(), row):
                self._signal_conformance(i, "decision-replay")
                raise ValueError(
                    f"decision log entry {i} does not replay "
                    f"bit-identically: policy now yields {dec}")
            self._logged_by_epoch[dec.epoch] = dec
        self.state = st

    @staticmethod
    def _signal_conformance(entry: int, check: str) -> None:
        """Replay-not-re-decide broke: capture a bundle before the
        raise tears the process down (no-op when disabled)."""
        from clonos_tpu.obs.incident import get_incidents
        get_incidents().signal("conformance.mismatch",
                               source="decision-log-replay",
                               entry=entry, check=check)

    # --- protocol steps (model-action aligned) -------------------------------

    def _observe_hooks(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    def observe(self, signals: ScaleSignals) -> None:
        """Take this fence's signal snapshot (model action: signal)."""
        self._signals = signals
        self._observe_hooks("observe", epoch=signals.epoch,
                            load=signals.load)

    def note_fence(self, epoch: int) -> None:
        """A fence completed and drained (model action: fence)."""
        self._fence = int(epoch)
        self._observe_hooks("fence", epoch=int(epoch))

    def decide(self) -> ScaleDecision:
        """Evaluate the policy on the last observed snapshot (model
        action: decide). Fences already in the log return the LOGGED
        decision — no policy call, no append, no pending execution."""
        if self._signals is None:
            raise RuntimeError("decide() before observe()")
        s = self._signals
        replayed = self._logged_by_epoch.get(s.epoch)
        if replayed is not None:
            self.replayed_decisions += 1
            self._observe_hooks("decide", epoch=replayed.epoch,
                                action=replayed.action,
                                seq=replayed.seq, replayed=True)
            return replayed
        decision, self.state = self.policy.decide(s, self.state)
        self.decisions_total += 1
        self.log.append_scale_determinant(decision_row(decision), s,
                                          decision)
        self._logged_by_epoch[decision.epoch] = decision
        self._observe_hooks("decide", epoch=decision.epoch,
                            action=decision.action, seq=decision.seq,
                            replayed=False)
        self._observe_hooks("log", seq=decision.seq)
        from clonos_tpu.obs import get_timeline
        tl = get_timeline()
        if tl.enabled:
            tl.record("scale.decision", epoch=decision.epoch,
                      action=decision.action, seq=decision.seq,
                      reason=decision.reason, signal_crc=s.crc())
        if decision.scales:
            self.pending = decision
        return decision

    def execute(self) -> Optional[ScaleDecision]:
        """Carry out the pending scale action, if still safe (model
        action: execute). Health is re-checked HERE, not just at decide
        time — a failure can land between the two, and executing a
        re-cut over an in-progress recovery is the seeded
        ``rescale-mid-recovery`` bug the model proves fatal."""
        if self.pending is None:
            return None
        decision, self.pending = self.pending, None
        if not self._healthy():
            self.refusals += 1
            self._observe_hooks("refuse", epoch=decision.epoch,
                                action=decision.action)
            return None
        if decision.action == SCALE_WORKERS:
            if self._execute_workers is not None:
                self._execute_workers(decision.target_workers)
            self.rescales_executed += 1
        elif decision.action == SCALE_REPLICAS:
            if decision.delta > 0:
                if self._add_replica is not None:
                    self._add_replica()
                self.replicas_added += 1
            else:
                if self._drop_replica is not None:
                    self._drop_replica()
                self.replicas_dropped += 1
        self._observe_hooks("execute", epoch=decision.epoch,
                            action=decision.action,
                            target=(decision.target_replicas
                                    if decision.action == SCALE_REPLICAS
                                    else decision.target_workers))
        return decision

    def on_fence(self, epoch: int, signals: ScaleSignals
                 ) -> Tuple[ScaleDecision, Optional[ScaleDecision]]:
        """The soak driver's one call per completed fence: observe,
        note the fence, decide, execute. Returns (decision, executed)
        where executed is None for holds / replays / refusals."""
        self.observe(signals)
        self.note_fence(epoch)
        decision = self.decide()
        executed = self.execute()
        return decision, executed

    # --- observability -------------------------------------------------------

    def register_gauges(self, registry, *,
                        actual_workers: Callable[[], int] = None,
                        actual_replicas: Callable[[], int] = None
                        ) -> None:
        """``autoscale.*`` gauges into a MetricRegistry — ride the same
        heartbeat piggyback / ``cluster_metrics()`` rollup the signal
        plane samples from, and render as ``clonos_tpu top``'s
        autoscale: row."""
        g = registry.group("autoscale")
        g.gauge("decisions-total", lambda: self.decisions_total)
        g.gauge("rescales-executed", lambda: self.rescales_executed)
        g.gauge("replicas-added", lambda: self.replicas_added)
        g.gauge("replicas-dropped", lambda: self.replicas_dropped)
        g.gauge("replayed-decisions", lambda: self.replayed_decisions)
        g.gauge("cooldown-active", lambda: self.state.cooldown)
        g.gauge("last-action",
                lambda: ACTION_CODES.get(self.state.last_action, 0))
        g.gauge("target-workers", lambda: self._last_target("workers"))
        g.gauge("target-replicas", lambda: self._last_target("replicas"))
        if actual_workers is not None:
            g.gauge("actual-workers", actual_workers)
        if actual_replicas is not None:
            g.gauge("actual-replicas", actual_replicas)

    def _last_target(self, dim: str) -> int:
        for rec in reversed(self.log.records):
            return int(rec["decision"]["target_" + dim])
        return 0

    def last_decision(self) -> Optional[ScaleDecision]:
        for rec in reversed(self.log.records):
            return ScaleDecision(**rec["decision"])
        return None
