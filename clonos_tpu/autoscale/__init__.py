"""Deterministic autoscaler: the policy engine that closes the loop on
``rescale_live`` and the serve tier.

Three layers (ISSUE 16 / ROADMAP "Autoscaling"):

- :mod:`autoscale.signals` — rolling-window aggregation of the metrics
  the system already exports into a typed, quantized ``ScaleSignals``
  snapshot per completed fence;
- :mod:`autoscale.policy` — a pure, deterministic
  ``ScalePolicy.decide(signals, state)`` with hysteresis, sustain
  windows, cooldowns, and bounded step size;
- :mod:`autoscale.controller` — fence-aligned evaluation that logs
  every decision as a ``SCALE`` determinant (plus the signal snapshot
  it saw) BEFORE acting, so recovery replays decisions bit-identically
  instead of re-deciding, and executes re-cuts through the PR 15
  fence→drain→migrate→redirect path.

Design-first verification lives in verify/models.ScalePolicyModel (the
sixth model) with conformance replay through the real controller.
"""

from clonos_tpu.autoscale.signals import (DEFAULT_WINDOW,  # noqa: F401
                                          ScaleSignals, SignalAggregator,
                                          signals_for_level)
from clonos_tpu.autoscale.policy import (ACTION_CODES, HOLD,  # noqa: F401
                                         SCALE_REPLICAS, SCALE_WORKERS,
                                         PolicyConfig, PolicyState,
                                         ScaleDecision, ScalePolicy)
from clonos_tpu.autoscale.controller import (AutoscaleController,  # noqa: F401
                                             DecisionLog, decision_row)

__all__ = [
    "DEFAULT_WINDOW", "ScaleSignals", "SignalAggregator",
    "signals_for_level", "ACTION_CODES", "HOLD", "SCALE_REPLICAS",
    "SCALE_WORKERS", "PolicyConfig", "PolicyState", "ScaleDecision",
    "ScalePolicy", "AutoscaleController", "DecisionLog", "decision_row",
]
