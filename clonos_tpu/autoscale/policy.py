"""Pure deterministic scaling policy: ``decide(signals, state)``.

No clocks, no I/O, no jax — the same (signals, state) pair ALWAYS
yields the same (decision, state') pair, which is what makes a logged
decision replayable bit-for-bit (controller.py) and the policy
explorable at small bounds (verify/models.ScalePolicyModel — the model
IS this function at abstract load levels; conformance replays model
traces through the real thing).

Three disciplines keep the loop stable:

- **hysteresis** — scale-up and scale-down trigger on different
  thresholds (``high_load`` / ``low_load``) with a dead band between
  them where streaks reset;
- **sustain** — a threshold crossing must persist ``sustain_fences``
  consecutive fences before it counts (one noisy fence is not a trend);
- **cooldown** — after any scale action, ``cooldown_fences`` fences
  must complete before the next one (the system needs time to show the
  effect of the last action before being judged again).

Priority when multiple arms fire: health > cooldown > worker scale-up
> replica add > worker scale-down > replica drop > hold. An unhealthy
cluster (failed subtask, unfenced epoch) always holds — rescaling over
an in-progress recovery is the one thing the exactly-once machinery
cannot absorb (``rescale_live`` refuses it too; the policy refusing
first keeps the refusal out of the hot path).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from clonos_tpu.autoscale.signals import ScaleSignals

# decision actions
HOLD = "hold"
SCALE_WORKERS = "scale-workers"
SCALE_REPLICAS = "scale-replicas"

#: action string <-> SCALE determinant row code (causal/determinant.py)
ACTION_CODES = {HOLD: 0, SCALE_WORKERS: 1, SCALE_REPLICAS: 2}
CODE_ACTIONS = {v: k for k, v in ACTION_CODES.items()}


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    high_load: float = 1.25      # sustained offered/achieved above: up
    low_load: float = 0.55       # sustained below: down (hysteresis band)
    sustain_fences: int = 2      # consecutive fences a signal must hold
    cooldown_fences: int = 3     # fences between scale actions
    max_step: int = 1            # bounded step size per action
    min_workers: int = 1
    max_workers: int = 8
    staleness_high: int = 2      # replica lag (epochs) that adds a replica
    read_p99_high_ms: float = 50.0
    min_replicas: int = 1
    max_replicas: int = 4

    def __post_init__(self):
        if self.min_workers < 1 or self.max_workers < self.min_workers:
            raise ValueError("worker bounds must satisfy "
                             "1 <= min_workers <= max_workers")
        if self.max_step < 1 or self.sustain_fences < 1:
            raise ValueError("max_step and sustain_fences must be >= 1")
        if self.low_load >= self.high_load:
            raise ValueError("hysteresis requires low_load < high_load")


@dataclasses.dataclass(frozen=True)
class PolicyState:
    """Everything the policy carries between fences. Reconstructable
    from the decision log (controller.py replays the log through
    ``decide`` to rebuild it — no hidden state)."""

    cooldown: int = 0        # fences left before the next action allowed
    over_streak: int = 0     # consecutive fences with load >= high_load
    under_streak: int = 0    # consecutive fences with load <= low_load
    stale_streak: int = 0    # consecutive fences with read tier lagging
    seq: int = 0             # decisions issued so far
    last_action: str = HOLD
    last_epoch: int = -1


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    epoch: int
    seq: int                 # 1-based decision sequence number
    action: str              # HOLD | SCALE_WORKERS | SCALE_REPLICAS
    delta: int = 0           # signed step; 0 for hold
    target_workers: int = 0
    target_replicas: int = 0
    reason: str = ""
    signal_crc: int = 0

    @property
    def scales(self) -> bool:
        return self.action != HOLD

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ScalePolicy:
    """The deterministic decision function. Stateless — all memory
    lives in the :class:`PolicyState` threaded through ``decide``."""

    def __init__(self, config: PolicyConfig = None):
        self.cfg = config or PolicyConfig()

    def decide(self, s: ScaleSignals,
               st: PolicyState) -> Tuple[ScaleDecision, PolicyState]:
        cfg = self.cfg
        # Fold this fence's signals into the streaks; hysteresis dead
        # band (low_load < load < high_load) resets both rate streaks.
        over = st.over_streak + 1 if s.load >= cfg.high_load else 0
        under = st.under_streak + 1 if s.load <= cfg.low_load else 0
        lagging = (s.max_staleness > cfg.staleness_high
                   or s.p99_read_ms > cfg.read_p99_high_ms)
        stale = st.stale_streak + 1 if lagging else 0
        cooldown = max(0, st.cooldown - 1)
        seq = st.seq + 1
        # A cluster with a sustained gray suspect is unhealthy too: a
        # re-cut would assign key groups to a worker already diagnosed
        # as limping (obs/detect.py feeds gray_suspects).
        healthy = (s.failed_subtasks == 0 and not s.unfenced
                   and s.gray_suspects == 0)

        action, delta, tgt_w, tgt_r, reason = (
            HOLD, 0, s.workers, s.replicas_total, "steady")
        if not healthy:
            reason = ("gray-suspect" if s.gray_suspects
                      and s.failed_subtasks == 0 and not s.unfenced
                      else "unhealthy")
        elif cooldown > 0:
            reason = "cooldown"
        elif over >= cfg.sustain_fences and s.workers < cfg.max_workers:
            delta = min(cfg.max_step, cfg.max_workers - s.workers)
            action, tgt_w = SCALE_WORKERS, s.workers + delta
            reason = "sustained-high-load"
        elif stale >= cfg.sustain_fences \
                and s.replicas_total < cfg.max_replicas:
            delta = 1
            action, tgt_r = SCALE_REPLICAS, s.replicas_total + 1
            reason = "read-tier-lagging"
        elif under >= cfg.sustain_fences and s.workers > cfg.min_workers:
            delta = -min(cfg.max_step, s.workers - cfg.min_workers)
            action, tgt_w = SCALE_WORKERS, s.workers + delta
            reason = "sustained-low-load"
        elif under >= cfg.sustain_fences \
                and s.replicas_total > cfg.min_replicas:
            delta = -1
            action, tgt_r = SCALE_REPLICAS, s.replicas_total - 1
            reason = "read-tier-idle"

        if action != HOLD:
            # the world is about to change: restart the cooldown clock
            # and every streak — post-action signals are a new trend.
            cooldown = cfg.cooldown_fences
            over = under = stale = 0
        decision = ScaleDecision(
            epoch=s.epoch, seq=seq, action=action, delta=delta,
            target_workers=tgt_w, target_replicas=tgt_r,
            reason=reason, signal_crc=s.crc())
        new_state = PolicyState(
            cooldown=cooldown, over_streak=over, under_streak=under,
            stale_streak=stale, seq=seq, last_action=action,
            last_epoch=s.epoch)
        return decision, new_state
