"""Signal plane: one typed snapshot of cluster load per completed fence.

The autoscaler never reads raw device state — it samples the same
metric rollup every other observer uses (the HEARTBEAT piggyback /
``cluster_metrics()`` snapshot, ``clonos_tpu top``'s input) and distills
it into a :class:`ScaleSignals` row: offered vs achieved throughput,
in-flight ring occupancy, read-tier staleness and p99, per-shard
health. A rolling window smooths the rate ratio so one noisy fence
cannot trip the policy; everything is quantized to fixed decimals so
the snapshot has ONE canonical byte encoding — its crc32 is what the
logged ``SCALE`` determinant pins, and what replay integrity checks
against (autoscale/controller.py).
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

#: rolling window length, in completed fences, for the load ratio.
DEFAULT_WINDOW = 4


def _pick(snap: Dict[str, Any], name: str, default: float = 0.0) -> float:
    """Fetch a metric by suffix from a registry snapshot: scopes prefix
    the name (``soak.rate``, ``job.<name>.backpressure...``), so match
    the un-scoped suffix the way ``clonos_tpu top`` does. Non-numeric
    values (gauge errors surface as strings) fall back to the default."""
    for key in (name,):
        if key in snap and isinstance(snap[key], (int, float)):
            return float(snap[key])
    suffix = "." + name
    for key, val in snap.items():
        if key.endswith(suffix) and isinstance(val, (int, float)):
            return float(val)
    return default


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """What the policy saw at one completed fence. Pure data, fully
    quantized — equal snapshots encode to equal bytes."""

    epoch: int = 0              # the fence this snapshot describes
    load: float = 0.0           # offered / achieved rate, window-smoothed
    backlog_chunks: int = 0     # token-bucket chunks behind schedule
    ring_occupancy: float = 0.0  # in-flight ring fill fraction [0, 1]
    p99_read_ms: float = 0.0    # serve-tier read latency
    max_staleness: int = 0      # worst replica staleness, epochs
    replicas_alive: int = 0
    replicas_total: int = 0
    workers: int = 0            # current keyed parallelism
    failed_subtasks: int = 0    # per-shard health: nonzero = mid-recovery
    unfenced: bool = False      # epoch tail not yet drained at sampling
    gray_suspects: int = 0      # sustained gray-failure suspects
    #                             (obs/detect.py); nonzero = unhealthy

    def canonical(self) -> bytes:
        """The one byte encoding (sorted-key JSON) the crc covers."""
        return json.dumps(dataclasses.asdict(self),
                          sort_keys=True).encode()

    def crc(self) -> int:
        return zlib.crc32(self.canonical())

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScaleSignals":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


class SignalAggregator:
    """Rolling-window smoothing over per-fence metric snapshots.

    ``sample_from`` takes the registry snapshot plus the few facts the
    registry does not carry (current parallelism, failed set size,
    fence-drain status) and returns the quantized :class:`ScaleSignals`.
    The load ratio is averaged over the last ``window`` fences; all
    other signals are instantaneous — staleness and health must not be
    smoothed or the policy would rescale on stale facts.
    """

    def __init__(self, window: int = DEFAULT_WINDOW):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self._ratios: Deque[float] = deque(maxlen=self.window)
        self.last: Optional[ScaleSignals] = None

    def sample_from(self, snap: Dict[str, Any], *, epoch: int,
                    workers: int, failed_subtasks: int = 0,
                    unfenced: bool = False,
                    gray_suspects: int = 0) -> ScaleSignals:
        offered = _pick(snap, "offered-rate",
                        _pick(snap, "target-rate"))
        achieved = _pick(snap, "rate")
        ratio = offered / achieved if achieved > 0.0 else (
            0.0 if offered <= 0.0 else float(self.window))
        self._ratios.append(min(ratio, 100.0))
        load = round(sum(self._ratios) / len(self._ratios), 2)
        staleness = [
            v for k, v in snap.items()
            if k.endswith(".staleness-epochs")
            and isinstance(v, (int, float))]
        sig = ScaleSignals(
            epoch=int(epoch),
            load=load,
            backlog_chunks=int(_pick(snap, "backlog-chunks")),
            ring_occupancy=round(
                _pick(snap, "backpressure.inflight-occupancy"), 3),
            p99_read_ms=round(_pick(snap, "p99-read-ms"), 3),
            max_staleness=int(max(staleness)) if staleness else 0,
            replicas_alive=int(_pick(snap, "replicas-alive")),
            replicas_total=len(staleness),
            workers=int(workers),
            failed_subtasks=int(failed_subtasks),
            unfenced=bool(unfenced),
            gray_suspects=int(gray_suspects),
        )
        self.last = sig
        return sig

    def reset(self) -> None:
        self._ratios.clear()


def signals_for_level(level: int, *, epoch: int, workers: int,
                      failed_subtasks: int = 0,
                      replicas: int = 1) -> ScaleSignals:
    """Synthesize a snapshot for an abstract model load level (0 low,
    1 steady, 2 high) — the verify/conformance bridge between
    ``ScalePolicyModel`` traces and the real controller. The values are
    chosen to sit squarely past the default hysteresis thresholds."""
    load = {0: 0.4, 1: 1.0, 2: 1.6}[int(level)]
    return ScaleSignals(epoch=int(epoch), load=load,
                        replicas_alive=int(replicas),
                        replicas_total=int(replicas),
                        workers=int(workers),
                        failed_subtasks=int(failed_subtasks))
