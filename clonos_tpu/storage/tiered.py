"""TieredEpochStore: host staging buffer + immutable disk segments.

One store owns the spilled sealed epochs of ONE log (an in-flight ring
vertex or the stacked determinant logs). Tiers and movement:

- **host tier**: the staging buffer. ``put`` accepts device arrays and
  returns immediately — the device→host copy (``np.asarray``) runs on
  the background writer thread, overlapped with the next epoch's
  compute. Sealed epochs are immutable, so the staged copy is final.
- **disk tier**: one checksummed segment file per epoch (storage/
  segment.py) plus a JSONL segment index under the shared torn-tail
  convention (utils/jsonl.py) — a SIGKILLed writer leaves at most one
  torn index line, which the reader drops; the segment it described is
  simply re-spilled or already covered by the host tier. Once a
  segment is durable, host copies beyond ``host_budget_epochs`` demote
  to disk-only (the budget bounds host DRAM like the ring bounds HBM).
- **refill**: ``load_epoch`` serves host hits without I/O; disk hits
  re-hash the segment against the indexed checksum and refuse torn
  bytes loudly (:class:`SegmentCorruptError` → recovery surfaces a
  labeled error instead of replaying garbage).

The writer is double-buffered by construction: the bounded queue lets
the fence stage epoch N+1 while the thread is still flushing epoch N;
``drain`` joins the queue for tests/shutdown. Spill and refill time is
attributed to the profiler's ``ft`` sections (``spill-write``,
``refill``) so ``bench --ablate`` prices the tiers, and the bandwidth
counters feed the ``spill.*`` gauges ``clonos_tpu top`` renders.

Audit composition: sealed epochs are already digest-chained into the
audit ledger at the fence (obs/audit.py). ``attach_digest`` records the
ledger digest in the segment index, so a spilled epoch carries the same
fingerprint the ledger pinned — ``diff_ledgers`` verifies spill/refill
round-trips for free.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

from clonos_tpu.storage.segment import read_segment, write_segment
from clonos_tpu.utils.jsonl import read_jsonl


class StorageError(RuntimeError):
    """Tiered-store failure a caller must not paper over (missing
    epoch, corrupt segment, unusable index)."""


class _Epoch:
    """One sealed epoch's residency record across the tiers."""

    __slots__ = ("start", "arrays", "path", "nbytes", "checksum",
                 "digest", "host_bytes")

    def __init__(self, start: int, arrays: Optional[Dict[str, Any]]):
        self.start = int(start)
        self.arrays = arrays          # host/device copy (None = disk-only)
        self.path: Optional[str] = None
        self.nbytes = 0               # serialized segment payload bytes
        self.checksum: Optional[str] = None
        self.digest: Optional[str] = None   # audit-ledger digest
        self.host_bytes = 0


def _arrays_nbytes(arrays: Mapping[str, Any]) -> int:
    total = 0
    for v in arrays.values():
        nb = getattr(v, "nbytes", None)
        if nb is None:
            v = np.asarray(v)
            nb = v.nbytes
        total += int(nb)
    return total


class TieredEpochStore:
    """Host-buffer + disk-segment owner of one log's spilled epochs."""

    def __init__(self, spool_dir: Optional[str], name: str,
                 durable: bool = True,
                 host_budget_epochs: Optional[int] = 2):
        self.name = name
        self.spool_dir = spool_dir
        self.durable = durable and spool_dir is not None
        self.host_budget_epochs = host_budget_epochs
        #: chaos hook (soak `stall` fault): per-segment-write sleep
        self.write_delay_s = 0.0
        if spool_dir:
            os.makedirs(spool_dir, exist_ok=True)
        self._epochs: Dict[int, _Epoch] = {}
        self._lock = threading.Lock()
        # Bandwidth/occupancy counters (spill.* gauges; bench --spill).
        self.bytes_spilled = 0
        self.bytes_refilled = 0
        self.spill_seconds = 0.0
        self.refill_seconds = 0.0
        self.segments_written = 0
        self.host_hits = 0
        self.disk_hits = 0
        self._writer_queue: "queue.Queue" = queue.Queue()
        self._writer = threading.Thread(target=self._writer_loop,
                                        daemon=True)
        self._writer.start()

    # --- paths ---------------------------------------------------------------

    def segment_path(self, epoch: int) -> str:
        return os.path.join(self.spool_dir, f"{self.name}_epoch{epoch}.seg")

    def index_path(self) -> str:
        return os.path.join(self.spool_dir, f"{self.name}.index.jsonl")

    def _label(self, epoch: int) -> str:
        return f"{self.name}:epoch{epoch}"

    # --- hot-path API --------------------------------------------------------

    def put(self, epoch: int, start: int,
            arrays: Mapping[str, Any]) -> None:
        """Accept one sealed epoch into the host tier and schedule its
        segment write. ``arrays`` may be device arrays — the d2h copy
        happens on the writer thread, off the critical path."""
        ep = _Epoch(start, dict(arrays))
        ep.host_bytes = _arrays_nbytes(ep.arrays)
        with self._lock:
            self._epochs[epoch] = ep
        if self.durable:
            self._writer_queue.put(("write", epoch))

    def attach_digest(self, epoch: int, digest: str) -> None:
        """Record the audit ledger's digest for a spilled epoch; the
        index entry lands via the writer thread (no fence-path I/O)."""
        with self._lock:
            ep = self._epochs.get(epoch)
            if ep is None:
                return
            ep.digest = digest
        if self.durable:
            self._writer_queue.put(("digest", epoch))

    def truncate(self, through_epoch: int) -> None:
        """Checkpoint complete: drop epochs <= ``through_epoch`` from
        every tier. Already-durable segments unlink synchronously (the
        checkpoint owns the data now; callers observe the files gone);
        epochs whose writes are still queued are handled by the queued
        truncate command — the writer re-checks residency before
        writing, and the command, ordered after every pending write,
        sweeps any segment that slipped through the check."""
        with self._lock:
            dead = [e for e in self._epochs if e <= through_epoch]
            paths = [self._epochs[e].path for e in dead
                     if self._epochs[e].path is not None]
            for e in dead:
                del self._epochs[e]
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
        if self.durable and dead:
            self._writer_queue.put(("truncate", through_epoch))

    def retained_epochs(self) -> List[int]:
        with self._lock:
            return sorted(self._epochs)

    def epoch_digest(self, epoch: int) -> Optional[str]:
        with self._lock:
            ep = self._epochs.get(epoch)
            return ep.digest if ep is not None else None

    # --- refill --------------------------------------------------------------

    def load_epoch(self, epoch: int) -> Tuple[int, Dict[str, np.ndarray]]:
        """One epoch back from whichever tier holds it: host tier is a
        lock-held dict read; disk tier re-hashes the segment against
        the indexed checksum before trusting a byte."""
        with self._lock:
            ep = self._epochs.get(epoch)
            if ep is None:
                raise StorageError(
                    f"{self._label(epoch)}: epoch not retained by any "
                    f"tier (truncated or never spilled)")
            if ep.arrays is not None:
                self.host_hits += 1
                return ep.start, {k: np.asarray(v)
                                  for k, v in ep.arrays.items()}
            path, checksum = ep.path, ep.checksum
        if path is None:
            raise StorageError(
                f"{self._label(epoch)}: epoch resident in no tier "
                f"(host copy dropped before its segment was durable)")
        t0 = time.monotonic()
        start, arrays = read_segment(path, checksum, self._label(epoch))
        dur = time.monotonic() - t0
        with self._lock:
            self.disk_hits += 1
            self.refill_seconds += dur
            self.bytes_refilled += sum(a.nbytes for a in arrays.values())
        from clonos_tpu.obs import get_profiler
        get_profiler().observe("refill", dur)
        return start, arrays

    # --- background writer ---------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._writer_queue.get()
            try:
                if item is None:
                    return
                kind, arg = item
                if kind == "write":
                    self._write_one(arg)
                elif kind == "digest":
                    self._index_digest(arg)
                elif kind == "truncate":
                    self._truncate_disk(arg)
            except Exception:
                # The thread must survive a poisoned command: its death
                # would deadlock every future drain() and silently stop
                # all spilling. The epoch keeps its host copy (put()
                # staged it), so replay still works; durability for
                # THIS epoch is lost, which load_epoch reports if the
                # host copy is ever dropped.
                pass
            finally:
                self._writer_queue.task_done()

    def _write_one(self, epoch: int) -> None:
        with self._lock:
            ep = self._epochs.get(epoch)
            if ep is None or ep.arrays is None:
                return                 # truncated while queued
            staged = ep.arrays
            digest = ep.digest
        # d2h materialization + serialization off the critical path.
        arrays = {k: np.asarray(v) for k, v in staged.items()}
        if self.write_delay_s:
            time.sleep(self.write_delay_s)      # chaos `stall` fault
        t0 = time.monotonic()
        try:
            nbytes, checksum = write_segment(
                self.segment_path(epoch), ep.start, arrays)
            self._index_append({
                "kind": "segment", "epoch": epoch, "start": ep.start,
                "file": os.path.basename(self.segment_path(epoch)),
                "blake2b": checksum, "bytes": nbytes,
                "digest": digest,
            })
        except OSError:
            # Flush failure: keep the host copy so replay still works
            # (the reference keeps the buffer on flush failure) — but
            # materialized, so the device buffer is released either way.
            with self._lock:
                if epoch in self._epochs:
                    self._epochs[epoch].arrays = arrays
            return
        dur = time.monotonic() - t0
        with self._lock:
            cur = self._epochs.get(epoch)
            if cur is not None:
                cur.arrays = arrays     # host tier now holds np copies
                cur.path = self.segment_path(epoch)
                cur.nbytes = nbytes
                cur.checksum = checksum
            self.segments_written += 1
            self.bytes_spilled += nbytes
            self.spill_seconds += dur
            self._enforce_host_budget_locked()
        from clonos_tpu.obs import get_profiler
        get_profiler().observe("spill-write", dur)

    def _enforce_host_budget_locked(self) -> None:
        """Demote durable host copies beyond the budget to disk-only
        (oldest epochs first — refill wants the newest near)."""
        if self.host_budget_epochs is None:
            return
        resident = sorted(e for e, ep in self._epochs.items()
                          if ep.arrays is not None and ep.path is not None)
        excess = len(resident) - self.host_budget_epochs
        for e in resident[:max(excess, 0)]:
            self._epochs[e].arrays = None

    def _index_digest(self, epoch: int) -> None:
        with self._lock:
            ep = self._epochs.get(epoch)
            if ep is None or ep.path is None:
                return                 # write pending: digest rides it
            digest = ep.digest
        try:
            self._index_append({"kind": "digest", "epoch": epoch,
                                "digest": digest})
        except OSError:
            pass

    def _truncate_disk(self, through_epoch: int) -> None:
        for fn in list(os.listdir(self.spool_dir)):
            if not (fn.startswith(f"{self.name}_epoch")
                    and fn.endswith(".seg")):
                continue
            try:
                e = int(fn[len(f"{self.name}_epoch"):-len(".seg")])
            except ValueError:
                continue
            if e <= through_epoch:
                try:
                    os.remove(os.path.join(self.spool_dir, fn))
                except OSError:
                    pass
        # Record the truncation unconditionally: some segments were
        # already unlinked synchronously by truncate(), and open_index
        # must not resurrect their index entries.
        try:
            self._index_append({"kind": "truncate",
                                "through": through_epoch})
        except OSError:
            pass

    def _index_append(self, record: dict) -> None:
        import json
        line = json.dumps(record, sort_keys=True,
                          separators=(",", ":")) + "\n"
        with open(self.index_path(), "a") as f:
            f.write(line)
            f.flush()

    # --- occupancy / lifecycle -----------------------------------------------

    def occupancy(self) -> Dict[str, int]:
        """Tier residency right now (the spill.* occupancy gauges)."""
        with self._lock:
            host_e = sum(1 for ep in self._epochs.values()
                         if ep.arrays is not None)
            host_b = sum(ep.host_bytes for ep in self._epochs.values()
                         if ep.arrays is not None)
            disk_e = sum(1 for ep in self._epochs.values()
                         if ep.path is not None)
            disk_b = sum(ep.nbytes for ep in self._epochs.values()
                         if ep.path is not None)
        return {"host_epochs": host_e, "host_bytes": host_b,
                "disk_epochs": disk_e, "disk_bytes": disk_b}

    def stats(self) -> Dict[str, Any]:
        """Cumulative movement counters (bench --spill fields)."""
        with self._lock:
            return {
                "bytes_spilled": self.bytes_spilled,
                "bytes_refilled": self.bytes_refilled,
                "spill_seconds": round(self.spill_seconds, 6),
                "refill_seconds": round(self.refill_seconds, 6),
                "segments_written": self.segments_written,
                "host_hits": self.host_hits,
                "disk_hits": self.disk_hits,
            }

    def drain(self) -> None:
        """Block until every queued spill/index write is durable."""
        self._writer_queue.join()

    def close(self) -> None:
        self._writer_queue.put(None)

    # --- fresh-process refill ------------------------------------------------

    @classmethod
    def open_index(cls, spool_dir: str, name: str) -> "TieredEpochStore":
        """Rebuild a store's disk tier from its segment index in a fresh
        process (standby-host refill): replay the index records in
        order — tail-tolerantly, so a SIGKILLed writer's torn final line
        drops silently while earlier corruption raises the labeled
        error (utils/jsonl.py convention)."""
        store = cls(spool_dir, name)
        label = f"{name}-index"
        records = read_jsonl(store.index_path(), label=label)
        with store._lock:
            for rec in records:
                kind = rec.get("kind")
                if kind == "segment":
                    e = int(rec["epoch"])
                    ep = _Epoch(int(rec["start"]), None)
                    ep.path = os.path.join(spool_dir, rec["file"])
                    ep.checksum = rec.get("blake2b")
                    ep.nbytes = int(rec.get("bytes", 0))
                    ep.digest = rec.get("digest")
                    store._epochs[e] = ep
                elif kind == "digest":
                    ep = store._epochs.get(int(rec["epoch"]))
                    if ep is not None:
                        ep.digest = rec.get("digest")
                elif kind == "truncate":
                    thr = int(rec["through"])
                    for e in [e for e in store._epochs if e <= thr]:
                        del store._epochs[e]
        return store
