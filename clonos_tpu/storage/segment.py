"""Immutable checksummed disk segments: one sealed epoch, one file.

A segment is a raw tensor container (magic + JSON header + concatenated
C-order array bytes) carrying one sealed epoch of spilled state, written
once and never mutated — truncation deletes the file, exactly the
reference's per-epoch spill-file trick
(SpillableSubpartitionInFlightLogger.java:45). The format is
deliberately NOT npz: the writer thread shares cores with compute, and
zip containers pay a second checksum pass (CRC32) plus an assembly copy
per array — here the payload streams through one blake2b pass straight
to the file. Durability discipline:

- the blake2b is computed over the exact file bytes as they are
  written; the file lands via tmp + ``os.replace`` so a SIGKILLed
  writer leaves either the whole segment or nothing;
- refill re-hashes the file and compares against the checksum recorded
  in the segment index — a torn/truncated/bit-rotted segment surfaces
  as :class:`SegmentCorruptError` naming the file, never as silently
  wrong replay bytes (the audit ledger would catch those too, but only
  after the replay already ran).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Tuple

import numpy as np

#: hex chars of the segment checksum (blake2b-128, the audit plane's
#: digest width — obs/digest.py DIGEST_BYTES).
CHECKSUM_BYTES = 16

#: container magic; bump the digit on any layout change so a reader
#: from the future refuses old bytes loudly instead of misparsing.
MAGIC = b"CLSEG1\n"


class SegmentCorruptError(RuntimeError):
    """A segment file's bytes do not hash to its indexed checksum."""


def segment_checksum(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=CHECKSUM_BYTES).hexdigest()


def write_segment(path: str, start: int,
                  arrays: Dict[str, np.ndarray]) -> Tuple[int, str]:
    """Serialize one sealed epoch and atomically place it at ``path``.
    Returns ``(payload_bytes, checksum)`` for the segment index."""
    entries = []
    mats = []
    for k, v in arrays.items():
        a = np.ascontiguousarray(np.asarray(v))
        entries.append({"name": k, "dtype": a.dtype.str,
                        "shape": list(a.shape)})
        mats.append(a)
    header = json.dumps({"start": int(start), "arrays": entries},
                        separators=(",", ":")).encode("utf-8") + b"\n"
    chunks = [MAGIC, header]
    for m in mats:
        if m.size:                     # 0-size views refuse the cast
            chunks.append(memoryview(m).cast("B"))
    h = hashlib.blake2b(digest_size=CHECKSUM_BYTES)
    nbytes = 0
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        for chunk in chunks:
            h.update(chunk)
            f.write(chunk)
            nbytes += len(chunk)
    os.replace(tmp, path)
    return nbytes, h.hexdigest()


def read_segment(path: str, checksum: str,
                 label: str) -> Tuple[int, Dict[str, np.ndarray]]:
    """Read and verify one segment. ``label`` names the owning store +
    epoch in the corruption error (the torn-tail convention's
    ``<label>: ...`` shape, utils/jsonl.py)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise SegmentCorruptError(
            f"{label}: segment {path} unreadable ({e})")
    got = segment_checksum(data)
    if got != checksum:
        raise SegmentCorruptError(
            f"{label}: segment {path} checksum mismatch "
            f"(got {got}, index says {checksum}) — torn or corrupt "
            f"segment; refill refused")
    try:
        if not data.startswith(MAGIC):
            raise ValueError("bad magic")
        nl = data.index(b"\n", len(MAGIC))
        meta = json.loads(data[len(MAGIC):nl])
        off = nl + 1
        out: Dict[str, np.ndarray] = {}
        for ent in meta["arrays"]:
            dt = np.dtype(ent["dtype"])
            shape = tuple(int(s) for s in ent["shape"])
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            out[ent["name"]] = np.frombuffer(
                data, dtype=dt, count=count, offset=off).reshape(shape)
            off += count * dt.itemsize
        start = int(meta["start"])
    except (ValueError, KeyError, TypeError) as e:
        # The checksum matched, so the INDEX vouched for these bytes —
        # a parse failure here means the index entry itself is wrong.
        raise SegmentCorruptError(
            f"{label}: segment {path} malformed ({e})")
    return start, out
