"""Tiered sealed-epoch storage: device ring → host buffer → disk segments.

The paper's in-flight log is "in memory, spillable to disk" (PAPER.md
core idea 3). This package is the spill fabric shared by the in-flight
rings (inflight/log.py) and the determinant logs (causal/log.py): the
hot tier stays a device tensor ring (the unchanged fast path); sealed
epochs are evicted asynchronously to a host staging buffer and persisted
as immutable checksummed segment files; recovery refills transparently
from whichever tier holds each epoch.

- :mod:`segment` — the on-disk unit: one sealed epoch, one file, one
  blake2b checksum, atomically replaced into place; a JSONL segment
  index with the shared torn-tail convention (utils/jsonl.py).
- :mod:`tiered` — :class:`TieredEpochStore`, the host-buffer +
  disk-segment owner with an asynchronous double-buffered writer,
  tier-occupancy accounting, spill/refill bandwidth counters, and audit
  digests attached to each sealed segment.
"""

from clonos_tpu.storage.segment import (SegmentCorruptError, read_segment,
                                        segment_checksum, write_segment)
from clonos_tpu.storage.tiered import StorageError, TieredEpochStore

__all__ = [
    "SegmentCorruptError",
    "StorageError",
    "TieredEpochStore",
    "read_segment",
    "segment_checksum",
    "write_segment",
]
