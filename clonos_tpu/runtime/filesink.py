"""Durable filesystem egress behind the 2PC sink — the
StreamingFileSink / FileSystem-connector analog (reference
flink-streaming-java .../functions/sink/filesystem/StreamingFileSink.java
+ the flink-connector-filesystem bucketing sink): exactly-once part
files via the write-pending / atomic-rename-on-commit protocol.

Protocol (riding runtime/txn.py's TransactionLog hooks):

- **pre-commit** (epoch seal): every subtask shard of the sealed epoch is
  written to ``part-<epoch>-<sub>.pending`` — durably on disk BEFORE the
  checkpoint can complete, the reference's preCommit-on-snapshot promise.
- **commit** (checkpoint complete): each pending part is atomically
  renamed to ``part-<epoch>-<sub>.final`` (``os.replace``). Only
  ``.final`` files are observable output; a consumer can never see data
  of an epoch whose checkpoint didn't complete.
- **abort / recovery**: a sink-subtask failure rebuilds its shards from
  replay (TransactionLog.rebuild_shard) and re-seals — the pending part
  is simply overwritten with the bit-identical replayed bytes. A process
  restart calls :meth:`sweep_pending`, deleting pendings of epochs that
  will never commit (the recoverAndAbort pass).
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence, Tuple

import numpy as np


class FileSystemSink:
    """One sink vertex's durable part-file store.

    ``fencing`` (optional) is a leadership handle exposing
    ``is_leader()`` — typically a ``runtime.leader.FileLeaderElection``.
    When set, every mutating operation (pending write, commit rename,
    and above all the destructive :meth:`sweep_pending`) refuses to run
    unless this incarnation currently holds the lease: two incarnations
    sharing the sink root is exactly the standby-takeover scenario, and
    the deposed one sweeping on startup would delete the healthy
    writer's in-progress pendings.

    ``token`` is the writer's fencing token — a monotone incarnation
    number (leader term, re-cut generation). It is baked into every
    part filename (``part-<epoch>-<sub>-t<token>.*``) so the
    destructive :meth:`sweep_pending` can tell WHOSE in-progress parts
    it is looking at: an incarnation only ever sweeps pendings of
    tokens at or below its own — a stale sweeper (old token) can never
    delete a newer writer's in-progress parts, even when no leadership
    handle is wired in. Token-less legacy filenames parse as token 0.
    """

    def __init__(self, root: str, fencing=None, token: int = 0):
        self.root = root
        self.fencing = fencing
        self.token = int(token)
        os.makedirs(root, exist_ok=True)

    def _check_fencing(self, what: str) -> None:
        if self.fencing is not None and not self.fencing.is_leader():
            raise PermissionError(
                f"filesink {what} refused: this incarnation does not hold "
                f"the leadership lease for {self.root!r} — a fenced-off "
                f"writer must not mutate a sink root another incarnation "
                f"may be writing")

    def _part(self, epoch: int, sub: int, state: str) -> str:
        return os.path.join(
            self.root, f"part-{epoch}-{sub}-t{self.token}.{state}")

    @staticmethod
    def _parse(fn: str) -> Tuple[int, int, int]:
        """``(epoch, subtask, token)`` of a part filename; token-less
        legacy names (``part-<e>-<s>.*``) read as token 0."""
        stem = fn.split(".", 1)[0]
        fields = stem.split("-")
        epoch, sub = int(fields[1]), int(fields[2])
        token = 0
        if len(fields) > 3 and fields[3].startswith("t"):
            token = int(fields[3][1:])
        return epoch, sub, token

    # --- TransactionLog hooks ------------------------------------------------

    def write_pending(self, epoch: int,
                      shards: Dict[int, np.ndarray]) -> None:
        """Pre-commit: persist every subtask shard of the sealed epoch
        (atomic per-file: temp + replace, so a crash mid-write never
        leaves a torn pending)."""
        self._check_fencing("write_pending")
        for sub, rows in shards.items():
            path = self._part(epoch, sub, "pending")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.save(f, np.asarray(rows, np.int32))
            os.replace(tmp, path)

    def commit(self, epoch: int, _rows: np.ndarray) -> None:
        """Checkpoint complete: pendings of ``epoch`` become final,
        atomically, subtask-major. Only parts at or below this writer's
        token — a newer incarnation's pendings are not this writer's to
        certify."""
        self._check_fencing("commit")
        for fn in sorted(os.listdir(self.root)):
            if not (fn.startswith(f"part-{epoch}-")
                    and fn.endswith(".pending")):
                continue
            if self._parse(fn)[2] > self.token:
                continue
            src = os.path.join(self.root, fn)
            os.replace(src, src[:-len(".pending")] + ".final")

    # --- restart / observation ----------------------------------------------

    def sweep_pending(self, keep_epochs: Sequence[int] = ()) -> List[str]:
        """Startup recovery: delete pendings whose epoch is not in
        ``keep_epochs`` (their checkpoint will never complete — the
        recoverAndAbort pass). Returns the removed filenames.

        Token-fenced: pendings and temp orphans above this writer's own
        token are a NEWER incarnation's in-progress parts — sharing the
        root during a handoff (live re-cut, standby takeover), a stale
        sweeper must leave them alone. Strictly-older tokens are always
        dead (their incarnation was fenced off) and sweep regardless of
        ``keep_epochs``; same-token pendings sweep unless kept."""
        self._check_fencing("sweep_pending")
        keep = set(keep_epochs)
        removed = []
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".tmp"):
                # A crash between temp write and rename leaves an orphan
                # that would otherwise accumulate forever.
                if self._parse(fn)[2] <= self.token:
                    os.remove(os.path.join(self.root, fn))
                    removed.append(fn)
                continue
            if not fn.endswith(".pending"):
                continue
            epoch, _sub, token = self._parse(fn)
            if token > self.token:
                continue
            if token < self.token or epoch not in keep:
                os.remove(os.path.join(self.root, fn))
                removed.append(fn)
        return removed

    def committed_epochs(self) -> List[int]:
        out = set()
        for fn in os.listdir(self.root):
            if fn.endswith(".final"):
                out.add(self._parse(fn)[0])
        return sorted(out)

    def read_committed(self) -> np.ndarray:
        """Every committed record in (epoch, subtask) order — what an
        external consumer observes."""
        parts: List[Tuple[int, int, str]] = []
        for fn in os.listdir(self.root):
            if fn.endswith(".final"):
                e, s, _t = self._parse(fn)
                parts.append((e, s, fn))
        rows = [np.load(os.path.join(self.root, fn))
                for _e, _s, fn in sorted(parts)]
        rows = [r for r in rows if r.shape[0]]
        return (np.concatenate(rows, axis=0) if rows
                else np.zeros((0, 3), np.int32))
