"""Leader election + HA services for the JobMaster.

Reference: flink-runtime .../leaderelection/ +
.../highavailability/ (StandaloneLeaderElectionService /
ZooKeeperLeaderElectionService): exactly one JobMaster leads at a time;
a standby takes over when the leader's lease lapses; every grant carries
a monotonically increasing **fencing token** that stale leaders' actions
are rejected by (the reference's leader session id).

This is the file-lease implementation (the shared-filesystem analog of
the ZK lock — the deployment unit here is hosts sharing a durable
directory, the same place checkpoints live): the lease file holds
``{leader_id, epoch, deadline}``; acquisition atomically replaces an
absent or EXPIRED lease with ``epoch + 1`` (os.replace — last writer
wins, and the epoch check makes a lost race visible to the loser);
renewal extends the deadline only while the epoch still matches (a
deposed leader's renew fails instead of silently split-braining)."""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class FileLeaderElection:
    """One contender's handle on a lease-file election."""

    def __init__(self, path: str, contender_id: str,
                 lease_ttl_s: float = 2.0,
                 clock=time.monotonic):
        self.path = path
        self.contender_id = contender_id
        self.ttl = lease_ttl_s
        self._clock = clock
        #: fencing token of OUR current leadership (None = not leader)
        self.epoch: Optional[int] = None

    # --- lease file ----------------------------------------------------------

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self, rec: dict) -> None:
        tmp = f"{self.path}.{self.contender_id}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    # --- contender API -------------------------------------------------------

    def _claim(self, epoch: int) -> bool:
        """Atomically claim fencing epoch ``epoch``: O_CREAT|O_EXCL on a
        per-epoch claim file — the filesystem arbitrates, so two
        contenders racing on one expired lease can NEVER both win the
        same epoch (the split-brain hole a write-then-re-read protocol
        leaves open)."""
        try:
            fd = os.open(f"{self.path}.epoch{epoch}.claim",
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def _max_claimed(self) -> int:
        """Highest epoch any contender ever claimed — a claimant that
        crashed between claim and lease write must not wedge the
        election (the next acquisition goes one higher)."""
        d = os.path.dirname(self.path) or "."
        base = os.path.basename(self.path) + ".epoch"
        hi = 0
        try:
            for fn in os.listdir(d):
                if fn.startswith(base) and fn.endswith(".claim"):
                    hi = max(hi, int(fn[len(base):-len(".claim")]))
        except OSError:
            pass
        return hi

    def try_acquire(self) -> bool:
        """Become leader iff the lease is absent, expired, or already
        ours. Returns True when this contender now leads; ``epoch`` is
        the fencing token to stamp outgoing actions with."""
        cur = self._read()
        now = self._clock()
        if cur is not None and cur["deadline"] > now \
                and cur["leader_id"] != self.contender_id:
            return False
        if cur is not None and cur["leader_id"] == self.contender_id \
                and cur["deadline"] > now:
            # Still ours: extend under the existing token.
            self.epoch = cur["epoch"]
            self._write({"leader_id": self.contender_id,
                         "epoch": self.epoch,
                         "deadline": now + self.ttl})
            return True
        new_epoch = max(cur["epoch"] if cur is not None else 0,
                        self._max_claimed()) + 1
        if not self._claim(new_epoch):
            self.epoch = None
            return False               # lost the race for this epoch
        self._write({"leader_id": self.contender_id, "epoch": new_epoch,
                     "deadline": now + self.ttl})
        self.epoch = new_epoch
        return True

    def renew(self) -> bool:
        """Extend our lease. Fails (and drops leadership) if the lease
        was taken over — the fencing epoch moved past ours."""
        if self.epoch is None:
            return False
        cur = self._read()
        if cur is None or cur["leader_id"] != self.contender_id \
                or cur["epoch"] != self.epoch:
            self.epoch = None
            return False
        self._write({"leader_id": self.contender_id, "epoch": self.epoch,
                     "deadline": self._clock() + self.ttl})
        return True

    def is_leader(self) -> bool:
        return self.epoch is not None

    def leader(self) -> Optional[str]:
        """Current lease holder (None when absent/expired)."""
        cur = self._read()
        if cur is None or cur["deadline"] <= self._clock():
            return None
        return cur["leader_id"]

    def fencing_valid(self, epoch: int) -> bool:
        """Would an action stamped with ``epoch`` be accepted now? (The
        receiver-side check: reject anything below the current lease
        epoch — a deposed leader's late RPCs.)"""
        cur = self._read()
        return cur is not None and epoch >= cur["epoch"]
