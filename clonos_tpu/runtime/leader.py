"""Leader election + HA services for the JobMaster.

Reference: flink-runtime .../leaderelection/ +
.../highavailability/ (StandaloneLeaderElectionService /
ZooKeeperLeaderElectionService): exactly one JobMaster leads at a time;
a standby takes over when the leader's lease lapses; every grant carries
a monotonically increasing **fencing token** that stale leaders' actions
are rejected by (the reference's leader session id).

File-lease implementation (the shared-filesystem analog of the ZK lock —
the deployment unit here is hosts sharing the durable directory
checkpoints live in). The design makes split-brain STRUCTURALLY
impossible rather than racily unlikely:

- every fencing epoch is one file, ``<path>.epoch<N>.claim``, created
  with O_CREAT|O_EXCL — the filesystem arbitrates, so an epoch has
  exactly one owner, ever;
- the claim file IS the lease: its content ``{leader_id, deadline_wall}``
  (a wall-clock deadline — comparable across hosts and boots) is
  rewritten (atomic tmp+replace) only by its owner on renewal — there is
  no shared lease file two writers could race on, which is exactly the
  TOCTOU a central lease record cannot avoid;
- the current leader is the OWNER OF THE HIGHEST epoch whose deadline
  has not lapsed; a deposed leader renewing its old epoch's file changes
  nothing any reader looks at, and ``fencing_valid`` rejects tokens
  below the highest claimed epoch;
- acquisition claims ``highest + 1`` and garbage-collects claims more
  than one epoch behind (superseded claims can never be read again)."""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple


def job_lease_path(base: str, job_id: Optional[str]) -> str:
    """Job-scoped lease namespace under one cluster lease ``base``.

    A multi-job cluster (runtime/dispatcher.py) runs one election PER
    JOB: each job's JobMaster fences its own DEPLOYs with its own epoch
    sequence. Claim files are discovered by basename prefix
    (``<path>.epoch<N>.claim``), so two jobs sharing one ``base`` would
    read each other's claims and a leader change in job A would fence
    job B's deployments. Scoping the path —
    ``<base>.<job_id>.epoch<N>.claim`` — keeps every job's claim family
    disjoint while still living in the shared lease directory workers
    validate against. An empty job id is the legacy single-job cluster:
    the base path is used as-is (claim files byte-identical)."""
    if not job_id:
        return base
    if "/" in job_id:
        raise ValueError(f"job id {job_id!r} must not contain '/'")
    return f"{base}.{job_id}"


class FileLeaderElection:
    """One contender's handle on a claim-file election."""

    def __init__(self, path: str, contender_id: str,
                 lease_ttl_s: float = 2.0,
                 clock=None):
        self.path = path
        self.contender_id = contender_id
        self.ttl = lease_ttl_s
        #: Lease deadlines are WALL-CLOCK (`time.time`) because claim
        #: files are shared-filesystem state read by contenders on OTHER
        #: hosts, across process (and host) restarts — CLOCK_MONOTONIC is
        #: per-boot and means nothing to another reader. The injected
        #: clock exists for tests only.
        # clonos: allow(wallclock): lease deadlines are cross-host wall
        # time by design (see note above); leases are never replayed.
        self._clock = time.time if clock is None else clock
        #: fencing token of OUR current leadership (None = not leader)
        self.epoch: Optional[int] = None
        #: transition observers: ``fn(kind, **fields)`` on every
        #: leadership transition (claim/renew/deposed/lost-race) —
        #: the verify conformance layer's observation surface.
        self.transition_observers: List = []

    def _observe(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    # --- claim files ---------------------------------------------------------

    def _claim_path(self, epoch: int) -> str:
        return f"{self.path}.epoch{epoch}.claim"

    def _claims(self) -> List[int]:
        d = os.path.dirname(self.path) or "."
        base = os.path.basename(self.path) + ".epoch"
        out = []
        try:
            for fn in os.listdir(d):
                if fn.startswith(base) and fn.endswith(".claim"):
                    out.append(int(fn[len(base):-len(".claim")]))
        except OSError:
            pass
        return sorted(out)

    def _read_claim(self, epoch: int) -> Optional[dict]:
        """Claim content, or a conservative placeholder while its owner
        is still between O_EXCL create and content write (treat as live
        until the creation-time grace lapses — never steal mid-write)."""
        p = self._claim_path(epoch)
        try:
            with open(p) as f:
                rec = json.load(f)
            rec["epoch"] = epoch
            return rec
        except ValueError:
            try:
                return {"leader_id": None, "epoch": epoch,
                        "deadline_wall": os.path.getmtime(p) + self.ttl,
                        "pending": True}
            except OSError:
                return None
        except OSError:
            return None

    def _write_own(self, epoch: int, deadline: float) -> None:
        # Single writer: only the O_EXCL winner of ``epoch`` ever writes
        # this file, so the replace cannot race another contender.
        tmp = f"{self._claim_path(epoch)}.{self.contender_id}.tmp"
        with open(tmp, "w") as f:
            json.dump({"leader_id": self.contender_id,
                       "deadline_wall": deadline}, f)
        os.replace(tmp, self._claim_path(epoch))

    def _current(self) -> Optional[dict]:
        """The highest-epoch claim record (the authoritative lease)."""
        claims = self._claims()
        return self._read_claim(claims[-1]) if claims else None

    def _expired(self, rec: dict) -> bool:
        if rec.get("pending"):
            # Grace keyed to wall time (mtime); the injected clock does
            # not apply to a foreign writer mid-create.
            # clonos: allow(wallclock): expiry of a foreign lease file
            return time.time() > rec["deadline_wall"]
        return self._clock() > rec["deadline_wall"]

    # --- contender API -------------------------------------------------------

    def try_acquire(self) -> bool:
        """Become leader iff no live higher claim exists. True when this
        contender now leads; ``epoch`` is the fencing token."""
        cur = self._current()
        if cur is not None and not self._expired(cur):
            if cur.get("leader_id") == self.contender_id:
                self.epoch = cur["epoch"]
                self._write_own(self.epoch, self._clock() + self.ttl)
                self._observe("renew", epoch=self.epoch)
                return True
            return False
        new_epoch = (cur["epoch"] + 1) if cur is not None else 1
        try:
            fd = os.open(self._claim_path(new_epoch),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            self.epoch = None
            self._observe("lost-race", epoch=new_epoch)
            return False               # lost the race for this epoch
        os.close(fd)
        self._write_own(new_epoch, self._clock() + self.ttl)
        self.epoch = new_epoch
        self._observe("claim", epoch=new_epoch)
        # Superseded claims (< epoch-1) can never be read again.
        for e in self._claims():
            if e < new_epoch - 1:
                try:
                    os.remove(self._claim_path(e))
                except OSError:
                    pass
        return True

    def renew(self) -> bool:
        """Extend our lease by rewriting OUR OWN epoch's claim — a no-op
        for every reader if a higher epoch was claimed meanwhile (the
        takeover can never be clobbered). Returns False and drops
        leadership once superseded."""
        if self.epoch is None:
            return False
        claims = self._claims()
        if not claims or claims[-1] != self.epoch:
            deposed = self.epoch
            self.epoch = None          # deposed: a higher claim exists
            self._observe("deposed", epoch=deposed)
            return False
        self._write_own(self.epoch, self._clock() + self.ttl)
        self._observe("renew", epoch=self.epoch)
        return True

    def is_leader(self) -> bool:
        return self.epoch is not None

    def leader(self) -> Optional[str]:
        """Current lease holder (None when absent/expired)."""
        cur = self._current()
        if cur is None or self._expired(cur):
            return None
        return cur.get("leader_id")

    def fencing_valid(self, epoch: int) -> bool:
        """Would an action stamped with ``epoch`` be accepted now? (The
        receiver-side check.) Valid tokens are exactly the HIGHEST
        EXISTING claim: anything below it is a deposed leader's late RPC,
        and anything above it is a forged token for an epoch nobody has
        won through O_EXCL arbitration — both are rejected."""
        claims = self._claims()
        return bool(claims) and epoch == claims[-1]
