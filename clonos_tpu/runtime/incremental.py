"""Incremental checkpoints: device-diffed, chunk-granular snapshot storage.

Capability analog of the reference's incremental state backend
(flink-state-backends RocksDBKeyedStateBackend.java:145 — only SST files
new since the last checkpoint upload). The TPU-first form diffs on the
*device*: the snapshotter keeps the previous completed snapshot's leaves
as a device-side shadow (jax arrays are immutable, so holding references
is free), and one jitted program per leaf shape

- chunks the flat leaf,
- flags chunks that changed since the shadow,
- compacts the changed chunk ids + payloads into a fixed budget
  (``jnp.nonzero(..., size=M)`` keeps shapes static for XLA),

so only the changed chunks ever cross the host link — on a tunneled TPU
the d2h transfer, not the disk write, is the dominant fence cost. Leaves
whose change count exceeds the budget ship whole (per-leaf, not
all-or-nothing); a chain of deltas is anchored by periodic full
snapshots, and deletion keeps a base alive until nothing retained
depends on it (the reference's shared-state registry, subsumed-
checkpoint disposal).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.runtime.checkpoint import (CheckpointStorage,
                                           CompletedCheckpoint,
                                           carry_to_host)


@dataclasses.dataclass
class LeafDelta:
    """Changed chunks of one flattened leaf since the previous snapshot."""

    chunk_ids: np.ndarray      # int32 [m] (m <= budget), ids < num_chunks
    chunks: np.ndarray         # [m, chunk_elems] in the leaf's dtype


#: per-leaf entry in a delta snapshot: LeafDelta, or the full leaf array
#: (budget exceeded / shape changed), or None (bit-identical leaf).
LeafEntry = Any


class DeviceDiffSnapshotter:
    """Computes per-leaf chunk deltas against a device-side shadow."""

    def __init__(self, chunk_elems: int = 1024, budget_frac: float = 0.5):
        self.chunk_elems = chunk_elems
        self.budget_frac = budget_frac
        self._shadow: Optional[List[jax.Array]] = None
        self._treedef = None
        self._jit: Dict[Tuple, Any] = {}

    def _diff_fn(self, n: int, dtype, chunk: int, m: int):
        key = (n, np.dtype(dtype).str, chunk, m)
        fn = self._jit.get(key)
        if fn is None:
            c = -(-n // chunk)
            pad = c * chunk - n

            def f(new, old):
                a = jnp.pad(new.reshape(-1), (0, pad)).reshape(c, chunk)
                b = jnp.pad(old.reshape(-1), (0, pad)).reshape(c, chunk)
                changed = jnp.any(a != b, axis=1)
                ids = jnp.nonzero(changed, size=m, fill_value=c)[0]
                data = a[jnp.clip(ids, 0, c - 1)]
                return (ids.astype(jnp.int32), data,
                        changed.sum().astype(jnp.int32))
            fn = self._jit[key] = jax.jit(f)
        return fn

    def advance_shadow(self, snap) -> None:
        """Adopt ``snap`` as the diff base without computing a delta
        (used when the caller decided on a full snapshot anyway — the
        diff programs and their d2h would be wasted work)."""
        self._shadow, self._treedef = jax.tree_util.tree_flatten(snap)

    def snapshot(self, snap) -> Tuple[str, Any]:
        """Returns ("full", host_pytree) or ("delta", [LeafEntry...]).
        Updates the shadow to ``snap`` either way."""
        leaves, treedef = jax.tree_util.tree_flatten(snap)
        prev, self._shadow, ptd = self._shadow, leaves, self._treedef
        self._treedef = treedef
        if prev is None or ptd != treedef or len(prev) != len(leaves):
            return "full", carry_to_host(snap)
        entries: List[LeafEntry] = []
        for new, old in zip(leaves, prev):
            new = jnp.asarray(new)
            if new.shape != old.shape or new.dtype != old.dtype:
                entries.append(np.asarray(new))
                continue
            n = int(new.size)
            if n == 0:
                entries.append(None)
                continue
            chunk = min(self.chunk_elems, n)
            c = -(-n // chunk)
            m = max(1, int(c * self.budget_frac))
            ids, data, nch = self._diff_fn(n, new.dtype, chunk, m)(new, old)
            nch = int(nch)
            if nch == 0:
                entries.append(None)
            elif nch > m:
                entries.append(np.asarray(new))       # whole leaf ships
            else:
                # Slice on DEVICE first: only the nch changed chunks
                # cross the host link, not the whole budget.
                entries.append(LeafDelta(
                    chunk_ids=np.asarray(ids[:nch]),
                    chunks=np.asarray(data[:nch])))
        return "delta", entries

    @staticmethod
    def apply(base_host, entries: List[LeafEntry], chunk_elems: int):
        """Apply one delta's entries over a host snapshot (new pytree)."""
        leaves, treedef = jax.tree_util.tree_flatten(base_host)
        out = []
        for leaf, e in zip(leaves, entries):
            if e is None:
                out.append(leaf)
            elif isinstance(e, LeafDelta):
                n = leaf.size
                chunk = min(chunk_elems, max(n, 1))
                c = -(-n // chunk)
                flat = np.zeros((c * chunk,), leaf.dtype)
                flat[:n] = np.asarray(leaf).reshape(-1)
                ch = flat.reshape(c, chunk)
                ch[e.chunk_ids] = e.chunks
                out.append(ch.reshape(-1)[:n].reshape(leaf.shape))
            else:
                out.append(e)                         # whole-leaf payload
        return jax.tree_util.tree_unflatten(treedef, out)


class IncrementalCheckpointStorage(CheckpointStorage):
    """File-backed delta-chain storage: every ``base_every``-th write is a
    full snapshot; the rest persist only the device-diffed changed
    chunks. Reads reconstruct base + delta chain; deleting a checkpoint
    that later retained deltas still depend on defers the physical
    removal until the chain no longer needs it."""

    #: the snapshotter diffs device arrays itself — the coordinator must
    #: NOT pre-materialize the carry to host (that transfer is the cost
    #: this backend exists to avoid).
    wants_host = False

    def __init__(self, root: str, base_every: int = 8,
                 chunk_elems: int = 1024, budget_frac: float = 0.5):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.base_every = base_every
        self.chunk_elems = chunk_elems
        self._snap = DeviceDiffSnapshotter(chunk_elems, budget_frac)
        self._since_base = 0
        #: cid -> ("full", None) | ("delta", base_cid)
        self._index: Dict[int, Tuple[str, Optional[int]]] = {}
        #: cids logically deleted but physically retained for a chain
        self._zombie: set = set()
        self._order: List[int] = []     # write order (chain order)
        self._recover_index()

    def _recover_index(self) -> None:
        """Rebuild the chain index from disk (process restart over the
        same directory — FileCheckpointStorage scans the same way). Only
        each file's small meta header is read (the payload is a second
        pickle object, skipped), so startup I/O scales with the index,
        not total checkpoint bytes. Persisted tombstones re-mark logical
        deletions; files whose chain is broken (their base was removed)
        are unreadable and deleted so the directory can't grow
        unboundedly across runs."""
        found: Dict[int, Tuple[str, Optional[int]]] = {}
        for fn in os.listdir(self.root):
            if not (fn.startswith("inc_") and fn.endswith(".pkl")):
                continue
            try:
                meta = self._load_meta(int(fn[4:-4]))
                found[meta["checkpoint_id"]] = (meta["kind"], meta["base"])
            except Exception:
                continue

        def chain_ok(cid: int) -> bool:
            seen = set()
            while found[cid][0] == "delta":
                base = found[cid][1]
                if base not in found or base in seen:
                    return False
                seen.add(base)
                cid = base
            return True
        for cid in sorted(found):
            if chain_ok(cid):
                self._index[cid] = found[cid]
                self._order.append(cid)
            else:
                try:
                    os.remove(self._path(cid))
                except OSError:
                    pass
        try:
            with open(self._tomb_path()) as f:
                import json
                self._zombie = {c for c in json.load(f)
                                if c in self._index}
        except (OSError, ValueError):
            self._zombie = set()
        self._gc()

    def _path(self, cid: int) -> str:
        return os.path.join(self.root, f"inc_{cid}.pkl")

    def _tomb_path(self) -> str:
        return os.path.join(self.root, "tombstones.json")

    def _write_tombstones(self) -> None:
        import json
        tmp = self._tomb_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sorted(self._zombie), f)
        os.replace(tmp, self._tomb_path())

    def write(self, ckpt: CompletedCheckpoint) -> None:
        # A full snapshot every base_every-th write (deltas in between).
        force_full = (self._since_base + 1 >= self.base_every
                      or not self._order)
        # The diff shadow must only advance when the write is durable: a
        # failed write would otherwise leave the next delta diffed
        # against a checkpoint that was never persisted — silently
        # missing chunks from its chain.
        prev_shadow = self._snap._shadow
        prev_td = self._snap._treedef
        # Everything from shadow advance through the durable rename sits
        # under one rollback guard: an exception ANYWHERE (diff program,
        # d2h, disk full, interrupt) must leave the shadow at the last
        # PERSISTED checkpoint, or the next delta silently misses chunks.
        try:
            if force_full:
                # Don't pay the diff programs + budgeted d2h only to
                # discard them — advance the shadow, materialize once.
                self._snap.advance_shadow(ckpt.carry)
                kind, payload = "full", carry_to_host(ckpt.carry)
            else:
                kind, payload = self._snap.snapshot(ckpt.carry)
            base = self._order[-1] if kind == "delta" else None
            meta = {"checkpoint_id": ckpt.checkpoint_id, "kind": kind,
                    "base": base, "wall_time": ckpt.wall_time,
                    "chunk_elems": self.chunk_elems}
            tmp = self._path(ckpt.checkpoint_id) + ".tmp"
            with open(tmp, "wb") as f:
                # Object 1: small meta header (index recovery reads only
                # this). Object 2: the payload.
                pickle.dump(meta, f, protocol=pickle.HIGHEST_PROTOCOL)
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._path(ckpt.checkpoint_id))
        except BaseException:
            self._snap._shadow = prev_shadow
            self._snap._treedef = prev_td
            raise
        self._index[ckpt.checkpoint_id] = (kind, base)
        self._order.append(ckpt.checkpoint_id)
        self._since_base = 0 if kind == "full" else self._since_base + 1

    def _load_meta(self, cid: int) -> dict:
        with open(self._path(cid), "rb") as f:
            return pickle.load(f)

    def _load(self, cid: int) -> dict:
        with open(self._path(cid), "rb") as f:
            meta = pickle.load(f)
            meta["payload"] = pickle.load(f)
            return meta

    def _chain(self, cid: int) -> List[int]:
        """cids from the anchoring full snapshot to ``cid`` inclusive."""
        chain = [cid]
        while self._index[chain[0]][0] == "delta":
            chain.insert(0, self._index[chain[0]][1])
        return chain

    def read(self, checkpoint_id: int) -> CompletedCheckpoint:
        if checkpoint_id not in self._index or \
                checkpoint_id in self._zombie:
            raise KeyError(checkpoint_id)
        carry = None
        rec = None
        for cid in self._chain(checkpoint_id):
            rec = self._load(cid)
            if rec["kind"] == "full":
                carry = rec["payload"]
            else:
                carry = DeviceDiffSnapshotter.apply(
                    carry, rec["payload"], rec["chunk_elems"])
        host = carry
        size = int(sum(np.asarray(x).nbytes for x in
                       jax.tree_util.tree_leaves(host)))
        return CompletedCheckpoint(
            checkpoint_id=checkpoint_id, carry=host,
            wall_time=rec["wall_time"], size_bytes=size)

    def delete(self, checkpoint_id: int) -> None:
        if checkpoint_id not in self._index:
            return
        self._zombie.add(checkpoint_id)
        # Tombstones persist so a restart can't resurrect a logically
        # deleted checkpoint (and its file eventually GCs).
        self._write_tombstones()
        self._gc()

    def _gc(self) -> None:
        # A zombie is removable once no retained (non-zombie) checkpoint's
        # chain passes through it.
        needed: set = set()
        for cid in self._index:
            if cid not in self._zombie:
                needed.update(self._chain(cid))
        removed = False
        for cid in [z for z in self._zombie if z not in needed]:
            for p in (self._path(cid), self._path(cid) + ".done"):
                try:
                    os.remove(p)
                except OSError:
                    pass
            self._zombie.discard(cid)
            self._index.pop(cid, None)
            if cid in self._order:
                self._order.remove(cid)
            removed = True
        if removed:
            self._write_tombstones()

    def list_ids(self) -> List[int]:
        return sorted(c for c in self._index if c not in self._zombie)

    def delta_bytes_on_disk(self) -> Dict[int, int]:
        """Observability: per-checkpoint file size (full vs delta)."""
        return {cid: os.path.getsize(self._path(cid))
                for cid in self._index}
