"""Slot-pool scheduler: one job spanning multiple worker processes.

Capability analog of the reference's deployment layer (reference
jobmaster/slotpool/SlotPool.java offer/allocate path,
TaskExecutor.java:422 submitTask, TaskDeploymentDescriptor, and the
JobMaster leader sessions whose fencing token rides every RPC). Until
now each worker process ran the WHOLE job and failover rebuilt the whole
job in the JobMaster process; this module makes a job genuinely span
worker processes and recover per-task:

- **Slots** (:class:`SlotPool`): workers advertise slot capacity at
  registration (``slots`` in the REGISTER info, topped up by SLOT_OFFER);
  the JobMaster-side pool tracks which task group occupies which slot.
- **Slicing** (:func:`partition_vertices` + ``JobGraph.subgraph``): the
  job's vertices are cut into contiguous topological slices, one per
  worker, balanced by subtask count. Cuts land on exchange edges; each
  slice is an independently-runnable sub-job whose cut in-edges become
  HostFeedSource boundaries and whose cut out-edges keep their producer
  ring alive behind a terminal export sink. The slice structure is a
  pure function of ``(vertex_ids, feed_batch)``, so the JobMaster and
  the worker derive identical topologies from the same descriptor.
- **Cross-worker edges** (:class:`EdgeExportServer` +
  :class:`RemoteEdgeFeedReader`): the upstream worker publishes each cut
  edge's records — read out of the producer's in-flight ring at every
  epoch fence, flattened in deterministic (step, lane, slot) order —
  into a retained buffer served over the control transport (FETCH_EDGE
  / EDGE_DATA). The downstream slice consumes it through a BLOCKING
  exact-count reader: every pull waits for a full batch, so per-step
  batch boundaries are identical across runs (the bit-identical-digest
  contract would break under "serve whatever has arrived" timing), and
  ``read_at`` re-serves exact absolute ranges for causal replay.
  Record payloads (key, value) cross the boundary; timestamps are
  re-stamped by the downstream HostFeedSource from its own causal time —
  the same contract as any external connector boundary.
- **Fenced deployment** (:class:`SlotPoolScheduler` +
  :class:`TaskExecutorEndpoint`): the scheduler acts only while holding
  the ``FileLeaderElection`` lease, and stamps its fencing epoch on
  every DEPLOY. The worker endpoint rejects a token that is not the
  highest EXISTING claim in the shared lease directory AND any token
  below the highest it has ever accepted — a deposed JobMaster's late
  orders cannot reach a runner.
- **Per-task recovery**: on worker death (heartbeat expiry) the
  scheduler redeploys ONLY the dead worker's task groups onto surviving
  slots — by preference onto each group's pre-assigned standby worker
  (rotate-by-one anti-affinity, ``distributed.standby_worker_order``) —
  shipping its mirrored determinant rows in the DEPLOY frame; the
  surviving worker drives ``ClusterRunner.bootstrap_standby`` for just
  that slice, replaying it to its last mirrored fence (bit-identical,
  per the causal-recovery contract) while every other slice keeps
  running untouched. The healthy upstream's edge export then re-serves
  the replayed input windows from absolute offsets.

Known limits (documented, not silent): a rebuilt slice re-exports only
what its replayed rings retain, so chains where a FAILED slice feeds a
further downstream worker need export spill to hand history back;
slices co-hosted on one worker step round-robin one epoch at a time, so
a co-hosted downstream slice must stay an epoch of feed demand behind
its upstream (the blocking reader fails loudly on timeout rather than
deadlocking forever).
"""

from __future__ import annotations

import collections
import dataclasses
import importlib
import json
import queue
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from clonos_tpu.causal import serde
from clonos_tpu.graph.job_graph import JobGraph, PartitionType
from clonos_tpu.obs import get_tracer
from clonos_tpu.parallel import transport as tp
from clonos_tpu.parallel.distributed import standby_worker_order
from clonos_tpu.runtime import remote as rm
from clonos_tpu.runtime.leader import FileLeaderElection, job_lease_path


class NotLeaderError(RuntimeError):
    """A scheduler action was attempted without holding the lease."""


class RescaleProtocolError(RuntimeError):
    """A live re-cut step was attempted out of protocol order."""


class RescaleCoordinator:
    """Control plane of ONE live re-cut: fence → drain → migrate →
    redirect (verify/models.py ``RepartitionModel`` is the checked
    abstraction of exactly this object; the conformance harness drives
    it through model traces and compares the observation stream).

    The JobMaster-side driver (``ClusterRunner.rescale_live``) walks it
    through the protocol while doing the data-plane work beside each
    step:

    - :meth:`fence` — a COMPLETED checkpoint fence is the cut point;
      the old incarnation stops admitting records.
    - :meth:`drain` — the old incarnation hands group ``g``'s buffered
      in-flight edge records into the migration payload (in the real
      re-cut they ride the checkpoint's edge buffers through
      ``route_hash_block``; "drained" here means *accounted for*, the
      opposite of dying with the old incarnation).
    - :meth:`migrate` — group ``g``'s keyed state moves to the N±k
      incarnation. Guarded on an empty in-flight count: migrating over
      a non-empty buffer is the ``migrate-skips-drain`` record-loss
      bug the model proves bites.
    - :meth:`redirect` — traffic cuts over. Guarded on every group
      having migrated (``redirect-before-migrate`` restarts unmigrated
      groups empty).

    Guards raise :class:`RescaleProtocolError` — the implementation
    refuses to reproduce the model's seeded bugs. ``transition_observers``
    (``fn(kind, **fields)``) emit the conformance stream."""

    PHASES = ("PRE", "FENCED", "REDIRECTED")

    def __init__(self, num_groups: int):
        if int(num_groups) < 1:
            raise ValueError("RescaleCoordinator needs >= 1 group")
        self.num_groups = int(num_groups)
        self.phase = "PRE"
        self.inflight = [0] * self.num_groups
        self.migrated = [False] * self.num_groups
        self.fence_checkpoint: Optional[int] = None
        #: transition observers: ``fn(kind, **fields)`` on every
        #: protocol step — the verify conformance surface.
        self.transition_observers: List = []

    def _observe(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    def _check_group(self, group: int) -> int:
        group = int(group)
        if not 0 <= group < self.num_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.num_groups})")
        return group

    def note_inflight(self, group: int, n: int = 1) -> None:
        """Pre-fence bookkeeping: ``n`` records entered (``n > 0``) or
        left (``n < 0``) group ``group``'s in-flight edge buffers. Not
        a protocol transition — nothing is observed."""
        group = self._check_group(group)
        if self.phase == "REDIRECTED":
            raise RescaleProtocolError(
                "note_inflight after redirect — the old incarnation "
                "no longer owns any group")
        if self.inflight[group] + n < 0:
            raise RescaleProtocolError(
                f"group {group} in-flight count would go negative "
                f"({self.inflight[group]} {n:+d})")
        self.inflight[group] += int(n)

    def fence(self, checkpoint_id: int) -> None:
        """A completed checkpoint fence: the cut point. PRE → FENCED."""
        if self.phase != "PRE":
            raise RescaleProtocolError(
                f"fence in phase {self.phase} — one re-cut per "
                f"coordinator")
        self.phase = "FENCED"
        self.fence_checkpoint = int(checkpoint_id)
        self._observe("fence", checkpoint_id=self.fence_checkpoint)

    def drain(self, group: int, n: int = 1) -> None:
        """``n`` buffered records of ``group`` handed into the
        migration payload."""
        group = self._check_group(group)
        if self.phase != "FENCED":
            raise RescaleProtocolError(
                f"drain({group}) in phase {self.phase} — draining is "
                f"only legal between fence and redirect")
        if self.migrated[group]:
            raise RescaleProtocolError(
                f"drain({group}) after the group migrated — the old "
                f"incarnation no longer owns it (stale writer)")
        if self.inflight[group] < n:
            raise RescaleProtocolError(
                f"drain({group}, {n}) exceeds the {self.inflight[group]} "
                f"record(s) in flight")
        self.inflight[group] -= int(n)
        self._observe("drain", group=group, n=int(n))

    def migrate(self, group: int) -> None:
        """Group ``group``'s keyed state moves to the new incarnation."""
        group = self._check_group(group)
        if self.phase != "FENCED":
            raise RescaleProtocolError(
                f"migrate({group}) in phase {self.phase}")
        if self.migrated[group]:
            raise RescaleProtocolError(f"group {group} already migrated")
        if self.inflight[group] != 0:
            raise RescaleProtocolError(
                f"migrate({group}) with {self.inflight[group]} in-flight "
                f"record(s) undrained — they would die with the old "
                f"incarnation at redirect (records lost)")
        self.migrated[group] = True
        self._observe("migrate", group=group)

    def redirect(self) -> None:
        """Traffic cuts over to the new incarnation. FENCED →
        REDIRECTED; the old incarnation is fenced off."""
        if self.phase != "FENCED":
            raise RescaleProtocolError(
                f"redirect in phase {self.phase}")
        missing = [g for g in range(self.num_groups)
                   if not self.migrated[g]]
        if missing:
            raise RescaleProtocolError(
                f"redirect with group(s) {missing} unmigrated — they "
                f"would restart empty on the new incarnation")
        self.phase = "REDIRECTED"
        self._observe("redirect")


def _load_job(spec: str) -> JobGraph:
    """'module.path:function' -> JobGraph (the CLI's job-spec form; both
    the JobMaster and every worker resolve the same spec)."""
    mod_name, _, fn_name = spec.partition(":")
    mod = importlib.import_module(mod_name)
    job = getattr(mod, fn_name or "build_job")()
    if not isinstance(job, JobGraph):
        raise TypeError(f"{spec} returned {type(job).__name__}, "
                        f"not JobGraph")
    return job


# --- placement ---------------------------------------------------------------


def partition_vertices(job: JobGraph, k: int) -> List[List[int]]:
    """Cut the topological order into ``k`` contiguous, non-empty slices
    balanced by subtask count, with every cut landing where ALL crossing
    edges are exchange edges (the wire-export constraint —
    ``JobGraph.subgraph``). Deterministic for a given job."""
    order = job.topo_order()
    n = len(order)
    if not 1 <= k <= n:
        raise ValueError(f"partition_vertices: cannot cut {n} vertices "
                         f"into {k} slices")
    pos = {vid: i for i, vid in enumerate(order)}
    valid = [i for i in range(1, n)
             if all(e.partition != PartitionType.FORWARD
                    for e in job.edges if pos[e.src] < i <= pos[e.dst])]
    if len(valid) < k - 1:
        raise ValueError(
            f"partition_vertices: only {len(valid)} exchange-edge cut "
            f"points for {k} slices — fewer workers or more exchanges")
    weights = [job.vertices[vid].parallelism for vid in order]
    total = sum(weights)
    prefix = np.cumsum([0] + weights)          # prefix[i] = subtasks before i
    cuts: List[int] = []
    for j in range(1, k):
        target = total * j / k
        # Closest valid cut to the balance target, strictly after the
        # previous cut and leaving enough cut points for the slices left.
        lo = cuts[-1] if cuts else 0
        cands = [i for i in valid if i > lo]
        cands = cands[: len(cands) - (k - 1 - j)]
        if not cands:
            raise ValueError("partition_vertices: cut points exhausted")
        cuts.append(min(cands, key=lambda i: (abs(prefix[i] - target), i)))
    bounds = [0] + cuts + [n]
    return [order[bounds[i]: bounds[i + 1]] for i in range(k)]


def cut_edges(job: JobGraph, part: Sequence[int]
              ) -> Tuple[List[int], List[int]]:
    """(in-cut, out-cut) original edge indices for a vertex slice."""
    keep = set(part)
    ins = [i for i, e in enumerate(job.edges)
           if e.dst in keep and e.src not in keep]
    outs = [i for i, e in enumerate(job.edges)
            if e.src in keep and e.dst not in keep]
    return ins, outs


@dataclasses.dataclass
class TaskSlot:
    """One deployment slot on a worker (SlotPool's allocation unit).

    ``group`` is the occupying task-group key: a bare int in legacy
    single-job mode, a ``(job_id, group)`` tuple when many jobs share
    the pool (runtime/dispatcher.py) — the pool only needs it hashable
    and orderable within one deployment."""

    worker_id: str
    index: int
    group: Optional[object] = None     # occupying task group, or free


class SlotPool:
    """JobMaster-side ledger of advertised slots and their occupants
    (reference SlotPool.java: offers come in from TaskExecutors, the
    scheduler allocates against them, a dead worker releases its slots
    and strands its groups for redeployment). One pool may be shared by
    many jobs' schedulers — group keys are then job-scoped tuples."""

    def __init__(self):
        self._slots: Dict[str, List[TaskSlot]] = {}

    def sync_offers(self, offers: Dict[str, int]) -> None:
        """Absorb the JobMasterServer's current slot advertisements
        (idempotent; capacity only grows — a shrinking advertisement
        never yanks a slot out from under a running task)."""
        for wid, cap in offers.items():
            cur = self._slots.setdefault(wid, [])
            while len(cur) < cap:
                cur.append(TaskSlot(wid, len(cur)))

    def workers(self) -> List[str]:
        return sorted(w for w, ss in self._slots.items() if ss)

    def free_slots(self, avoid: Sequence[str] = ()) -> List[TaskSlot]:
        return [s for w in self.workers() if w not in set(avoid)
                for s in self._slots[w] if s.group is None]

    def allocate(self, group, prefer: Optional[str] = None,
                 avoid: Sequence[str] = ()) -> TaskSlot:
        free = self.free_slots(avoid)
        if prefer is not None:
            preferred = [s for s in free if s.worker_id == prefer]
            free = preferred or free
        if not free:
            raise RuntimeError(
                f"SlotPool: no free slot for group {group} "
                f"(avoid={sorted(set(avoid))})")
        slot = free[0]
        slot.group = group
        return slot

    def release_group(self, group) -> None:
        for ss in self._slots.values():
            for s in ss:
                if s.group == group:
                    s.group = None

    def drop_worker(self, worker_id: str) -> List[object]:
        """Worker died: forget its slots; returns the task groups that
        were running there (the redeployment work list)."""
        lost = self._slots.pop(worker_id, [])
        return sorted(s.group for s in lost if s.group is not None)

    def placements(self) -> Dict[object, str]:
        return {s.group: w for w, ss in self._slots.items()
                for s in ss if s.group is not None}


# --- cross-worker edges ------------------------------------------------------


class EdgeExportServer:
    """Serves a slice's cut out-edges to downstream workers.

    At every epoch fence the worker's main thread calls :meth:`publish`:
    the fresh steps of each cut edge's producer ring are snapshotted and
    their valid records appended — flattened in (step, lane, slot)
    order, which is deterministic — to a retained per-edge buffer.
    Remote readers fetch ``[start, start+n)`` windows by ABSOLUTE record
    offset (FETCH_EDGE), so the stream is rewindable for causal replay;
    retention is currently unbounded (the ``floor`` field in EDGE_DATA
    reserves the trim protocol). The wire analog of handing the in-flight
    log across hosts (reference InFlightLogRequestEvent), lifted to
    record streams so the consumer can be a HostFeedSource boundary."""

    def __init__(self, runner, exports: Dict[int, int],
                 host: str = "127.0.0.1", port: int = 0):
        self.runner = runner
        self._srcs = {int(e): int(vid) for e, vid in exports.items()}
        self._recs: Dict[int, np.ndarray] = {
            e: np.zeros((0, 2), np.int32) for e in self._srcs}
        self._published: Dict[int, Optional[int]] = {
            e: None for e in self._srcs}
        self._final = False
        self._lock = threading.Lock()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address
        # Publish inside run_epoch's fence window: checkpoint completion
        # truncates the producer rings at the fence, so reading their
        # fresh steps AFTER run_epoch returns would already be too late.
        runner.fence_hooks.append(lambda _closed: self.publish())

    def publish(self) -> None:
        """Main-thread fence hook: absorb each producer ring's fresh
        steps into the retained record buffers."""
        from clonos_tpu.inflight import log as ifl
        import jax.numpy as jnp
        for eidx, vid in self._srcs.items():
            ri = self.runner.executor.compiled.ring_index[vid]
            el = self.runner.executor.carry.out_rings[ri]
            head, tail = int(el.head), int(el.tail)
            lo = self._published[eidx]
            if lo is None:
                # First publish: a fresh runner's ring starts at 0; a
                # REBUILT runner's ring starts at its recovery fence (it
                # re-exports only what replay retained — see module
                # docstring on failed-upstream chains).
                lo = tail
            if lo < tail:
                raise RuntimeError(
                    f"edge export {eidx}: ring truncated past the last "
                    f"published step ({lo} < tail {tail}) — publish at "
                    f"every fence")
            if head <= lo:
                continue
            n = head - lo
            batch, _, _ = ifl.slice_steps(el, jnp.asarray(lo, jnp.int32), n)
            keys = np.asarray(batch.keys)[:n]
            vals = np.asarray(batch.values)[:n]
            mask = np.asarray(batch.valid)[:n].astype(bool)
            recs = np.stack([keys[mask], vals[mask]], axis=1)
            with self._lock:
                if recs.shape[0]:
                    self._recs[eidx] = np.concatenate(
                        [self._recs[eidx], recs.astype(np.int32)])
                self._published[eidx] = head

    def mark_final(self) -> None:
        """The producing slice finished its run: readers blocked past the
        end of the stream fail loudly instead of waiting forever."""
        with self._lock:
            self._final = True

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype != tp.FETCH_EDGE:
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        req = tp.unpack_json(payload)
        tp.adopt_trace(req)
        tp.adopt_hlc(req, verb="FETCH_EDGE")
        eidx, start, count = (int(req["edge"]), int(req["start"]),
                              int(req["count"]))
        with self._lock:
            if eidx not in self._recs:
                return tp.ERROR, tp.pack_json(
                    {"error": f"edge {eidx} is not exported here "
                              f"(have {sorted(self._recs)})"})
            arr = self._recs[eidx]
            final = self._final
        avail = arr.shape[0]
        lo, hi = min(start, avail), min(start + count, avail)
        rows = np.ascontiguousarray(arr[lo:hi])
        tr = get_tracer()
        if tr.enabled and hi > lo:
            # only non-empty serves — blocked readers poll this endpoint
            tr.event("edge.serve", edge=eidx, start=lo, count=hi - lo)
        hdr = tp.pack_json({"edge": eidx, "start": lo,
                            "count": int(hi - lo), "avail": avail,
                            "floor": 0, "final": final})
        return tp.EDGE_DATA, (len(hdr).to_bytes(4, "little") + hdr
                              + rows.tobytes())

    def close(self) -> None:
        self.server.close()


class RemoteEdgeFeedReader:
    """Rewindable feed over a remote :class:`EdgeExportServer` — the
    downstream side of a cut edge (api/feeds.py contract).

    Live pulls BLOCK until the full requested count is available:
    deterministic per-step batch boundaries are what make a spanned
    job's digests reproducible across runs (and are exactly what the
    BUFFER_BUILT determinants pin for replay); "serve what has arrived"
    would make them timing-dependent. ``read_at`` re-fetches exact
    absolute ranges during causal replay. A stream the upstream marked
    final, or a wait past ``timeout_s``, raises instead of hanging —
    a stalled upstream must surface, not deadlock the worker loop."""

    def __init__(self, address: Tuple[str, int], edge: int,
                 num_subtasks: int = 1, poll_s: float = 0.02,
                 timeout_s: float = 180.0):
        if num_subtasks != 1:
            raise ValueError(
                "RemoteEdgeFeedReader serves one flattened stream; the "
                "boundary HostFeedSource runs at parallelism 1")
        self._address = tuple(address)
        self._edge = int(edge)
        self._client = tp.ControlClient(self._address)
        self._cursor = [0]
        self._poll = poll_s
        self._timeout = timeout_s
        # Pulls run on the worker main thread; checkpoint-complete
        # notifications may arrive from a coordinator writer thread.
        self._lock = threading.RLock()

    def _fetch_exact(self, start: int, n: int) -> np.ndarray:
        """Blocking fetch of records [start, start+n) as [n, 2] int32."""
        if n == 0:
            return np.zeros((0, 2), np.int32)
        deadline = time.monotonic() + self._timeout
        while True:
            with self._lock:
                rt, resp = self._client.call(
                    tp.FETCH_EDGE,
                    tp.pack_json(tp.attach_hlc(tp.attach_trace(
                        {"edge": self._edge, "start": start,
                         "count": n}), verb="FETCH_EDGE")))
            if rt == tp.ERROR:
                raise RuntimeError(tp.unpack_json(resp)["error"])
            hlen = int.from_bytes(resp[:4], "little")
            hdr = tp.unpack_json(resp[4: 4 + hlen])
            if int(hdr["floor"]) > start:
                from clonos_tpu.api.feeds import RetentionExpiredError
                raise RetentionExpiredError(
                    f"edge {self._edge}: offset {start} below upstream "
                    f"retention floor {hdr['floor']}")
            if int(hdr["count"]) == n:
                rows = np.frombuffer(resp[4 + hlen:], np.int32)
                return rows.reshape(n, 2)
            if hdr.get("final") and int(hdr["avail"]) < start + n:
                raise RuntimeError(
                    f"edge {self._edge}: upstream finished with "
                    f"{hdr['avail']} records; cannot serve "
                    f"[{start}, {start + n})")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"edge {self._edge}: waited {self._timeout}s for "
                    f"records [{start}, {start + n}) "
                    f"(upstream at {hdr['avail']}) — upstream stalled "
                    f"or co-hosted slice ordering starves this feed")
            time.sleep(self._poll)

    def seek(self, subtask: int, offset: int) -> None:
        """Reposition the live cursor (after a bootstrap replay, the
        cursor resumes at the recovered HostFeedSource offset)."""
        self._cursor[subtask] = int(offset)

    def rewire(self, address: Tuple[str, int]) -> None:
        """Point at a redeployed upstream's export endpoint."""
        with self._lock:
            self._client.close()
            self._address = tuple(address)
            self._client = tp.ControlClient(self._address)

    # --- FeedReader contract -------------------------------------------------

    def pull(self, subtask: int, max_n: int):
        rows = self._fetch_exact(self._cursor[subtask], max_n)
        self._cursor[subtask] += max_n
        return rows[:, 0].tolist(), rows[:, 1].tolist()

    def pull_block(self, subtask: int, batch: int, k: int):
        flat = self._fetch_exact(self._cursor[subtask], k * batch)
        self._cursor[subtask] += k * batch
        blk = flat.reshape(k, batch, 2)
        return (np.ascontiguousarray(blk[:, :, 0]),
                np.ascontiguousarray(blk[:, :, 1]),
                np.full((k,), batch, np.int32))

    def read_at(self, subtask: int, offset: int, n: int):
        rows = self._fetch_exact(int(offset), int(n))
        return rows[:, 0].tolist(), rows[:, 1].tolist()

    def notify_checkpoint_complete(self, offsets: Sequence[int]) -> None:
        """No-op: upstream retention is unbounded for now (the EDGE_DATA
        ``floor`` field reserves the trim protocol)."""

    def close(self) -> None:
        self._client.close()


# --- worker side -------------------------------------------------------------


class TaskExecutorEndpoint:
    """Worker-side deployment gateway (TaskExecutorGateway.submitTask).

    Every DEPLOY carries the JobMaster's fencing token; it is checked
    against (a) the shared lease directory — the token must be the
    highest EXISTING claim (``FileLeaderElection.fencing_valid``) — and
    (b) the highest token this worker has ever accepted, which stays
    monotone even while the lease directory is briefly unreadable. A
    deposed JobMaster's late deployment orders are rejected at this
    door, before any runner state is touched. Accepted descriptors are
    queued; the MAIN loop builds them (jax dispatch stays on the main
    thread)."""

    def __init__(self, lease_path: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.queue: "queue.Queue[dict]" = queue.Queue()
        self._lease_path = lease_path
        # Highest accepted token PER JOB: every job runs its own
        # election (leader.job_lease_path), so epoch sequences are
        # independent — job A's epoch 5 must not fence job B's epoch 1.
        # "" is the legacy single-job cluster.
        self._highest: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: transition observers: ``fn(kind, **fields)`` on every
        #: fencing decision (accept / reject-stale / reject-invalid) —
        #: the verify conformance layer's receiver-side surface.
        self.transition_observers: List = []
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    def _observe(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    def _check_fencing(self, epoch, job_id: str = "") -> None:
        if epoch is None:
            self._observe("fence-reject", job_id=job_id, epoch=None,
                          why="missing")
            raise PermissionError("DEPLOY carries no fencing token")
        epoch = int(epoch)
        with self._lock:
            if epoch < self._highest.get(job_id, -1):
                self._observe("fence-reject", job_id=job_id,
                              epoch=epoch, why="stale")
                raise PermissionError(
                    f"stale fencing token {epoch} < highest accepted "
                    f"{self._highest[job_id]} (deposed JobMaster)")
        if self._lease_path is not None:
            observer = FileLeaderElection(
                job_lease_path(self._lease_path, job_id), "observer")
            if not observer.fencing_valid(epoch):
                self._observe("fence-reject", job_id=job_id,
                              epoch=epoch, why="not-current-claim")
                raise PermissionError(
                    f"fencing token {epoch} is not the current lease "
                    f"claim — deposed or forged JobMaster identity")
        with self._lock:
            self._highest[job_id] = max(self._highest.get(job_id, -1),
                                        epoch)
        self._observe("fence-accept", job_id=job_id, epoch=epoch)

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype != tp.DEPLOY:
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        hlen = int.from_bytes(payload[:4], "little")
        tdd = tp.unpack_json(payload[4: 4 + hlen])
        try:
            self._check_fencing(tdd.get("fencing_epoch"),
                                str(tdd.get("job_id") or ""))
        except PermissionError as e:
            return tp.ERROR, tp.pack_json({"error": str(e)})
        frame = payload[4 + hlen:]
        if frame:
            tdd["_mirror_rows"] = {
                flat: (np.asarray(rows, np.int32), start)
                for flat, start, rows in serde.decode_delta(frame)}
        self.queue.put(tdd)
        return tp.OK, tp.pack_json({"accepted": True,
                                    "group": tdd.get("group")})

    def close(self) -> None:
        self.server.close()


@dataclasses.dataclass
class _DeployedSlice:
    group: int
    runner: object
    log_ep: rm.HostLogEndpoint
    export: Optional[EdgeExportServer]
    readers: Dict[int, object]
    target_epochs: int
    complete_every: int
    attempt: int
    finished: bool = False
    job_id: str = ""                   # "" = legacy single-job cluster
    #: the deploying JobMaster's trace context — re-adopted before every
    #: epoch so co-hosted slices of DIFFERENT jobs each span under their
    #: own job's trace id
    trace_ctx: Optional[dict] = None


class SliceWorker:
    """TaskExecutor-process driver: advertise slots, accept fenced
    DEPLOYs, and run every deployed slice's epochs round-robin on the
    main thread — publishing its edge exports and refreshing its
    determinant-log endpoint at every fence, reporting TASK_STATE
    transitions, and emitting one JSON status line per (group, epoch) on
    stdout (digest BEFORE the endpoint refresh, so a mirror never holds
    a fence whose digest was not reported)."""

    def __init__(self, executor_id: str, jm_address: Tuple[str, int],
                 lease_path: Optional[str] = None, slots: int = 1,
                 bind_host: str = "127.0.0.1",
                 heartbeat_interval: float = 0.5, emit=None,
                 chaos_step_delay_s: float = 0.0):
        self.executor_id = executor_id
        self.bind_host = bind_host
        #: gray-failure injection surface for the soak/chaos harness
        #: (``clonos_tpu slotworker --chaos-step-delay``): every epoch
        #: round sleeps this long FIRST, so the worker is degraded — its
        #: fences run late and co-hosted tenants see the slowdown — but
        #: never dead: heartbeats keep flowing and the JobMaster must
        #: classify it via HeartbeatMonitor.degraded(), not expired().
        self.chaos_step_delay_s = float(chaos_step_delay_s)
        self.endpoint = TaskExecutorEndpoint(lease_path, bind_host)
        self._jm = tp.ControlClient(tuple(jm_address))
        # Heartbeats piggyback the worker's last metric snapshot so the
        # JobMaster aggregates a cluster view (JobMasterServer
        # .cluster_metrics). The cache is refreshed on the MAIN loop —
        # snapshot() evaluates watchdog gauges that read device state,
        # and jax dispatch is main-thread-only — the heartbeat thread
        # only ships the cached host dict.
        self._metrics_cache: Dict[str, object] = {}
        self._metrics_lock = threading.Lock()
        self.tx = rm.TaskExecutorClient(
            executor_id, jm_address, interval_s=heartbeat_interval,
            info={"slots": slots, "deploy_host": bind_host,
                  "deploy_port": self.endpoint.address[1]},
            payload_fn=self._hb_payload)
        #: deployed slices keyed (job_id, group) — one worker may host
        #: slices of many concurrent jobs (the multi-tenant pool)
        self.slices: Dict[Tuple[str, int], _DeployedSlice] = {}
        #: recovery rebuilds deferred behind healthy epochs (fence
        #: priority — see :meth:`step`)
        self._recovery_backlog: Deque[dict] = collections.deque()
        self._emit = emit or (lambda obj: print(json.dumps(obj),
                                                flush=True))

    def _hb_payload(self) -> dict:
        with self._metrics_lock:
            cache = self._metrics_cache
        return {"metrics": cache} if cache else {}

    def _refresh_metrics(self) -> None:
        """Main-thread snapshot of every slice's registry (replaces the
        cache wholesale; the heartbeat thread only reads the old ref).
        Job-scoped slices prefix ``job.<jid>.`` so the JobMaster can
        roll metrics up per tenant (remote.cluster_metrics)."""
        snap: Dict[str, object] = {}
        for (jid, group), sl in self.slices.items():
            prefix = (f"job.{jid}.group.{group}." if jid
                      else f"group.{group}.")
            for k, v in sl.runner.metrics.snapshot().items():
                snap[prefix + k] = v
        with self._metrics_lock:
            self._metrics_cache = snap

    def _task_state(self, group: int, state: str, job_id: str = "",
                    **extra) -> None:
        msg = {"executor_id": self.executor_id, "group": group,
               "state": state, **extra}
        if job_id:
            msg["job_id"] = job_id
        try:
            self._jm.call_json(tp.TASK_STATE, msg)
        except (OSError, RuntimeError):
            pass        # JM unreachable; its heartbeat deadline arbitrates

    def _make_reader(self, spec: dict):
        kind = spec.get("kind")
        if kind == "edge":
            return RemoteEdgeFeedReader(
                (spec["host"], int(spec["port"])), edge=int(spec["edge"]),
                timeout_s=float(spec.get("timeout_s", 180.0)))
        if kind == "socket":
            from clonos_tpu.api.feeds import SocketFeedReader
            return SocketFeedReader(
                spec["host"], int(spec["port"]),
                num_subtasks=int(spec.get("num_subtasks", 1)),
                retention=spec.get("retention"))
        raise ValueError(f"unknown feed kind {kind!r}")

    def build(self, tdd: dict) -> _DeployedSlice:
        """Materialize one deployment descriptor into a running slice
        (fresh runner, or a ``bootstrap_standby`` causal rebuild when
        the descriptor ships mirror rows)."""
        from clonos_tpu.runtime.cluster import ClusterRunner
        group = int(tdd["group"])
        jid = str(tdd.get("job_id") or "")
        attempt = int(tdd.get("attempt", 0))
        # Join the deploying JobMaster's trace: every span this worker
        # emits for THIS slice (epochs, checkpoints, recovery phases)
        # shares its id — per job, since each job's JobMaster runs its
        # own tracer (the context is kept on the slice and re-adopted
        # before every epoch). Likewise its audit stance (a JobMaster
        # with auditing on makes every deployed runner seal + validate
        # epoch digests) and its profiling stance (overhead attribution
        # spans the slot pool).
        tp.adopt_trace(tdd)
        tp.adopt_audit(tdd)
        tp.adopt_profile(tdd)
        tp.adopt_lineage(tdd)
        tp.adopt_hlc(tdd, verb="DEPLOY")
        tr = get_tracer()
        self._task_state(group, "DEPLOYING", job_id=jid, attempt=attempt)
        job = _load_job(tdd["job"])
        sub, vmap, feeds, exports = job.subgraph(
            [int(v) for v in tdd["vertices"]],
            feed_batch_size=int(tdd.get("feed_batch", 8)))
        readers: Dict[int, object] = {}
        for eidx_s, spec in (tdd.get("feeds") or {}).items():
            readers[feeds[int(eidx_s)]] = self._make_reader(spec)
        for vid_s, spec in (tdd.get("external_feeds") or {}).items():
            readers[vmap[int(vid_s)]] = self._make_reader(spec)
        kw = dict(tdd.get("runner_kw") or {})
        recovered = bool(tdd.get("recover"))
        span_kw = {"job": jid} if jid else {}
        if recovered:
            with tr.span("recovery.rebuild", group=group,
                         attempt=attempt, **span_kw):
                runner, _report = ClusterRunner.bootstrap_standby(
                    sub, tdd["checkpoint_dir"],
                    tdd.get("_mirror_rows") or {},
                    ignored_checkpoints=tdd.get("ignored") or (),
                    feed_readers=readers, **kw)
            # Live pulls resume at the replayed feed offsets.
            for nvid, r in readers.items():
                if hasattr(r, "seek"):
                    off = np.asarray(
                        runner.executor.vertex_state(nvid)["offset"])
                    for s in range(off.shape[0]):
                        r.seek(s, int(off[s]))
        else:
            runner = ClusterRunner(sub, checkpoint_dir=tdd["checkpoint_dir"],
                                   **kw)
            for nvid, r in readers.items():
                runner.executor.register_feed(nvid, r)
        export = (EdgeExportServer(runner, exports, host=self.bind_host)
                  if exports else None)
        if export is not None:
            export.publish()
        log_ep = rm.HostLogEndpoint(runner.executor, host=self.bind_host)
        sl = _DeployedSlice(
            group=group, runner=runner, log_ep=log_ep, export=export,
            readers=readers,
            target_epochs=int(tdd.get("target_epochs", 8)),
            complete_every=int(tdd.get("complete_every", 1)),
            attempt=attempt, job_id=jid, trace_ctx=tdd.get("trace"))
        self.slices[(jid, group)] = sl
        if recovered:
            tr.event("recovery.caught_up", group=group, attempt=attempt,
                     epoch=runner.executor.epoch_id,
                     global_step=runner.global_step, **span_kw)
        self._task_state(
            group, "RUNNING", job_id=jid, attempt=attempt,
            log_port=log_ep.address[1],
            export_ports={str(e): export.address[1] for e in exports}
            if export else {},
            num_subtasks=sub.total_subtasks(), recovered=recovered)
        status = {"deployed": group, "attempt": attempt,
                  "vertices": [int(v) for v in tdd["vertices"]],
                  "recovered": recovered,
                  "epoch": runner.executor.epoch_id,
                  "global_step": runner.global_step,
                  "digest": runner.state_digest()}
        if jid:
            status["job"] = jid
        self._emit(status)
        return sl

    def step(self) -> bool:
        """Drain pending deployments, run one epoch of every due slice,
        then build AT MOST ONE recovery rebuild. Returns whether
        anything progressed.

        Ordering is the worker-side tenant-isolation mechanism: fresh
        deployments build immediately, but recovery rebuilds (causal
        replay — the expensive part of another tenant's failure storm)
        are deferred to a backlog and admitted one per round, AFTER
        every healthy slice has run its epoch. Between any two rebuilds
        every co-hosted healthy tenant therefore reaches its next
        checkpoint fence — a storm of N rebuilds inflates a neighbor's
        fence latency by at most one rebuild each round, never by the
        whole storm."""
        progressed = False
        while True:
            try:
                tdd = self.endpoint.queue.get_nowait()
            except queue.Empty:
                break
            if tdd.get("recover"):
                self._recovery_backlog.append(tdd)
            else:
                self.build(tdd)
            progressed = True
        tr = get_tracer()
        for key in sorted(self.slices):
            sl = self.slices[key]
            group = sl.group
            if tr.enabled and sl.trace_ctx:
                # Each slice's spans land under its OWN job's trace id.
                tr.adopt(sl.trace_ctx)
            if sl.runner.executor.epoch_id >= sl.target_epochs:
                if not sl.finished:
                    sl.finished = True
                    if sl.export is not None:
                        sl.export.mark_final()
                    self._task_state(group, "FINISHED", job_id=sl.job_id,
                                     attempt=sl.attempt)
                    status = {"finished": group,
                              "epoch": sl.runner.executor.epoch_id,
                              "global_step": sl.runner.global_step,
                              "digest": sl.runner.state_digest()}
                    if sl.job_id:
                        status["job"] = sl.job_id
                    self._emit(status)
                continue
            closed = sl.runner.executor.epoch_id
            if self.chaos_step_delay_s:
                time.sleep(self.chaos_step_delay_s)
            sl.runner.run_epoch(
                complete_checkpoint=(closed % sl.complete_every == 0))
            # Status BEFORE the refresh (see class docstring).
            status = {"group": group,
                      "epoch": sl.runner.executor.epoch_id,
                      "global_step": sl.runner.global_step,
                      "digest": sl.runner.state_digest()}
            if sl.job_id:
                status["job"] = sl.job_id
            self._emit(status)
            sl.log_ep.refresh()
            progressed = True
        if self._recovery_backlog:
            self.build(self._recovery_backlog.popleft())
            progressed = True
        if progressed:
            self._refresh_metrics()
        return progressed

    def run(self, max_seconds: float = 600.0, idle_sleep: float = 0.05,
            epoch_sleep: float = 0.0) -> None:
        """Serve until killed (or the wall guard lapses): finished
        slices keep their exports and log endpoints up — downstream
        workers and JobMaster mirrors still read them."""
        deadline = time.monotonic() + max_seconds
        while time.monotonic() < deadline:
            if self.step():
                if epoch_sleep:
                    time.sleep(epoch_sleep)
            else:
                time.sleep(idle_sleep)

    def close(self) -> None:
        self.tx.close()
        self._jm.close()
        self.endpoint.close()
        for sl in self.slices.values():
            sl.log_ep.close()
            if sl.export is not None:
                sl.export.close()


# --- JobMaster side ----------------------------------------------------------


class SlotPoolScheduler:
    """JobMaster-side deployment driver: partition the job over the
    registered workers' slots, deploy each slice with standby
    anti-affinity, mirror every slice's determinant logs, and on worker
    death redeploy ONLY the lost task groups (with their mirrored rows
    in the DEPLOY frame) onto surviving slots. Owns the
    :class:`FileLeaderElection` lease — every action requires a live
    renewal, and every outbound DEPLOY is stamped with the current
    fencing epoch (deposed incarnations are rejected worker-side)."""

    def __init__(self, jm: rm.JobMasterServer,
                 election: FileLeaderElection, job_spec: str,
                 runner_kw: Optional[dict] = None, feed_batch: int = 8,
                 target_epochs: int = 8, complete_every: int = 1,
                 checkpoint_root: str = "/tmp/clonos-scheduler",
                 mirror_capacity: int = 1 << 14,
                 mirror_max_epochs: int = 64,
                 deploy_timeout_s: float = 240.0,
                 job_id: str = "", tenant: str = "",
                 pool: Optional[SlotPool] = None, tracer=None):
        self.jm = jm
        self.election = election
        self.job_spec = job_spec
        self.job = _load_job(job_spec)
        self.runner_kw = dict(runner_kw or {})
        self.feed_batch = feed_batch
        self.target_epochs = target_epochs
        self.complete_every = complete_every
        self.checkpoint_root = checkpoint_root
        self.mirror_capacity = mirror_capacity
        self.mirror_max_epochs = mirror_max_epochs
        self.deploy_timeout_s = deploy_timeout_s
        #: multi-tenant identity (runtime/dispatcher.py): a non-empty
        #: job_id namespaces slot keys, DEPLOY headers, task_state
        #: lookups, and standby bookkeeping so many schedulers share one
        #: pool. "" is the legacy one-job-per-cluster mode.
        self.job_id = str(job_id)
        self.tenant = str(tenant)
        #: a dispatcher passes its SHARED pool; a standalone scheduler
        #: owns a private one and syncs offers itself on deploy()
        self.pool = SlotPool() if pool is None else pool
        self._owns_pool = pool is None
        #: per-job tracer injected by the dispatcher (each job's spans
        #: carry that job's trace id); None = the process tracer
        self._tracer = tracer
        self.parts: List[List[int]] = []
        self.placements: Dict[int, str] = {}
        self.standby: Dict[int, str] = {}
        self.mirrors: Dict[int, rm.RemoteReplicaMirror] = {}
        self.groups: Dict[int, dict] = {}          # deployed descriptors
        self._export_addr: Dict[int, Tuple[str, int]] = {}
        self._attempts: Dict[int, int] = {}
        self._deploy_clients: Dict[str, tp.ControlClient] = {}
        # JobMaster-side latency distributions for the scheduler's own
        # recovery phases (the worker-side phases ride heartbeats).
        from clonos_tpu.utils import metrics as met
        self.metrics = met.MetricRegistry()
        g = self.metrics.group("scheduler")
        self._m_deploy_ms = g.histogram("deploy-ms")
        self._m_fetch_ms = g.histogram("recovery.determinant-fetch-ms")
        self._m_redeploy_ms = g.histogram("recovery.redeploy-ms")
        self._detected: set = set()    # workers already traced as failed

    def _tr(self):
        return self._tracer if self._tracer is not None else get_tracer()

    def _slot_key(self, group: int):
        """Pool allocation key: job-scoped when this scheduler shares a
        pool with other jobs, the bare group in legacy mode."""
        return (self.job_id, int(group)) if self.job_id else int(group)

    # --- leadership ----------------------------------------------------------

    def _require_leadership(self) -> None:
        if not self.election.is_leader() or not self.election.renew():
            raise NotLeaderError(
                f"scheduler {self.election.contender_id!r} does not hold "
                f"the JobMaster lease — refusing to issue deployments")

    # --- plumbing ------------------------------------------------------------

    def _worker_info(self, worker_id: str) -> dict:
        info = self.jm.info(worker_id)
        if "deploy_port" not in info:
            raise RuntimeError(
                f"worker {worker_id} registered without a deploy "
                f"endpoint (not a slot worker)")
        return info

    def _deploy_client(self, worker_id: str) -> tp.ControlClient:
        if worker_id not in self._deploy_clients:
            info = self._worker_info(worker_id)
            self._deploy_clients[worker_id] = tp.ControlClient(
                (info.get("deploy_host", "127.0.0.1"),
                 int(info["deploy_port"])))
        return self._deploy_clients[worker_id]

    def _send_deploy(self, worker_id: str, tdd: dict,
                     frame: bytes = b"") -> dict:
        hdr = tp.pack_json(tdd)
        rt, resp = self._deploy_client(worker_id).call(
            tp.DEPLOY, len(hdr).to_bytes(4, "little") + hdr + frame)
        if rt == tp.ERROR:
            raise RuntimeError(tp.unpack_json(resp)["error"])
        return tp.unpack_json(resp)

    def _wait_running(self, worker_id: str, group: int,
                      attempt: int) -> dict:
        deadline = time.monotonic() + self.deploy_timeout_s
        while time.monotonic() < deadline:
            st = self.jm.task_state(worker_id, group, self.job_id)
            if (st and st.get("state") == "RUNNING"
                    and int(st.get("attempt", -1)) == attempt):
                return st
            time.sleep(0.05)
        raise TimeoutError(
            f"group {group} (attempt {attempt}) did not reach RUNNING "
            f"on {worker_id} within {self.deploy_timeout_s}s")

    def _descriptor(self, group: int, part: Sequence[int],
                    external_feeds: Dict[int, dict]) -> dict:
        ins, _outs = cut_edges(self.job, part)
        feeds_spec = {}
        for eidx in ins:
            if eidx not in self._export_addr:
                raise RuntimeError(
                    f"group {group}: upstream export for edge {eidx} "
                    f"not deployed yet (deploy slices in topo order)")
            host, port = self._export_addr[eidx]
            feeds_spec[str(eidx)] = {"kind": "edge", "host": host,
                                     "port": port, "edge": eidx}
        tdd = {
            "group": group,
            "job": self.job_spec,
            "vertices": [int(v) for v in part],
            "feed_batch": self.feed_batch,
            "feeds": feeds_spec,
            "external_feeds": {str(v): spec
                               for v, spec in external_feeds.items()
                               if v in set(part)},
            "checkpoint_dir": f"{self.checkpoint_root}/g{group}",
            "runner_kw": self.runner_kw,
            "target_epochs": self.target_epochs,
            "complete_every": self.complete_every,
            "standby_worker": self.standby.get(group),
        }
        if self.job_id:
            # Routes per-job worker state (slice keying, metric
            # prefixes, per-job fencing); absent in legacy mode so the
            # single-job wire bytes stay identical.
            tdd["job_id"] = self.job_id
            tdd["tenant"] = self.tenant
        return tdd

    def _place(self, group: int, tdd: dict, worker_id: str,
               frame: bytes = b"") -> dict:
        """Stamp, send, await RUNNING, and wire mirror + exports."""
        attempt = self._attempts.get(group, -1) + 1
        self._attempts[group] = attempt
        hdr = dict(tdd, attempt=attempt, fencing_epoch=self.election.epoch)
        # Like tp.attach_trace but through THIS job's tracer, so every
        # worker span for this slice joins this job's trace id.
        ctx = self._tr().wire_context()
        if ctx is not None:
            hdr["trace"] = ctx
        tdd = tp.attach_hlc(
            tp.attach_lineage(tp.attach_profile(tp.attach_audit(hdr))),
            verb="DEPLOY")
        span_kw = {"job": self.job_id} if self.job_id else {}
        t0 = time.monotonic()
        with self._tr().span("deploy", group=group, worker=worker_id,
                             attempt=attempt,
                             recover=bool(tdd.get("recover")), **span_kw):
            self._send_deploy(worker_id, tdd, frame)
            st = self._wait_running(worker_id, group, attempt)
        self._m_deploy_ms.update((time.monotonic() - t0) * 1e3)
        info = self._worker_info(worker_id)
        host = info.get("deploy_host", "127.0.0.1")
        _ins, outs = cut_edges(self.job, tdd["vertices"])
        for eidx in outs:
            self._export_addr[eidx] = (
                host, int(st["export_ports"][str(eidx)]))
        old = self.mirrors.pop(group, None)
        if old is not None:
            old.close()
        self.mirrors[group] = rm.RemoteReplicaMirror(
            (host, int(st["log_port"])),
            flats=list(range(int(st["num_subtasks"]))),
            capacity=self.mirror_capacity,
            max_epochs=self.mirror_max_epochs)
        self.placements[group] = worker_id
        self.groups[group] = tdd
        return st

    # --- deployment ----------------------------------------------------------

    def deploy(self, workers: Optional[List[str]] = None,
               external_feeds: Optional[Dict[int, dict]] = None,
               num_slices: Optional[int] = None) -> Dict[int, str]:
        """Partition the job across the given workers (default: every
        registered worker with slot capacity, in id order) and deploy
        slice by slice in topological order — each slice's cut in-edges
        dial the export endpoints its upstream slices just reported.
        ``num_slices`` decouples the cut count from the worker count
        (a tenant may ask for fewer slices than the pool has workers,
        or stack several slices per worker); default one slice per
        worker. Returns {group: worker}."""
        self._require_leadership()
        if self._owns_pool:
            self.pool.sync_offers(self.jm.slots())
        workers = list(workers) if workers else self.pool.workers()
        if not workers:
            raise RuntimeError("deploy: no workers with slots registered")
        k = int(num_slices) if num_slices else len(workers)
        self.parts = partition_vertices(self.job, k)
        order = standby_worker_order(len(workers))
        for gi in range(len(self.parts)):
            self.standby[gi] = workers[order[gi % len(workers)]]
        for gi, part in enumerate(self.parts):
            slot = self.pool.allocate(self._slot_key(gi),
                                      prefer=workers[gi % len(workers)])
            tdd = self._descriptor(gi, part, external_feeds or {})
            self._place(gi, tdd, slot.worker_id)
        return dict(self.placements)

    def sync(self) -> Dict[int, int]:
        """One mirror pull round over groups on healthy workers."""
        out = {}
        dead = set(self.jm.expired())
        for group, mirror in self.mirrors.items():
            if self.placements.get(group) in dead:
                continue
            try:
                out[group] = mirror.sync()
            except OSError:
                out[group] = -1      # endpoint gone; heartbeats decide
        return out

    def failed_workers(self) -> List[str]:
        placed = set(self.placements.values())
        out = [w for w in self.jm.expired() if w in placed]
        tr = self._tr()
        if tr.enabled:
            for w in out:
                if w not in self._detected:     # once per worker death
                    self._detected.add(w)
                    tr.event("recovery.detect", worker=w,
                             groups=sorted(
                                 g for g, pw in self.placements.items()
                                 if pw == w))
        return out

    def recover_worker(self, dead_worker: str,
                       max_groups: Optional[int] = None
                       ) -> Dict[int, str]:
        """A worker died: redeploy ONLY its task groups — preferring
        each group's standby worker (anti-affinity guarantees it is a
        different process) — shipping the mirrored determinant rows for
        the causal rebuild. Every other group keeps running untouched.
        ``max_groups`` caps how many groups ONE CALL redeploys (the
        dispatcher's per-tenant concurrent-recovery cap — remaining
        lost groups stay attributed to the dead worker and a later call
        picks them up). Returns {group: new worker}."""
        self._require_leadership()
        lost = sorted(g for g, w in self.placements.items()
                      if w == dead_worker)
        self.pool.drop_worker(dead_worker)       # idempotent across jobs
        self._deploy_clients.pop(dead_worker, None)
        if max_groups is not None:
            lost = lost[: max(0, int(max_groups))]
        with self.jm._lock:
            ignored = sorted(set(self.jm._ignored))
        moved: Dict[int, str] = {}
        tr = self._tr()
        span_kw = {"job": self.job_id} if self.job_id else {}
        t0 = time.monotonic()
        with tr.span("recovery.redeploy", worker=dead_worker,
                     groups=lost, **span_kw):
            for group in lost:
                target = self.standby.get(group)
                if (target == dead_worker
                        or target not in self.pool.workers()):
                    target = None
                slot = self.pool.allocate(self._slot_key(group),
                                          prefer=target,
                                          avoid=(dead_worker,))
                mirror = self.mirrors[group]
                tf = time.monotonic()
                with tr.span("recovery.determinant_fetch", group=group):
                    deltas = []
                    for flat in mirror.flats:
                        rows, start = mirror.rows_with_start(flat)
                        deltas.append(
                            (flat, start, np.asarray(rows, np.int32)))
                    frame = serde.encode_delta(deltas)
                self._m_fetch_ms.update((time.monotonic() - tf) * 1e3)
                tdd = dict(self.groups[group], recover=True,
                           ignored=ignored)
                self._place(group, tdd, slot.worker_id, frame)
                moved[group] = slot.worker_id
        self._m_redeploy_ms.update((time.monotonic() - t0) * 1e3)
        return moved

    def release_pool_slots(self) -> None:
        """Free every pool slot this job occupies (the dispatcher calls
        this on job completion / cancellation so queued jobs admit)."""
        for group in list(self.placements):
            self.pool.release_group(self._slot_key(group))

    def close(self) -> None:
        for m in self.mirrors.values():
            m.close()
        for c in self._deploy_clients.values():
            c.close()
