"""Read-path scale-out: queryable state served from standby replicas.

The standby pool already restores every completed checkpoint
(StandbyPool, reference Execution.java:373 re-dispatching state to
STANDBY executions) and the audit plane already extracts each sealed
epoch's causal surface at the fence (``epoch_window``). This module
composes the two into a read tier — the fault-tolerance mechanism
itself becomes the scale-out mechanism, the same move Clonos makes for
recovery:

- :class:`ReadReplica` keeps a restored checkpoint **fence-fresh** by
  tailing sealed-epoch deltas off the runner's serve feed
  (``ClusterRunner.serve_feeds``): for operators that emit their
  updated running value per record (``emits_running_value``, the
  KeyedReduceOperator contract), the LAST emitted value per key in the
  epoch's deterministic (step, lane, slot) order IS the fence value of
  that key — so scattering the epoch's output-ring window into the
  dense table reconstructs the owner's fence state **bit-identically by
  construction**. Operators without that property fall back to
  checkpoint-only freshness (larger but still honest staleness).

- :class:`ReplicaServeEndpoint` coalesces concurrent point lookups into
  ONE jitted gather per device dispatch (the ``epoch_row_windows``
  idiom applied to serving): transport threads enqueue keys, a single
  dispatch thread drains the queue and issues one fused
  ``acc[owner_subtask(keys), keys]`` read for the whole batch instead
  of N host round-trips into the carry. The dispatch region is wrapped
  in serve-window markers and lint-enforced dispatch-only
  (lint/overlapwindow.py) — a stray host sync there re-serializes the
  exact batching win.

- :class:`ServeRouter` routes lookups by key-group across owner +
  replicas with per-replica staleness bounds; a replica past its bound,
  dead, or mid-revival is skipped in favor of the owner (a counted
  REROUTE, never a client-visible error). Every response carries
  ``(epoch, staleness_epochs)`` — reads are never torn mid-epoch
  because replicas only ever publish whole sealed-epoch states.

Consistency model: a replica at epoch ``e`` serves exactly the state
the owner had at fence ``e`` — same key-group assignment, same values
(asserted bit-for-bit in tests/test_serve_replica.py). Staleness is
``last_sealed_epoch - replica_epoch``; the router's bound is the
per-replica freshness SLO.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from clonos_tpu.parallel import transport as tp
from clonos_tpu.runtime.query import (QueryRejectedError,
                                      QueryTimeoutError, _call_with_retry,
                                      owner_subtask_np)

#: padded gather bucket sizes — one compiled program per bucket, so a
#: mixed read load compiles O(log max_batch) programs, not one per
#: batch shape.
_MIN_BUCKET = 64


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


_gather_cache: Dict[Tuple[int, int], object] = {}


def _gather_fn(parallelism: int, num_key_groups: int):
    """ONE fused device read for a whole key batch: key -> key group ->
    owning subtask -> table entry, all inside a single jitted program
    (the device twin of :func:`owner_subtask_np` — same hash, same
    assignment, so replica reads agree with the exchange's routing
    byte-for-byte)."""
    key = (parallelism, num_key_groups)
    fn = _gather_cache.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        from clonos_tpu.parallel.routing import hash32

        def f(acc, keys):
            kg = (hash32(keys) % jnp.uint32(num_key_groups)
                  ).astype(jnp.int32)
            sub = (kg * parallelism) // num_key_groups
            return acc[sub, keys], sub, kg

        fn = jax.jit(f)
        _gather_cache[key] = fn
    return fn


class ReadReplica:
    """A standby's live read view of one vertex's dense keyed state.

    Restores from the standby pool's completed checkpoints and advances
    one sealed epoch at a time off the runner's serve feed. All feed
    callbacks are host-only (numpy) and lock-guarded — they run on the
    fence worker when the fence is pipelined."""

    def __init__(self, runner, vertex_id: int, state: str = "acc",
                 name: str = "replica-0"):
        self.runner = runner
        self.vertex_id = int(vertex_id)
        self.state_name = state
        self.name = name
        v = runner.job.vertices[self.vertex_id]
        self.parallelism = v.parallelism
        self.num_key_groups = runner.job.num_key_groups
        #: the operator's running-value contract is what makes
        #: output-ring tailing bit-exact; without it the replica is
        #: checkpoint-fresh only (honest, larger staleness).
        self.tailable = bool(getattr(v.operator, "emits_running_value",
                                     False))
        self._lock = threading.Lock()
        self._arr: Optional[np.ndarray] = None     # host [P, K]
        self._epoch = -1                           # fence the view is at
        self._owner_of: Optional[np.ndarray] = None
        self.alive = True
        self.applied_epochs = 0
        self.restores = 0
        self.revivals = 0
        #: device-side cache for the serve endpoint's fused gather —
        #: touched ONLY by the endpoint's single dispatch thread.
        self._dev = None
        self._dev_epoch = -1
        runner.serve_feeds.append(self._on_seal)
        runner.coordinator.subscribe_completed_state(self._on_checkpoint)
        ck = runner.standbys.latest
        if ck is not None:
            self._on_checkpoint(ck)

    # --- state plane (runner-side callbacks) -----------------------------

    def _table_from(self, ckpt) -> Optional[np.ndarray]:
        st = ckpt.carry.op_states[self.vertex_id]
        if not isinstance(st, dict) or self.state_name not in st:
            return None
        arr = np.array(st[self.state_name])
        if arr.ndim < 2 or arr.shape[0] != self.parallelism:
            return None
        return arr

    def _on_checkpoint(self, ckpt) -> None:
        """Standby restore path: adopt any completed checkpoint that is
        FRESHER than the current view (checkpoint id == the epoch it
        fences). For tailable operators the delta feed usually got
        there first and this is a no-op."""
        with self._lock:
            if not self.alive or ckpt.checkpoint_id <= self._epoch:
                return
            arr = self._table_from(ckpt)
            if arr is None:
                return
            self._adopt(arr, int(ckpt.checkpoint_id))
            self.restores += 1

    def _adopt(self, arr: np.ndarray, epoch: int) -> None:
        self._arr = arr
        self._epoch = epoch
        if self._owner_of is None or len(self._owner_of) != arr.shape[-1]:
            _, self._owner_of = owner_subtask_np(
                np.arange(arr.shape[-1]), self.parallelism,
                self.num_key_groups)

    def _on_seal(self, epoch: int, window) -> None:
        """Serve-feed tail: apply one sealed epoch's output-ring window.
        Contiguity rule: deltas only ever advance ``e-1 -> e``; any gap
        (late attach, revival) waits for the checkpoint path to close
        it — staleness stays OBSERVABLE rather than silently wrong."""
        with self._lock:
            if not self.alive:
                # Revival within one fence of the kill: re-adopt the
                # standby pool's restore point; the staleness spike is
                # (sealed - checkpoint) until completions catch up.
                ck = self.runner.standbys.latest
                if ck is None:
                    return
                arr = self._table_from(ck)
                if arr is None:
                    return
                self.alive = True
                self.revivals += 1
                self._epoch = -1
                self._adopt(arr, int(ck.checkpoint_id))
                self.restores += 1
            if (not self.tailable or self._arr is None
                    or self._epoch != epoch - 1 or window is None):
                return
            steps = window.get("rings", {}).get(self.vertex_id)
            if steps is None:
                return
            self._apply_running_values(steps)
            self._epoch = epoch
            self.applied_epochs += 1

    def _apply_running_values(self, steps) -> None:
        """Last-write-wins scatter of one epoch's emitted running values
        into the dense table: each valid record carries its key's value
        AFTER that record folded in, and the window's steps are in
        deterministic order — so the last record per key is exactly the
        owner's fence value for that key."""
        ks = [np.asarray(k, np.int64) for k, _, _ in steps if len(k)]
        vs = [np.asarray(v) for k, v, _ in steps if len(k)]
        if not ks:
            return
        keys = np.concatenate(ks)
        vals = np.concatenate(vs)
        # np.unique returns FIRST occurrences; reverse so "first in
        # reversed" == "last overall".
        rk, rv = keys[::-1], vals[::-1]
        uk, first = np.unique(rk, return_index=True)
        self._arr[self._owner_of[uk], uk] = rv[first]

    # --- serve plane -----------------------------------------------------

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def staleness_epochs(self) -> int:
        """How many fences behind the owner's last seal this view is
        (0 = fence-fresh; grows while dead or gapped)."""
        with self._lock:
            sealed = int(self.runner.last_sealed_epoch)
            if self._epoch < 0:
                return sealed + 1
            return max(0, sealed - self._epoch)

    def status(self) -> dict:
        with self._lock:
            sealed = int(self.runner.last_sealed_epoch)
            stal = (sealed + 1 if self._epoch < 0
                    else max(0, sealed - self._epoch))
            return {"epoch": self._epoch, "staleness_epochs": stal,
                    "alive": self.alive, "role": "replica",
                    "name": self.name, "tailable": self.tailable,
                    "applied_epochs": self.applied_epochs,
                    "restores": self.restores}

    def host_view(self) -> Tuple[Optional[np.ndarray], int]:
        """(table copy reference, epoch) under the lock — the table is
        mutated in place by the tail, so the device cache keys on the
        epoch stamp and re-uploads only when it moved."""
        with self._lock:
            if not self.alive or self._arr is None:
                return None, self._epoch
            return self._arr.copy(), self._epoch

    def device_view(self):
        """Device-resident table for the fused gather, cached per epoch
        stamp. Called only from the endpoint's single dispatch thread —
        the one thread allowed to touch the device on the serve path."""
        import jax.numpy as jnp
        arr, epoch = self.host_view()
        if arr is None:
            return None, epoch
        if epoch != self._dev_epoch or self._dev is None:
            dev = jnp.asarray(arr)
            with self._lock:
                self._dev = dev
                self._dev_epoch = epoch
            return dev, epoch
        return self._dev, epoch

    def kill(self) -> None:
        """Chaos surface (``replica-kill``): the replica stops serving
        and drops its view; the router must re-route to the owner with
        zero client-visible errors. Revives at the next seal. The epoch
        stamp resets too — a dead replica has NO view, so its staleness
        is ``sealed + 1`` (behind every fence), the spike the soak's
        degradation witness measures until revival recovers it."""
        with self._lock:
            self.alive = False
            self._arr = None
            self._epoch = -1
            self._dev = None
            self._dev_epoch = -1

    def rehome(self, new_runner) -> None:
        """Live re-cut (``ClusterRunner.rescale_live``): re-attach this
        replica to the NEW incarnation. The key-group->subtask owner map
        changes with the vertex's parallelism, so the old view's
        table SHAPE is wrong — drop it and re-adopt from the new
        runner's restore point (rescale_live re-fences the handoff
        checkpoint in the new shape, so one is always there). During
        the window between re-home and re-adopt the replica reads as
        dead: the router REROUTES to the owner, clients see staleness,
        never errors."""
        with self._lock:
            try:
                self.runner.serve_feeds.remove(self._on_seal)
            except ValueError:
                pass
            self.runner = new_runner
            v = new_runner.job.vertices[self.vertex_id]
            self.parallelism = v.parallelism
            self.num_key_groups = new_runner.job.num_key_groups
            self.tailable = bool(getattr(v.operator,
                                         "emits_running_value", False))
            self.alive = True
            self._arr = None
            self._epoch = -1
            self._owner_of = None
            self._dev = None
            self._dev_epoch = -1
        new_runner.serve_feeds.append(self._on_seal)
        new_runner.coordinator.subscribe_completed_state(
            self._on_checkpoint)
        ck = new_runner.standbys.latest
        if ck is not None:
            self._on_checkpoint(ck)

    def close(self) -> None:
        with self._lock:
            try:
                self.runner.serve_feeds.remove(self._on_seal)
            except ValueError:
                pass


class ReplicaServeEndpoint:
    """Serves a :class:`ReadReplica` over the control transport with
    request coalescing: transport threads enqueue keys and block on a
    ticket; a single dispatch thread drains the whole queue into ONE
    padded, jitted gather per device dispatch."""

    def __init__(self, replica: ReadReplica, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 4096):
        self.replica = replica
        self.max_batch = int(max_batch)
        self._cv = threading.Condition()
        self._pending: deque = deque()
        self._closed = False
        #: observability: device dispatches vs keys served — the
        #: coalescing ratio the batching win is made of.
        self.dispatches = 0
        self.keys_served = 0
        self.requests = 0
        self._thread = threading.Thread(
            target=self._serve_loop, name=f"serve-{replica.name}",
            daemon=True)
        self._thread.start()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    # --- transport side --------------------------------------------------

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype == tp.SERVE_STATUS:
            st = self.replica.status()
            st["dispatches"] = self.dispatches
            st["keys_served"] = self.keys_served
            return tp.QUERY_RESPONSE, tp.pack_json(st)
        if mtype not in (tp.QUERY_STATE, tp.QUERY_BATCH):
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        req = tp.unpack_json(payload)
        tp.adopt_hlc(req, verb="QUERY_STATE" if mtype == tp.QUERY_STATE
                     else "QUERY_BATCH")
        if req["vertex"] != self.replica.vertex_id or \
                req.get("state", "acc") != self.replica.state_name:
            return tp.ERROR, tp.pack_json(
                {"error": f"replica serves (vertex "
                          f"{self.replica.vertex_id}, "
                          f"{self.replica.state_name!r}) only"})
        single = mtype == tp.QUERY_STATE
        keys = np.asarray([req["key"]] if single else req["keys"],
                          np.int64)
        ticket = {"keys": keys, "event": threading.Event(),
                  "out": None, "err": None}
        with self._cv:
            if self._closed:
                return tp.ERROR, tp.pack_json(
                    {"error": "endpoint closed", "rejected": True})
            self._pending.append(ticket)
            self.requests += 1
            self._cv.notify()
        ticket["event"].wait()
        if ticket["err"] is not None:
            return tp.ERROR, tp.pack_json(ticket["err"])
        vals, subs, kgs, epoch, stal = ticket["out"]
        # ``replica`` + ``epoch`` are the provenance stamp a lineage
        # path terminates on (the router adds ``rerouted``).
        if single:
            return tp.QUERY_RESPONSE, tp.pack_json(
                {"value": vals[0], "subtask": subs[0],
                 "key_group": kgs[0], "epoch": epoch,
                 "staleness_epochs": stal, "served_by":
                 self.replica.name, "replica": self.replica.name})
        return tp.QUERY_BATCH_RESPONSE, tp.pack_json(
            {"values": vals, "subtasks": subs, "key_groups": kgs,
             "epoch": epoch, "staleness_epochs": stal,
             "served_by": self.replica.name,
             "replica": self.replica.name})

    # --- the single dispatch thread --------------------------------------

    def _serve_loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                batch: List[dict] = []
                n = 0
                while self._pending and n < self.max_batch:
                    t = self._pending.popleft()
                    batch.append(t)
                    n += len(t["keys"])
            try:
                self._dispatch(batch)
            except BaseException as e:   # keep the loop alive; fail the batch
                for t in batch:
                    if not t["event"].is_set():
                        t["err"] = {"error": f"serve dispatch failed: {e}",
                                    "rejected": True}
                        t["event"].set()

    def _dispatch(self, batch: List[dict]) -> None:
        import jax.numpy as jnp
        r = self.replica
        arr_dev, epoch = r.device_view()
        if arr_dev is None:
            why = ("replica dead" if not r.alive
                   else "replica has no restored state yet")
            for t in batch:
                t["err"] = {"error": why, "rejected": True}
                t["event"].set()
            return
        num_keys = arr_dev.shape[-1]
        all_keys = np.concatenate([t["keys"] for t in batch])
        if all_keys.min() < 0 or all_keys.max() >= num_keys:
            for t in batch:
                bad = (t["keys"].min() < 0
                       or t["keys"].max() >= num_keys)
                if bad:
                    t["err"] = {"error": f"key out of range "
                                         f"[0, {num_keys})"}
                    t["event"].set()
            batch = [t for t in batch if not t["event"].is_set()]
            if not batch:
                return
            all_keys = np.concatenate([t["keys"] for t in batch])
        n = len(all_keys)
        b = _bucket(n)
        padded = np.zeros(b, np.int32)
        padded[:n] = all_keys
        fn = _gather_fn(r.parallelism, r.num_key_groups)
        keys_dev = jnp.asarray(padded)
        # clonos: serve-window-begin
        vals_d, subs_d, kgs_d = fn(arr_dev, keys_dev)
        # clonos: serve-window-end
        # The drain happens OUTSIDE the marked window: the window is the
        # dispatch-only region (one fused gather for the whole coalesced
        # batch); blocking host reads belong here, after it.
        vals = np.asarray(vals_d)[:n].tolist()
        subs = np.asarray(subs_d)[:n].tolist()
        kgs = np.asarray(kgs_d)[:n].tolist()
        stal = r.staleness_epochs()
        self.dispatches += 1
        self.keys_served += n
        off = 0
        for t in batch:
            m = len(t["keys"])
            t["out"] = (vals[off:off + m], subs[off:off + m],
                        kgs[off:off + m], epoch, stal)
            off += m
            t["event"].set()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self.server.close()
        self._thread.join(timeout=5.0)


class ReplicaStateClient:
    """Client for a :class:`ReplicaServeEndpoint` (same wire protocol
    as QueryableStateClient, same timeout/backoff discipline). One
    connection, NOT thread-safe: concurrent readers hold one client
    each — the endpoint coalesces across connections, the socket does
    not."""

    def __init__(self, address, timeout_s: float = 5.0,
                 retries: int = 2, backoff_s: float = 0.05):
        self.address = tuple(address)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._client = tp.ControlClient(self.address,
                                        timeout_s=self.timeout_s)

    def _call(self, mtype: int, payload: dict) -> dict:
        if mtype in (tp.QUERY_STATE, tp.QUERY_BATCH):
            tp.attach_hlc(payload,
                          verb="QUERY_STATE" if mtype == tp.QUERY_STATE
                          else "QUERY_BATCH")
        rt, resp = _call_with_retry(
            self._client, mtype, tp.pack_json(payload), self.address,
            self.timeout_s, self.retries, self.backoff_s)
        out = tp.unpack_json(resp)
        if rt == tp.ERROR:
            if out.get("rejected"):
                raise QueryRejectedError(out["error"])
            raise KeyError(out["error"])
        return out

    def query(self, vertex: int, key: int, state: str = "acc") -> dict:
        return self._call(tp.QUERY_STATE,
                          {"vertex": vertex, "state": state, "key": key})

    def query_batch(self, vertex: int, keys: Sequence[int],
                    state: str = "acc") -> dict:
        return self._call(tp.QUERY_BATCH,
                          {"vertex": vertex, "state": state,
                           "keys": [int(k) for k in keys]})

    def status(self) -> dict:
        return self._call(tp.SERVE_STATUS, {})

    def close(self) -> None:
        self._client.close()


class ServeRouter:
    """Routes keyed lookups across owner + replicas by key group.

    Endpoints are duck-typed (``query`` / ``query_batch`` / ``status``)
    so the routing policy is unit-testable with fakes (no cluster).
    Policy: key -> key group -> replica ``kg % R``; the replica is used
    iff its last-known status is alive and within ``staleness_bound``
    sealed epochs of the owner; otherwise the read REROUTES to the
    owner (counted, never an error). Liveness failures against a
    replica (timeout / rejection / transport) also reroute — clients
    see degradation as staleness and latency, not exceptions."""

    def __init__(self, owner, replicas: Sequence,
                 num_key_groups: int, staleness_bound: int = 2,
                 status_ttl_s: float = 0.05, lineage=None):
        self.owner = owner
        #: lineage plane for serve-read termini (obs/lineage.py);
        #: None resolves to the process-global plane per read, so a
        #: router built before arming still records. Dyed keys only —
        #: the Null plane records nothing.
        self.lineage = lineage
        self.replicas = list(replicas)
        self.num_key_groups = int(num_key_groups)
        self.staleness_bound = int(staleness_bound)
        self.status_ttl_s = float(status_ttl_s)
        self.reads = 0
        self.reroutes = 0
        self.replica_reads = 0
        self.owner_reads = 0
        self.errors = 0
        #: recent end-to-end read latencies (ms) for the p99 gauge
        self.recent_ms: deque = deque(maxlen=8192)
        self._status: List[Optional[dict]] = [None] * len(self.replicas)
        self._status_at = [0.0] * len(self.replicas)
        self._lock = threading.Lock()

    # --- policy ----------------------------------------------------------

    def key_group(self, key: int) -> int:
        kg, _ = owner_subtask_np(np.asarray(key), 1, self.num_key_groups)
        return int(kg)

    def replica_for_group(self, kg: int) -> Optional[int]:
        if not self.replicas:
            return None
        return int(kg) % len(self.replicas)

    def replica_staleness(self, i: int) -> Optional[int]:
        st = self._probe(i)
        if st is None:
            return None
        return int(st.get("staleness_epochs", 0))

    def _probe(self, i: int) -> Optional[dict]:
        """Cached freshness probe (one STATUS call per TTL per replica
        — the routing decision must not double every read's round
        trips)."""
        now = _time.monotonic()
        with self._lock:
            if (self._status[i] is not None
                    and now - self._status_at[i] < self.status_ttl_s):
                return self._status[i]
        try:
            st = self.replicas[i].status()
        except (QueryTimeoutError, QueryRejectedError, OSError,
                KeyError):
            st = None
        with self._lock:
            self._status[i] = st
            self._status_at[i] = _time.monotonic()
        return st

    def _usable(self, i: Optional[int]) -> bool:
        if i is None:
            return False
        st = self._probe(i)
        return (st is not None and st.get("alive", True)
                and int(st.get("staleness_epochs", 0))
                <= self.staleness_bound)

    def _invalidate(self, i: int) -> None:
        with self._lock:
            if i < len(self._status):
                self._status[i] = None

    # --- reads -----------------------------------------------------------

    def _lineage(self):
        if self.lineage is not None:
            return self.lineage
        from clonos_tpu.obs.lineage import get_lineage
        return get_lineage()

    def query(self, vertex: int, key: int, state: str = "acc") -> dict:
        t0 = _time.monotonic()
        kg = self.key_group(key)
        i = self.replica_for_group(kg)
        out = None
        rerouted = False
        if self._usable(i):
            try:
                out = self.replicas[i].query(vertex, key, state=state)
                self.replica_reads += 1
            except (QueryTimeoutError, QueryRejectedError, OSError,
                    IndexError):
                # IndexError: the tier shrank between routing and the
                # call (drop_replica) — reroute like any replica loss
                self._invalidate(i)
                out = None
        if out is None:
            if i is not None:
                self.reroutes += 1
                rerouted = True
            out = self.owner.query(vertex, key, state=state)
            self.owner_reads += 1
        # Provenance stamp: which endpoint actually answered, at which
        # sealed epoch, and whether the read fell back to the owner —
        # enough for a lineage path to terminate at this read.
        out = dict(out)
        out["replica"] = str(out.get("served_by", "owner"))
        out["rerouted"] = rerouted
        lin = self._lineage()
        if lin.enabled:
            lin.observe_serve(key, epoch=int(out.get("epoch", -1)),
                              replica=out["replica"],
                              rerouted=rerouted)
        self.reads += 1
        self.recent_ms.append((_time.monotonic() - t0) * 1e3)
        return out

    def query_batch(self, vertex: int, keys: Sequence[int],
                    state: str = "acc") -> dict:
        """Batched routed read: keys are grouped per endpoint choice and
        each group goes out as ONE wire request (the replica end fuses
        it further into one device gather). Results return in input
        order with per-key provenance."""
        t0 = _time.monotonic()
        keys = [int(k) for k in keys]
        groups: Dict[object, List[int]] = {}
        routed_away: List[int] = []
        for pos, k in enumerate(keys):
            i = self.replica_for_group(self.key_group(k))
            dest = i if self._usable(i) else None
            if dest is None and i is not None:
                self.reroutes += 1
                routed_away.append(pos)
            groups.setdefault(dest, []).append(pos)
        n = len(keys)
        values = [None] * n
        epochs = [None] * n
        stals = [None] * n
        served = [None] * n
        rerouted = [False] * n
        for dest, positions in groups.items():
            sub_keys = [keys[p] for p in positions]
            out = None
            if dest is not None:
                try:
                    out = self.replicas[dest].query_batch(
                        vertex, sub_keys, state=state)
                    self.replica_reads += len(positions)
                except (QueryTimeoutError, QueryRejectedError, OSError,
                        IndexError):
                    self._invalidate(dest)
                    self.reroutes += len(positions)
                    for p in positions:
                        rerouted[p] = True
                    out = None
            if out is None:
                out = self.owner.query_batch(vertex, sub_keys,
                                             state=state)
                self.owner_reads += len(positions)
            who = out.get("served_by", "owner")
            for j, p in enumerate(positions):
                values[p] = out["values"][j]
                epochs[p] = out["epoch"]
                stals[p] = out.get("staleness_epochs", 0)
                served[p] = who
        for p in routed_away:
            rerouted[p] = True
        lin = self._lineage()
        if lin.enabled:
            for p, k in enumerate(keys):
                lin.observe_serve(k, epoch=int(epochs[p] or -1),
                                  replica=str(served[p]),
                                  rerouted=rerouted[p])
        self.reads += n
        self.recent_ms.append((_time.monotonic() - t0) * 1e3)
        return {"values": values, "epochs": epochs,
                "staleness_epochs": stals, "served_by": served,
                "rerouted": rerouted}


class ServeTier:
    """One runner's assembled read tier: replicas + their endpoints +
    clients + the router, plus the ``serve.*`` gauges riding the
    heartbeat piggyback into ``cluster_metrics()``."""

    def __init__(self, runner, vertex_id: int, n_replicas: int = 2,
                 staleness_bound: int = 2, state: str = "acc",
                 timeout_s: float = 5.0):
        self.runner = runner
        self.vertex_id = int(vertex_id)
        self.state_name = state
        self.timeout_s = float(timeout_s)
        #: monotone name counter — replica NAMES are never reused even
        #: when an index is (drop then add), so logs stay unambiguous
        self._n_created = 0
        self.owner_endpoint = None
        from clonos_tpu.runtime.query import (QueryableStateClient,
                                              QueryableStateEndpoint)
        self.owner_endpoint = QueryableStateEndpoint(runner)
        self.owner_client = QueryableStateClient(
            self.owner_endpoint.address, timeout_s=timeout_s)
        self.replicas: List[ReadReplica] = []
        self.endpoints: List[ReplicaServeEndpoint] = []
        self.clients: List[ReplicaStateClient] = []
        for _ in range(n_replicas):
            self._build_replica()
        self.router = ServeRouter(
            self.owner_client, self.clients,
            num_key_groups=runner.job.num_key_groups,
            staleness_bound=staleness_bound,
            lineage=getattr(runner, "lineage", None))
        # Owner endpoint snapshots refresh at every fence (fence hooks
        # run before truncation, after the seal stamped
        # last_sealed_epoch on the sequential path).
        runner.fence_hooks.append(self._on_fence)
        self._register_gauges()

    def _build_replica(self):
        """One replica + endpoint + client, appended to the tier's
        parallel lists (NOT yet visible to the router)."""
        rep = ReadReplica(self.runner, self.vertex_id,
                          state=self.state_name,
                          name=f"replica-{self._n_created}")
        self._n_created += 1
        ep = ReplicaServeEndpoint(rep)
        self.replicas.append(rep)
        self.endpoints.append(ep)
        self.clients.append(ReplicaStateClient(
            ep.address, timeout_s=self.timeout_s))
        return rep

    # --- runtime-adjustable replica count (the autoscaler's read-path
    # --- scale knob; ROADMAP "replica count fixed at tier build")

    def add_replica(self) -> int:
        """Grow the read tier by one replica at runtime. The new
        replica adopts ``standbys.latest`` immediately if one exists
        and (re)fills at the next seal — the PR 14 revival path — so
        it serves with honest staleness from the first read. The
        router's ``kg % R`` assignment picks up the new count the
        moment the replica is published under the router lock."""
        i = len(self.replicas)
        self._build_replica()
        with self.router._lock:
            self.router.replicas.append(self.clients[i])
            self.router._status.append(None)
            self.router._status_at.append(0.0)
        g = self.runner.metrics.group("serve")
        g.gauge(f"replica.{i}.staleness-epochs",
                lambda i=i: self.replicas[i].staleness_epochs())
        return i

    def drop_replica(self) -> int:
        """Shrink the read tier by one replica (the last index, so the
        ``kg % R`` map and the dense gauge indexing both contract
        cleanly). The router stops routing to it under the lock BEFORE
        the endpoint closes — an in-flight read that already picked it
        reroutes to the owner like any replica failure (staleness,
        never an error). Its status cache entries drop with it and its
        staleness gauge is unregistered (the registry would otherwise
        pin the dead closure forever)."""
        if len(self.replicas) <= 1:
            raise ValueError("cannot drop the last read replica")
        i = len(self.replicas) - 1
        with self.router._lock:
            self.router.replicas.pop()
            self.router._status = [None] * len(self.router.replicas)
            self.router._status_at = [0.0] * len(self.router.replicas)
        client = self.clients.pop()
        ep = self.endpoints.pop()
        rep = self.replicas.pop()
        client.close()
        ep.close()
        rep.close()
        self.runner.metrics.unregister(
            f"serve.replica.{i}.staleness-epochs")
        return i

    def _on_fence(self, closed: int) -> None:
        # Fence hooks fire before the (possibly pipelined) seal lands;
        # the executor state IS this fence's state, so stamp `closed`
        # explicitly rather than reading the trailing seal counter.
        self.owner_endpoint.refresh(epoch=closed)

    def _register_gauges(self) -> None:
        from clonos_tpu.soak.slo import quantile
        g = self.runner.metrics.group("serve")
        router = self.router
        g.gauge("reads", lambda: router.reads)
        g.gauge("reroutes", lambda: router.reroutes)
        g.gauge("replica-reads", lambda: router.replica_reads)
        g.gauge("owner-reads", lambda: router.owner_reads)
        g.gauge("read-errors", lambda: router.errors)
        g.gauge("p99-read-ms", lambda: round(
            quantile(list(router.recent_ms), 0.99), 3))
        g.gauge("replicas-alive",
                lambda: sum(1 for r in self.replicas if r.alive))
        self._meter = g.meter("reads-per-sec")
        # index-based closures (not per-object): the gauge for slot i
        # always reads the CURRENT occupant, so a drop-then-add cycle
        # that reuses the index never serves a dead replica's numbers.
        for i in range(len(self.replicas)):
            g.gauge(f"replica.{i}.staleness-epochs",
                    lambda i=i: self.replicas[i].staleness_epochs())

    def mark_reads(self, n: int) -> None:
        self._meter.mark(n)

    def rehome(self, new_runner) -> None:
        """Re-home the whole read tier after a live re-cut: the owner
        endpoint snapshots the NEW runner, every replica re-adopts in
        the new shape (key-group->replica assignment ``kg % R`` is
        recomputed per read from the new parallelism), fence hooks and
        gauges move over. Reads issued during the handoff window
        reroute to the owner — degradation shows as staleness, never as
        a client-visible error."""
        try:
            self.runner.fence_hooks.remove(self._on_fence)
        except ValueError:
            pass
        self.runner = new_runner
        self.owner_endpoint.runner = new_runner
        self.owner_endpoint.refresh()
        for rep in self.replicas:
            rep.rehome(new_runner)
        # Freshness probes cached against the old incarnation would
        # keep routing on stale staleness for a TTL — drop them.
        with self.router._lock:
            self.router._status = [None] * len(self.router.replicas)
        new_runner.fence_hooks.append(self._on_fence)
        self._register_gauges()

    def kill_replica(self, i: int) -> None:
        self.replicas[i % len(self.replicas)].kill()

    def staleness(self) -> List[int]:
        return [r.staleness_epochs() for r in self.replicas]

    def close(self) -> None:
        for c in self.clients:
            c.close()
        for ep in self.endpoints:
            ep.close()
        for r in self.replicas:
            r.close()
        self.owner_client.close()
        self.owner_endpoint.close()
        try:
            self.runner.fence_hooks.remove(self._on_fence)
        except ValueError:
            pass


def build_serve_tier(runner, vertex_id: int, n_replicas: int = 2,
                     staleness_bound: int = 2,
                     state: str = "acc") -> ServeTier:
    """Convenience assembly used by bench --serve, the soak serve load,
    and tests."""
    return ServeTier(runner, vertex_id, n_replicas=n_replicas,
                     staleness_bound=staleness_bound, state=state)
