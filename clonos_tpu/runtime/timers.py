"""Processing-time timers with causal record/replay.

Capability parity with the reference's timer machinery
(flink-streaming-java .../runtime/tasks/SystemProcessingTimeService.java:50
— implements ProcessingTimeForceable :79-114; each fired timer logs a
TimerTriggerDeterminant {recordCount, callbackID, ts}; during replay timers
are *forced* at the recorded record count :143,163).

TPU split: timers are host-side control-plane events (they drive host
callbacks — external flushes, window cleanup RPCs); on-device windows fire
on causal time directly (operators.TumblingWindowCountOperator). The
service checks due timers at superstep boundaries against causal time, so
firing granularity is one superstep — which is also what makes replay
exact: a fired timer's determinant records the step stamp and callback id.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Callable, Dict, List, Optional, Tuple

from clonos_tpu.causal import determinant as det
from clonos_tpu.causal.services import ReplayFeed


class ProcessingTimeService:
    """Per-task timer service.

    Live: ``advance(now, stamp)`` fires every timer with fire_time <= now —
    appending a TIMER_TRIGGER determinant and invoking the callback.
    Replay: ``force_fire(d)`` re-fires a recovered TimerTriggerDeterminant
    (reference ProcessingTimeForceable.forceFire), re-appending it so the
    rebuilt log matches.
    """

    def __init__(self, append: Callable[[det.Determinant], None]):
        self._append = append
        self._heap: List[Tuple[int, int]] = []   # (fire_time, callback_id)
        self._callbacks: Dict[int, Callable[[int], None]] = {}
        self._next_id = 1

    def register_callback(self, fn: Callable[[int], None],
                          callback_id: Optional[int] = None) -> int:
        """Callbacks must be re-registered under stable ids after restore
        (ids are what the determinant records)."""
        cid = callback_id if callback_id is not None else self._next_id
        self._next_id = max(self._next_id, cid + 1)
        self._callbacks[cid] = fn
        return cid

    def register_timer(self, fire_time: int, callback_id: int) -> None:
        if callback_id not in self._callbacks:
            raise ValueError(f"unknown callback id {callback_id}")
        heapq.heappush(self._heap, (fire_time, callback_id))

    def advance(self, now: int, stamp: int) -> int:
        """Fire all due timers; returns count fired."""
        fired = 0
        while self._heap and self._heap[0][0] <= now:
            ft, cid = heapq.heappop(self._heap)
            d = det.TimerTriggerDeterminant(
                record_count=max(stamp, 1), callback_id=cid, timestamp=ft)
            self._append(d)
            self._callbacks[cid](ft)
            fired += 1
        return fired

    def force_fire(self, d: det.TimerTriggerDeterminant) -> None:
        """Replay path: fire exactly the recorded timer (and drop its
        pending registration if present, to avoid double fire),
        re-appending its determinant (append-even-during-replay)."""
        self._append(d)
        self.refire(d)

    def refire(self, d: det.TimerTriggerDeterminant) -> None:
        """Recovery path when the determinant row was already restored
        into the rebuilt log (block-replay splices async rows back):
        re-run the callback effect WITHOUT re-appending — a second append
        would duplicate the recovered row."""
        self._heap = [(ft, cid) for ft, cid in self._heap
                      if not (ft == d.timestamp and cid == d.callback_id)]
        heapq.heapify(self._heap)
        cb = self._callbacks.get(d.callback_id)
        if cb is None:
            raise ValueError(
                f"replayed timer references unregistered callback "
                f"{d.callback_id}; re-register callbacks before replay")
        cb(d.timestamp)

    def replay_all(self, feed: ReplayFeed) -> int:
        """Force-fire every recorded TIMER_TRIGGER determinant in order."""
        n = 0
        while not feed.exhausted():
            d = feed.next_of(det.TimerTriggerDeterminant)
            self.force_fire(d)
            n += 1
        return n

    @property
    def pending(self) -> int:
        return len(self._heap)
