"""Block executor: the whole job as a few large fused programs per epoch.

This replaces the reference's task plane + stream runtime
(taskexecutor/TaskExecutor.java:422, taskmanager/Task.java:124,
runtime/tasks/StreamTask.java and the OneInputStreamTask.run hot loop,
OneInputStreamTask.java:106) with the TPU-native execution model:

- Every vertex's subtasks are a ``[P]`` dim of its state/batches, shardable
  over a ``jax.sharding.Mesh`` axis — the analog of deploying subtasks to
  TaskManagers.
- A **superstep** advances every vertex by one batch concurrently: vertex v
  consumes the batch its upstream routed in the *previous* superstep
  (depth-1 edge buffers). That is pipeline parallelism — all stages busy
  every step — without queues/threads/backpressure machinery.
- The executor runs supersteps in **blocks of K**: each vertex processes a
  whole ``[K, P, B]`` stack per program (``Operator.process_block``), each
  exchange routes the whole stack, and the causal/in-flight logs take one
  bulk append per block. Per-step semantics are preserved exactly (the
  depth-1 shift is a concatenate of the carried edge buffer with the first
  K-1 routed outputs; ``tests/test_executor.py::test_scan_epoch_equals_
  stepwise`` proves block == stepwise bit-for-bit) — but the kernel count
  per epoch is O(vertices + edges), not O(steps · ops). On hardware where
  each non-fused kernel in a sequential loop costs hundreds of
  microseconds, this is the difference between 10^4 and 10^7 records/sec.
- The per-superstep causal determinants (TIMESTAMP of the causal time
  input, RNG draw, ORDER of the consumed channel, BUFFER_BUILT with the
  emitted record count — reference CausalBufferOrderService.java:112,
  PipelinedSubpartition buffer cuts) are materialized for the whole block
  as one ``[L, K·4, lanes]`` tensor and appended to the stacked device log
  and its replicas in two scatters.
- **Determinant durability boundary == output visibility boundary**: sink
  outputs and routed batches leave the device only when a block program
  returns, and the same program has already appended + replicated every
  determinant describing them. This is the step-fused form of the
  reference's piggybacking (deltas ride the data they describe,
  NettyMessage.java:156-242).

Host Python never touches records: it stages each block's causal
time/RNG arrays in one transfer and reads sink batches out.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time as _time
from functools import partial
from typing import (Any, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.operators import (BlockContext, HostFeedSource, OpContext,
                                      TwoInputOperator)
from clonos_tpu.api.records import RecordBatch, empty, zero_invalid
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import replication as rep
from clonos_tpu.graph.job_graph import JobGraph, PartitionType
from clonos_tpu.inflight import log as ifl
from clonos_tpu.parallel import routing

# Determinants appended per subtask per superstep on the sync path, in this
# fixed order: TIMESTAMP (causal time read), RNG (causal host-RNG draw),
# ORDER (consumed channel), BUFFER_BUILT (emitted batch cut). The fixed
# layout is what lets the replayer parse the log as a [steps, 4, lanes]
# tensor on device.
DETS_PER_STEP = 4


class StepInputs(NamedTuple):
    """Host-fed inputs for one superstep (single-step API; the block path
    uses :class:`BlockInputs`). ``time``/``rng_bits`` are the causal-service
    scalars (recorded as determinants; replayed from the log). ``feeds``
    carries one RecordBatch per HostFeedSource vertex (in vertex-id order) —
    the external-system boundary (Kafka/socket analog)."""

    time: jnp.ndarray
    rng_bits: jnp.ndarray
    feeds: Tuple[RecordBatch, ...] = ()


class BlockInputs(NamedTuple):
    """Host-fed inputs for a block of K supersteps, staged in one transfer."""

    times: jnp.ndarray                    # int32[K]
    rng_bits: jnp.ndarray                 # int32[K]
    epoch: jnp.ndarray                    # int32 scalar
    step0: jnp.ndarray                    # int32 scalar (global step index)
    feeds: Tuple[RecordBatch, ...] = ()   # per feed vertex, [K, P, B]


class JobCarry(NamedTuple):
    """The complete device-resident job state (the block program's carry)."""

    op_states: Tuple[Any, ...]          # per-vertex operator state pytrees
    edge_bufs: Tuple[RecordBatch, ...]  # per-edge routed batch [P_dst, cap]
    rr_offsets: Tuple[jnp.ndarray, ...] # per-edge [1] round-robin cursors
    record_counts: jnp.ndarray          # int32[L] records consumed per subtask
    logs: clog.ThreadLogState           # stacked [L, cap, lanes]
    out_rings: Tuple[ifl.EdgeLogState, ...]  # per producing vertex: its raw
                                        # output batches [S, P, out_cap] — the
                                        # PipelinedSubpartition in-flight log,
                                        # owned by (and dying with) the
                                        # producer's subtask shards
    replicas: clog.ThreadLogState       # stacked [R, cap, lanes] downstream
                                        # determinant replicas


class LeanSnapshot(NamedTuple):
    """What a checkpoint actually persists (reference: async snapshots of
    *operator state* only, StreamTask.java:854; RocksDB incremental
    backends). Causal logs, replicas, and in-flight rings are NOT
    snapshotted: a completed checkpoint *truncates* them, so their
    post-fence content is exactly what recovery regenerates — persisting
    them would be GB-scale dead weight (round-1 VERDICT weakness #12).
    Only their fence offsets ride along."""

    op_states: Tuple[Any, ...]
    edge_bufs: Tuple[RecordBatch, ...]   # the depth-1 in-flight batch per
                                         # edge — the aligned-barrier channel
                                         # state spanning the fence
    rr_offsets: Tuple[jnp.ndarray, ...]
    record_counts: jnp.ndarray
    log_heads: jnp.ndarray               # int32[L] log heads at the fence
    ring_heads: Tuple[jnp.ndarray, ...]  # per-ring heads at the fence


class StepOutputs(NamedTuple):
    sinks: Dict[int, RecordBatch]       # vertex_id -> emitted batch [P, cap]
    dropped: Dict[int, jnp.ndarray]     # edge index -> [P_dst] drops
    consumed: jnp.ndarray               # int32[L] records consumed this step


class BlockOutputs(NamedTuple):
    sinks: Dict[int, RecordBatch]       # vertex_id -> [K, P, cap]
    dropped: Dict[int, jnp.ndarray]     # edge index -> [K, P_dst]
    consumed: jnp.ndarray               # int32[K, L]


@dataclasses.dataclass
class CompiledJob:
    """A job graph lowered to (init_carry, run_block) pure functions."""

    job: JobGraph
    log_capacity: int = 1 << 14
    max_epochs: int = 64
    inflight_ring_steps: int = 64
    mesh: Optional[jax.sharding.Mesh] = None
    task_axis: str = "tasks"
    replication_factor: int = -1   # holder subtasks per (owner, holder
                                   # vertex); -1 = all (see replication.py)

    def __post_init__(self):
        self.job.validate()
        self.topo = self.job.topo_order()
        self.L = self.job.total_subtasks()
        #: vertex ids of host-fed sources, in id order (feeds positions
        #: align with this list).
        self.feed_vertices = [v.vertex_id for v in self.job.vertices
                              if isinstance(v.operator, HostFeedSource)]
        self.plan = rep.ReplicationPlan.from_job(
            self.job, self.job.sharing_depth,
            replication_factor=self.replication_factor)
        self._owner_idx = self.plan.owner_index()
        #: vertices owning an in-flight output ring (everything that feeds
        #: a downstream consumer).
        self.ring_vertices = [v.vertex_id for v in self.job.vertices
                              if self.job.out_edges(v.vertex_id)]
        self.ring_index = {vid: i for i, vid in enumerate(self.ring_vertices)}
        #: HASH edges whose producer emits statically-keyed slots get a
        #: compile-time gather plan instead of the sort exchange.
        self.static_route: Dict[int, routing.StaticRoutePlan] = {}
        for eidx, e in enumerate(self.job.edges):
            if e.partition != PartitionType.HASH:
                continue
            sk = self.job.vertices[e.src].operator.static_out_keys()
            if sk is None:
                continue
            src_p = self.job.vertices[e.src].parallelism
            dst_p = self.job.vertices[e.dst].parallelism
            # The static plan reserves a slot for EVERY (producer, key)
            # pair, so a hash-skewed target can need more than the
            # requested receive window even though the dynamic exchange
            # never drops (it only sees per-step live arrivals). The
            # edge capacity is a lower-bound request — widen it to fit
            # the densest target (rounded to the 128 TPU lane width):
            # total extra memory is bounded by the hash imbalance times
            # the producer's own output width, and it buys the gather
            # plan (~50x cheaper than the sort exchange at bench shapes).
            need = routing.static_hash_capacity(
                sk, src_p, dst_p, self.job.num_key_groups)
            if need > max(4 * e.capacity, 1024):
                # The static plan would need far more receive memory than
                # the user asked for (very dense key table or extreme
                # hash skew into a narrow edge): keep the dynamic
                # exchange rather than silently multiplying the edge and
                # downstream buffers.
                continue
            if need > e.capacity:
                e.capacity = -(-need // 128) * 128
            plan = routing.plan_static_hash(
                sk, src_p, dst_p, self.job.num_key_groups, e.capacity)
            if len(plan.drop_p):                       # pragma: no cover
                raise RuntimeError(
                    f"static plan for edge {eidx} still has "
                    f"{len(plan.drop_p)} overflow slots at capacity "
                    f"{e.capacity} — static_hash_capacity disagrees "
                    f"with plan_static_hash")
            self.static_route[eidx] = plan

    def consumer_slot_keys(self, vid: int) -> Optional[np.ndarray]:
        """Static per-slot input keys of vertex ``vid`` ([P, cap], -1 =
        unmapped), when its (single) input edge is statically routed."""
        ins = self.job.in_edges(vid)
        if len(ins) == 1 and ins[0] in self.static_route:
            return self.static_route[ins[0]].slot_keys
        return None

    # --- shapes -------------------------------------------------------------

    def vertex_out_capacity(self, vid: int) -> int:
        v = self.job.vertices[vid]
        if v.operator.out_capacity is not None:
            return v.operator.out_capacity
        ins = self.job.in_edges(vid)
        if ins:
            return self.job.edges[ins[0]].capacity
        return 1

    # --- sharding -----------------------------------------------------------

    def _shard_axis(self, x: jnp.ndarray, axis: int) -> jnp.ndarray:
        """Constrain ``x`` to be sharded over the task mesh axis along
        ``axis`` when divisible (the subtask->device deployment)."""
        if self.mesh is None:
            return x
        n = self.mesh.shape[self.task_axis]
        if x.ndim <= axis or x.shape[axis] % n != 0:
            return x
        spec = [None] * x.ndim
        spec[axis] = self.task_axis
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(*spec)))

    def _shard_leading(self, x: jnp.ndarray) -> jnp.ndarray:
        if getattr(x, "ndim", 0) == 0:
            return x
        return self._shard_axis(x, 0)

    def _shard_tree(self, tree):
        return jax.tree_util.tree_map(self._shard_leading, tree)

    def _shard_block(self, tree):
        """Block tensors are [K, P, ...]: shard the subtask axis (1)."""
        return jax.tree_util.tree_map(
            lambda x: self._shard_axis(x, 1) if getattr(x, "ndim", 0) > 1
            else x, tree)

    def carry_partition_spec(self, carry: JobCarry):
        """Rule-driven PartitionSpec pytree for the full carry
        (parallel/distributed.py:CARRY_PARTITION_RULES — regex over
        flattened leaf names; scalars and indivisible dims replicate).
        None when no mesh is attached."""
        if self.mesh is None:
            return None
        from clonos_tpu.parallel import distributed as dist
        return dist.infer_partition_spec(carry, self.mesh,
                                         axis=self.task_axis)

    def carry_shardings(self, carry: JobCarry):
        """NamedSharding pytree over the task mesh for the full carry
        (the form jit in/out_shardings take), or None without a mesh."""
        if self.mesh is None:
            return None
        from clonos_tpu.parallel import distributed as dist
        return dist.named_shardings(carry, self.mesh, axis=self.task_axis)

    def constrain_carry(self, carry: JobCarry) -> JobCarry:
        """Constrain EVERY carry leaf to its rule-assigned sharding —
        logs/replicas on their leading task axis, ring payloads on their
        subtask axis (1), control scalars replicated. Applied at carry
        construction and at the end of every block so the traced
        program's layout always matches the explicit jit shardings."""
        if self.mesh is None:
            return carry
        shardings = self.carry_shardings(carry)
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, carry, shardings)

    # --- initialization -----------------------------------------------------

    def init_carry(self) -> JobCarry:
        if DETS_PER_STEP * self.inflight_ring_steps > self.log_capacity:
            # Not fatal (logs may checkpoint more often than rings wrap),
            # but the block path appends 4K rows per block and requires
            # block <= capacity; enforced in run_block.
            pass
        op_states = tuple(
            v.operator.init_state(v.parallelism) for v in self.job.vertices)
        edge_bufs = tuple(
            empty((self.job.vertices[e.dst].parallelism, e.capacity))
            for e in self.job.edges)
        rr = tuple(jnp.zeros((1,), jnp.int32) for _ in self.job.edges)
        logs = jax.vmap(lambda _: clog.create(self.log_capacity, self.max_epochs)
                        )(jnp.arange(self.L))
        out_rings = tuple(
            ifl.create(self.inflight_ring_steps,
                       self.job.vertices[vid].parallelism,
                       self.vertex_out_capacity(vid), self.max_epochs)
            for vid in self.ring_vertices)
        replicas = rep.create_replicas(self.plan, self.log_capacity,
                                       self.max_epochs)
        carry = JobCarry(op_states, edge_bufs, rr,
                         jnp.zeros((self.L,), jnp.int32), logs, out_rings,
                         replicas)
        return self.constrain_carry(carry)

    # --- the block program --------------------------------------------------

    def run_block(self, carry: JobCarry, binputs: BlockInputs
                  ) -> Tuple[JobCarry, BlockOutputs]:
        """Advance K supersteps as one traced program."""
        job = self.job
        K = binputs.times.shape[0]
        if DETS_PER_STEP * K > self.log_capacity:
            raise ValueError(
                f"block of {K} steps appends {DETS_PER_STEP * K} determinant"
                f" rows > log capacity {self.log_capacity}")
        if K > self.inflight_ring_steps:
            raise ValueError(
                f"block of {K} steps exceeds in-flight ring "
                f"({self.inflight_ring_steps} steps)")
        op_states = list(carry.op_states)
        rr_offsets = list(carry.rr_offsets)
        out_rings = list(carry.out_rings)
        new_edge_bufs = list(carry.edge_bufs)
        routed: Dict[int, RecordBatch] = {}
        sinks: Dict[int, RecordBatch] = {}
        dropped: Dict[int, jnp.ndarray] = {}
        consumed_parts: Dict[int, jnp.ndarray] = {}
        emit_parts: Dict[int, jnp.ndarray] = {}

        def shifted(eidx: int) -> RecordBatch:
            # Depth-1 pipeline: the batch consumed at block step k is the
            # upstream's routed output of step k-1; step 0 consumes the
            # carried edge buffer (the previous block's last routed batch).
            return jax.tree_util.tree_map(
                lambda r, b: jnp.concatenate([b[None], r[:-1]], axis=0),
                routed[eidx], carry.edge_bufs[eidx])

        for vid in self.topo:
            v = job.vertices[vid]
            p = v.parallelism
            in_edges = job.in_edges(vid)
            bctx = BlockContext(
                times=binputs.times, rng_bits=binputs.rng_bits,
                epoch=binputs.epoch, step0=binputs.step0,
                subtask=jnp.arange(p, dtype=jnp.int32))
            if isinstance(v.operator, TwoInputOperator):
                ins = (shifted(in_edges[0]), shifted(in_edges[1]))
                consumed = ins[0].count() + ins[1].count()       # [K, P]
            elif in_edges:
                ins = shifted(in_edges[0])
                consumed = ins.count()
            elif vid in self.feed_vertices and binputs.feeds:
                ins = binputs.feeds[self.feed_vertices.index(vid)]
                consumed = ins.count()
            else:
                ins = empty((K, p, self.vertex_out_capacity(vid)))
                consumed = None
            slot_keys = self.consumer_slot_keys(vid)
            if slot_keys is not None and hasattr(
                    v.operator, "process_block_static_keys"):
                state, out = v.operator.process_block_static_keys(
                    op_states[vid], ins, bctx, slot_keys)
            else:
                state, out = v.operator.process_block(op_states[vid], ins,
                                                      bctx)
            if consumed is None:
                # Pure generators "consume" what they emit (their record
                # count advances with generated records, like the
                # reference's source loop).
                consumed = out.count()
            op_states[vid] = self._shard_tree(state)
            out = self._shard_block(out)
            if in_edges and not job.out_edges(vid):
                sinks[vid] = out
            consumed_parts[vid] = consumed
            emit_parts[vid] = out.count()                        # [K, P]

            for eidx in job.out_edges(vid):
                e = job.edges[eidx]
                dst_p = job.vertices[e.dst].parallelism
                if eidx in self.static_route:
                    r, d = self.static_route[eidx].apply(out)
                elif e.partition == PartitionType.HASH:
                    r, d = routing.route_hash_block(
                        out, dst_p, job.num_key_groups, e.capacity)
                elif e.partition == PartitionType.FORWARD:
                    r, d = routing.route_forward_block(out, e.capacity)
                elif e.partition == PartitionType.REBALANCE:
                    counts = out.count().sum(axis=1)             # [K]
                    offs = (rr_offsets[eidx][0]
                            + jnp.cumsum(counts) - counts)       # exclusive
                    r, d = routing.route_rebalance_block(
                        out, dst_p, e.capacity, offs)
                    rr_offsets[eidx] = (
                        (rr_offsets[eidx] + counts.sum())
                        % jnp.asarray(dst_p, jnp.int32))
                else:
                    r, d = routing.route_broadcast_block(
                        out, dst_p, e.capacity)
                routed[eidx] = self._shard_block(r)
                dropped[eidx] = d
                new_edge_bufs[eidx] = jax.tree_util.tree_map(
                    lambda x: x[-1], routed[eidx])

            if vid in self.ring_index:
                # In-flight logging: retain the producer's raw output block
                # (reference PipelinedSubpartition.add -> InFlightLog.log);
                # consumers re-derive their input by re-running the
                # deterministic exchange during replay.
                ri = self.ring_index[vid]
                el = ifl.append_block(out_rings[ri], out)
                # Re-pin the ring payload to its subtask axis (axis 1):
                # append_block's scatter would otherwise let the
                # partitioner re-layout the [S, P, cap] tensors along the
                # ring-step axis, splitting every step's batch across
                # chips instead of keeping each subtask's lane local.
                out_rings[ri] = el._replace(
                    keys=self._shard_axis(el.keys, 1),
                    values=self._shard_axis(el.values, 1),
                    timestamps=self._shard_axis(el.timestamps, 1),
                    valid=self._shard_axis(el.valid, 1))

        # Determinant block: one [L, K*4, lanes] tensor, two bulk appends.
        emits_all = jnp.concatenate(
            [emit_parts[v.vertex_id] for v in job.vertices], axis=1)  # [K, L]
        consumed_all = jnp.concatenate(
            [consumed_parts[v.vertex_id] for v in job.vertices], axis=1)
        rows = self._det_rows(binputs, emits_all)                 # [L, 4K, 8]
        logs = clog.v_append_full(carry.logs, rows)
        logs = self._shard_tree(logs)
        if self.plan.num_replicas > 0:
            # Piggyback replication: the same block of determinants lands in
            # every downstream replica before any of this block's outputs
            # become externally visible (the per-message netty delta becomes
            # one owner-indexed bulk append at the block fence).
            replicas = clog.v_append_full(carry.replicas,
                                          rows[self._owner_idx])
            replicas = self._shard_tree(replicas)
        else:
            replicas = carry.replicas

        new_carry = JobCarry(
            tuple(op_states), tuple(new_edge_bufs), tuple(rr_offsets),
            carry.record_counts + consumed_all.sum(axis=0), logs,
            tuple(out_rings), replicas)
        new_carry = self.constrain_carry(new_carry)
        return new_carry, BlockOutputs(sinks, dropped, consumed_all)

    def _det_rows(self, binputs: BlockInputs, emits_all: jnp.ndarray
                  ) -> jnp.ndarray:
        """Build the block's packed determinant rows [L, K*4, lanes]."""
        K = binputs.times.shape[0]
        t_hi = jnp.where(binputs.times < 0, -1, 0)
        base = jnp.zeros((K, DETS_PER_STEP, det.NUM_LANES), jnp.int32)
        base = base.at[:, 0, det.LANE_TAG].set(det.TIMESTAMP)
        base = base.at[:, 0, det.LANE_P].set(t_hi)
        base = base.at[:, 0, det.LANE_P + 1].set(binputs.times)
        base = base.at[:, 1, det.LANE_TAG].set(det.RNG)
        base = base.at[:, 1, det.LANE_P].set(binputs.rng_bits)
        base = base.at[:, 2, det.LANE_TAG].set(det.ORDER)
        base = base.at[:, 3, det.LANE_TAG].set(det.BUFFER_BUILT)
        rows = jnp.broadcast_to(base[None],
                                (self.L, K, DETS_PER_STEP, det.NUM_LANES))
        rows = rows.at[:, :, 3, det.LANE_P].set(
            emits_all.T)                                          # [L, K]
        return rows.reshape(self.L, K * DETS_PER_STEP, det.NUM_LANES)

    # --- single-step compatibility API --------------------------------------

    def superstep(self, carry: JobCarry, inputs: StepInputs
                  ) -> Tuple[JobCarry, StepOutputs]:
        """One superstep (a K=1 block): the dryrun/test surface."""
        binputs = BlockInputs(
            times=inputs.time[None], rng_bits=inputs.rng_bits[None],
            epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
            feeds=tuple(jax.tree_util.tree_map(lambda x: x[None], f)
                        for f in inputs.feeds))
        carry, outs = self.run_block(carry, binputs)
        return carry, StepOutputs(
            sinks={vid: jax.tree_util.tree_map(lambda x: x[0], b)
                   for vid, b in outs.sinks.items()},
            dropped={e: d[0] for e, d in outs.dropped.items()},
            consumed=outs.consumed[0])


def _canon_log(state: clog.ThreadLogState) -> clog.ThreadLogState:
    """Zero ring rows outside [tail, head) and epoch-index slots outside
    [epoch_base, latest_epoch] — the physically-present-but-logically-dead
    storage. Two runs are equivalent iff their canonical carries are
    bit-identical (truncated slots may hold different garbage: a recovered
    log never re-materializes rows a completed checkpoint already dropped)."""
    cap = state.capacity
    pos = (state.tail + jnp.arange(cap, dtype=jnp.int32)) & (cap - 1)
    live = jnp.zeros((cap,), jnp.bool_).at[pos].set(
        jnp.arange(cap, dtype=jnp.int32) < (state.head - state.tail))
    m = state.max_epochs
    eidx = jnp.arange(m, dtype=jnp.int32)
    base = state.epoch_base
    # Live epochs: [max(base, latest-m+1), latest]; slot e % m.
    lo = jnp.maximum(base, state.latest_epoch - m + 1)
    live_e = jnp.zeros((m,), jnp.bool_).at[
        (lo + eidx) % m].set(lo + eidx <= state.latest_epoch)
    return state._replace(
        rows=jnp.where(live[:, None], state.rows, 0),
        epoch_starts=jnp.where(live_e, state.epoch_starts, 0))


def _canon_ring(state: ifl.EdgeLogState) -> ifl.EdgeLogState:
    S = state.ring_steps
    pos = (state.tail + jnp.arange(S, dtype=jnp.int32)) & (S - 1)
    live = jnp.zeros((S,), jnp.bool_).at[pos].set(
        jnp.arange(S, dtype=jnp.int32) < (state.head - state.tail))
    lv = live[:, None, None]
    m = state.max_epochs
    eidx = jnp.arange(m, dtype=jnp.int32)
    lo = jnp.maximum(state.epoch_base, state.latest_epoch - m + 1)
    live_e = jnp.zeros((m,), jnp.bool_).at[
        (lo + eidx) % m].set(lo + eidx <= state.latest_epoch)
    return state._replace(
        keys=jnp.where(lv, state.keys, 0),
        values=jnp.where(lv, state.values, 0),
        timestamps=jnp.where(lv, state.timestamps, 0),
        valid=jnp.where(lv, state.valid, False),
        epoch_starts=jnp.where(live_e, state.epoch_starts, 0))


@jax.jit
def canonical_carry(carry: JobCarry) -> JobCarry:
    """The carry with all logically-dead storage zeroed — the equality
    domain for the bit-identical-recovery property (tests compare
    ``canonical_carry(recovered) == canonical_carry(never_failed)``)."""
    return carry._replace(
        logs=jax.vmap(_canon_log)(carry.logs),
        replicas=(jax.vmap(_canon_log)(carry.replicas)
                  if carry.replicas.head.shape[0] > 0 else carry.replicas),
        out_rings=tuple(_canon_ring(r) for r in carry.out_rings))


def _slice_log_window(epoch: int, rows: np.ndarray, heads: np.ndarray,
                      starts: np.ndarray) -> Dict[int, np.ndarray]:
    """Slice one closed epoch's determinant rows out of the stacked
    causal-log arrays (host side). Shared by the live fence path
    (``epoch_window`` reading the resident carry) and the pipelined
    fence's deferred drain (``FenceHandles`` reading captured device
    copies), so both produce byte-identical audit-digest input."""
    cap = rows.shape[1]
    me = starts.shape[1]
    logs: Dict[int, np.ndarray] = {}
    for flat in range(rows.shape[0]):
        s = int(starts[flat, epoch % me])
        t = int(starts[flat, (epoch + 1) % me])
        if t < s:               # next epoch's start not stamped yet
            t = int(heads[flat])
        pos = np.arange(s, t) & (cap - 1)
        logs[flat] = np.ascontiguousarray(rows[flat][pos])
    return logs


def _slice_ring_window(epoch: int, keys: np.ndarray, values: np.ndarray,
                       stamps: np.ndarray, valid: np.ndarray,
                       estarts: np.ndarray, head: int) -> list:
    """Per-step valid records of one output ring for one closed epoch,
    in the deterministic (lane, slot) order — the ring half of
    :func:`_slice_log_window`'s shared-extraction contract."""
    rme = estarts.shape[0]
    s = int(estarts[epoch % rme])
    t = int(estarts[(epoch + 1) % rme])
    if t < s:
        t = int(head)
    rcap = keys.shape[0]
    steps = []
    for step in range(s, t):
        p = step & (rcap - 1)
        m = valid[p]
        steps.append((keys[p][m], values[p][m], stamps[p][m]))
    return steps


def iter_ring_steps(window: Dict[str, Any]):
    """Deterministic scan order over one ``epoch_window`` dict's ring
    section: ``(vertex_id, step_seq, keys, values, timestamps)`` tuples,
    vertices ascending, steps in epoch-relative order, records already
    in the (lane, slot) order :func:`_slice_ring_window` fixed. The
    lineage plane's dye scan (obs/lineage.py) consumes this so the
    window shape stays owned here."""
    rings = window.get("rings", {}) or {}
    for vid in sorted(rings, key=int):
        for seq, (keys, values, stamps) in enumerate(rings[vid]):
            yield int(vid), seq, keys, values, stamps


class FenceHandles:
    """Device-side capture of one closed epoch's fence surface — the
    health vector plus (optionally) the causal-log / in-flight-ring
    window arrays the audit seal digests. Produced by
    :meth:`LocalExecutor.capture_fence` as deep device copies with d2h
    started asynchronously, so the pipelined fence can dispatch the
    next epoch's compute immediately and let a worker thread drain the
    handles off the critical path. The handles never alias the live
    carry (whose buffers are donated into later block programs)."""

    def __init__(self, epoch: int, health, window, ring_index):
        self.epoch = epoch
        self._health = health
        self._window = window
        self._ring_index = ring_index

    def health(self) -> np.ndarray:
        """Drain the fused health vector (blocks until the capture
        program and its async d2h complete)."""
        return np.asarray(self._health)

    def window(self) -> Optional[Dict[str, Any]]:
        """Drain the captured causal surface into the exact
        ``epoch_window`` dict shape (None when captured without one)."""
        if self._window is None:
            return None
        rows, heads, starts, rings_t = self._window
        logs = _slice_log_window(self.epoch, np.asarray(rows),
                                 np.asarray(heads), np.asarray(starts))
        rings: Dict[int, list] = {}
        for vid, ri in self._ring_index.items():
            keys, values, stamps, valid, estarts, head = rings_t[ri]
            rings[vid] = _slice_ring_window(
                self.epoch, np.asarray(keys), np.asarray(values),
                np.asarray(stamps), np.asarray(valid),
                np.asarray(estarts), int(np.asarray(head)))
        return {"logs": logs, "rings": rings}


class CausalTimeSource:
    """Host clock for the live path (reference CausalTimeService /
    PeriodicCausalTimeService.java — one amortized read per superstep).
    Produces int32 millis since executor start; values are recorded in every
    task's log as TIMESTAMP determinants by the block program itself."""

    def __init__(self):
        self._t0 = _time.monotonic()

    def now(self) -> int:
        return int((_time.monotonic() - self._t0) * 1000) & 0x7FFFFFFF


class LogicalTimeSource:
    """Deterministic causal time: 1 ms per superstep, read as the
    absolute step index about to be stamped. Wall-clock TIMESTAMP
    determinants are the one live-path input that replay reproduces but
    two INDEPENDENT runs never share; with logical time the whole step
    input stream is a pure function of (job, seed, feed records), so a
    spanned job's slices can be digest-compared against a no-failure
    control run. After a standby rebuild the restored
    ``step_input_history`` makes the clock resume exactly at the fence
    step — bit-identical with the run that never failed."""

    def __init__(self, executor: "LocalExecutor"):
        self._ex = executor

    def now(self) -> int:
        # Called exactly once per superstep, just before the (t, rng)
        # append — history length IS the global index of that step.
        return len(self._ex.step_input_history) & 0x7FFFFFFF


class LocalExecutor:
    """Single-process job driver (MiniCluster analog): owns the compiled
    job, the carry, the causal time/RNG sources, and the epoch loop."""

    def __init__(self, job: JobGraph, steps_per_epoch: int = 16,
                 log_capacity: int = 1 << 14, max_epochs: int = 64,
                 inflight_ring_steps: int = 64,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 spool_dir: Optional[str] = None,
                 spill_policy: str = ifl.SpillPolicy.EAGER,
                 spill_host_budget_epochs: int = 2,
                 block_steps: Optional[int] = None,
                 replication_factor: int = -1,
                 seed: int = 0, logical_time: bool = False):
        self.compiled = CompiledJob(job, log_capacity=log_capacity,
                                    max_epochs=max_epochs,
                                    inflight_ring_steps=inflight_ring_steps,
                                    mesh=mesh,
                                    replication_factor=replication_factor)
        self.job = job
        self.steps_per_epoch = steps_per_epoch
        self.block_steps = min(block_steps or 512, steps_per_epoch,
                               inflight_ring_steps)
        self.carry = self.compiled.init_carry()
        self.time_source = (LogicalTimeSource(self) if logical_time
                            else CausalTimeSource())
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self.epoch_id = 0
        self.step_in_epoch = 0
        #: (flat, epoch) -> async rows appended in that epoch's roll gap
        #: (after the roll, before its first step) — recovery subtracts
        #: this when re-deriving epoch start offsets from TIMESTAMP
        #: anchors (the rows belong to the NEW epoch).
        self.roll_gap_async: Dict[Tuple[int, int], int] = {}
        #: (flat, epoch) -> ALL async rows appended to that task's log
        #: during that epoch. A host-side mirror of log cleanness: a task
        #: with zero async rows since an epoch fence has a pure k-row
        #: sync-block stream there (only the block program appended), so
        #: recovery can take the device-resident clean path WITHOUT a
        #: metadata round-trip — the device parse still validates it, but
        #: as a deferred assert folded into recovery's final read.
        self.async_counts: Dict[Tuple[int, int], int] = {}
        #: supersteps actually executed (the staged epoch path pre-fills
        #: step_input_history, so len(history) over-counts mid-epoch).
        self._steps_executed = 0
        # Explicit shardings for every jitted entry point when a mesh is
        # attached: the carry rides its rule-driven NamedSharding tree
        # (parallel/distributed.py rules — the SAME table the in-trace
        # constraints use, so entry layout and traced layout can never
        # disagree), host-fed step inputs replicate. Donation stays on:
        # input and output carry shardings match leaf-for-leaf, so XLA
        # aliases the GB-scale buffers shard-locally (no cross-chip copy
        # at the donate boundary).
        self._carry_ns = self.compiled.carry_shardings(self.carry)
        self._repl_ns = (jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
            if mesh is not None else None)

        def _mesh_kw(in_shardings, out_shardings=None):
            if mesh is None:
                return {}
            kw = {"in_shardings": in_shardings}
            if out_shardings is not None:
                kw["out_shardings"] = out_shardings
            return kw

        def _ns0(x):
            # Leading-axis task sharding for a plain array arg (the
            # stacked-log storage the async-append program touches),
            # with the same divisibility guard the rule table applies.
            if mesh is None:
                return None
            n = mesh.shape[self.compiled.task_axis]
            shp = getattr(x, "shape", ())
            if len(shp) >= 1 and shp[0] % n == 0 and shp[0] > 0:
                return jax.sharding.NamedSharding(
                    mesh,
                    jax.sharding.PartitionSpec(self.compiled.task_axis))
            return self._repl_ns

        # The carry is donated: the block program updates GB-scale log /
        # ring storage in place instead of copying it every call (the
        # carry's buffers are only ever referenced by the live executor;
        # checkpoints deep-copy what they keep — lean_snapshot).
        self._jit_block = jax.jit(
            self.compiled.run_block, donate_argnums=0,
            **_mesh_kw((self._carry_ns, self._repl_ns)))

        plan = self.compiled.plan

        def _roll(carry: JobCarry, e) -> JobCarry:
            # Epoch fence: record the new epoch's start offset on every
            # log, replica, and in-flight ring coherently. Replica heads
            # equal owner heads by construction (the block program appends
            # both from the same tensor).
            replicas = carry.replicas
            if plan.num_replicas > 0:
                replicas = rep.sync_replica_epochs(replicas, e)
            return carry._replace(
                logs=clog.v_start_epoch(carry.logs, e),
                # Ring markers sit exactly at the fence. The batch appended
                # at the fence's last step is still in flight (its consumer
                # reads it one step after the fence), but that batch rides
                # the checkpoint as the depth-1 edge buffer of the
                # LeanSnapshot — the ring copy is redundant, so truncation
                # may drop it (and recovery never rebuilds it).
                out_rings=tuple(ifl.start_epoch(el, e)
                                for el in carry.out_rings),
                replicas=replicas)

        def _trunc(carry: JobCarry, e) -> JobCarry:
            replicas = carry.replicas
            if plan.num_replicas > 0:
                replicas = clog.v_truncate(replicas, e)
            return carry._replace(
                logs=clog.v_truncate(carry.logs, e),
                out_rings=tuple(ifl.truncate(el, e)
                                for el in carry.out_rings),
                replicas=replicas)

        self._jit_roll = jax.jit(
            _roll, donate_argnums=0,
            **_mesh_kw((self._carry_ns, self._repl_ns), self._carry_ns))
        self._jit_trunc = jax.jit(
            _trunc, donate_argnums=0,
            **_mesh_kw((self._carry_ns, self._repl_ns), self._carry_ns))
        # Host-side spill owners, one per ring vertex (None = disabled).
        self.spill_policy = spill_policy
        self.spill_logs: Optional[List[ifl.SpillingInFlightLog]] = None
        #: determinant-log tier (storage/tiered.py): sealed epochs of every
        #: stacked causal log spill through the same host→disk tiers as the
        #: in-flight rings, so replication depth is no longer HBM-bounded.
        self.det_store = None
        #: per-ring epochs deferred by the AVAILABILITY policy, awaiting
        #: either a later spill (before a wrap) or truncation.
        self._pending_spill: List[List[Tuple[int, int, int]]] = [
            [] for _ in self.compiled.ring_vertices]
        if spool_dir is not None:
            from clonos_tpu.storage import TieredEpochStore
            self.spill_logs = [
                ifl.SpillingInFlightLog(
                    spool_dir, edge_id=vid, policy=spill_policy,
                    host_budget_epochs=spill_host_budget_epochs)
                for vid in self.compiled.ring_vertices]
            self.det_store = TieredEpochStore(
                spool_dir, "dets",
                durable=spill_policy != ifl.SpillPolicy.DISABLED,
                host_budget_epochs=spill_host_budget_epochs)
            # Static bound for the fused epoch-window gather: the sync
            # block stream is DETS_PER_STEP rows/step; async appends
            # (timers, sources) ride on top, so leave headroom and fall
            # back to the exact host extraction when a hot epoch blows
            # past it (_spill_epoch checks counts against this).
            self._det_window_rows = min(
                self.compiled.log_capacity,
                steps_per_epoch * DETS_PER_STEP * 2 + 64)
            self._jit_det_window = jax.jit(
                partial(clog.epoch_row_windows,
                        max_rows=self._det_window_rows))
        # Anti-alias the initial carry: constructors (and XLA CSE inside
        # jitted init paths) can hand several leaves the same underlying
        # buffer, which the donated block program rejects ("donate the
        # same buffer twice"). An eager copy per leaf guarantees distinct
        # buffers once; later programs keep them distinct (outputs alias
        # donated inputs one-to-one).
        self.carry = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).copy(), self.carry)
        # Epoch 0 starts at log offset 0 for every log.
        self.carry = self._jit_roll(self.carry, 0)
        self.step_input_history: List[Tuple[int, int]] = []
        #: vid -> FeedReader for HostFeedSource vertices
        self.feed_readers: Dict[int, Any] = {}
        #: called after every block with (last_causal_time, record_stamp) —
        #: the superstep-boundary hook timer services advance on.
        self.block_listeners: List[Any] = []
        #: optional hook fed (BlockOutputs, epoch_id) after every block —
        #: the transactional-sink egress tap (runtime/txn.py).
        self.on_block_outputs: Optional[Any] = None

        owner_idx = self.compiled._owner_idx
        nrep = self.compiled.plan.num_replicas

        def _append_many(log_rows, log_heads, rep_rows, rep_heads,
                         rows1, counts):
            # Masked single-row append per selected log + its replicas,
            # donated in-place (rows storage is referenced only by the
            # live carry; heads are returned fresh because lean snapshots
            # alias them).
            L = log_heads.shape[0]
            capm = self.compiled.log_capacity - 1
            pos = log_heads & capm
            cur = log_rows[jnp.arange(L), pos]
            sel = counts[:, None] > 0
            log_rows = log_rows.at[jnp.arange(L), pos].set(
                jnp.where(sel, rows1, cur))
            log_heads = log_heads + counts
            if nrep > 0:
                rrows1 = rows1[owner_idx]
                rcounts = counts[owner_idx]
                rpos = rep_heads & capm
                rcur = rep_rows[jnp.arange(nrep), rpos]
                rsel = rcounts[:, None] > 0
                rep_rows = rep_rows.at[jnp.arange(nrep), rpos].set(
                    jnp.where(rsel, rrows1, rcur))
                rep_heads = rep_heads + rcounts
            return log_rows, log_heads, rep_rows, rep_heads

        c0 = self.carry
        self._jit_append_many = jax.jit(
            _append_many, donate_argnums=(0, 2),
            **_mesh_kw(
                (_ns0(c0.logs.rows), _ns0(c0.logs.head),
                 _ns0(c0.replicas.rows), _ns0(c0.replicas.head),
                 _ns0(c0.logs.rows), _ns0(c0.logs.head)),
                (_ns0(c0.logs.rows), _ns0(c0.logs.head),
                 _ns0(c0.replicas.rows), _ns0(c0.replicas.head))))

        bs = self.block_steps

        def _staged_run(carry, t_all, r_all, lo, epoch, g0):
            # Staging (slice this block's inputs from the epoch-wide
            # uploaded time/rng streams, cursor carried on device) FUSED
            # with the block program itself: one dispatch per block, not
            # two — each dispatch costs ~10-20ms of tunnel latency, and
            # the staged epoch loop is the steady-state hot path.
            bi = BlockInputs(
                times=jax.lax.dynamic_slice(t_all, (lo,), (bs,)),
                rng_bits=jax.lax.dynamic_slice(r_all, (lo,), (bs,)),
                epoch=epoch, step0=g0 + lo, feeds=())
            carry, outs = self.compiled.run_block(carry, bi)
            return carry, outs, lo + bs

        self._jit_staged_run = jax.jit(
            _staged_run, donate_argnums=0,
            **_mesh_kw((self._carry_ns,) + (self._repl_ns,) * 5))

    def register_feed(self, vertex_id: int, reader) -> None:
        """Attach a rewindable reader (api/feeds.py) to a HostFeedSource
        vertex — the external-system ingestion boundary."""
        if vertex_id not in self.compiled.feed_vertices:
            raise ValueError(f"vertex {vertex_id} is not a HostFeedSource")
        self.feed_readers[vertex_id] = reader

    def _pull_feeds(self, k: int) -> Tuple[RecordBatch, ...]:
        """Pull k steps' worth of records from every feed reader into
        stacked [k, P, B] batches (one device put per feed)."""
        from clonos_tpu.api.records import empty as empty_batch
        feeds = []
        for vid in self.compiled.feed_vertices:
            v = self.job.vertices[vid]
            b = v.operator.batch_size
            reader = self.feed_readers.get(vid)
            if reader is None:
                feeds.append(empty_batch((k, v.parallelism, b)))
                continue
            rows_k = np.zeros((k, v.parallelism, b), np.int32)
            rows_v = np.zeros((k, v.parallelism, b), np.int32)
            counts = np.zeros((k, v.parallelism), np.int32)
            for s in range(v.parallelism):
                ks, vs, cnt = reader.pull_block(s, b, k)
                rows_k[:, s, :], rows_v[:, s, :] = ks, vs
                counts[:, s] = cnt
            valid = np.arange(b)[None, None, :] < counts[:, :, None]
            feeds.append(RecordBatch(
                jnp.asarray(rows_k), jnp.asarray(rows_v),
                jnp.zeros((k, v.parallelism, b), jnp.int32),
                jnp.asarray(valid)))
        return tuple(feeds)

    def _next_block_inputs(self, k: int) -> BlockInputs:
        times = np.empty((k,), np.int32)
        rngs = np.empty((k,), np.int32)
        for i in range(k):
            t = self.time_source.now()
            r = int(self._rng.randint(0, 2 ** 31, dtype=np.int64))
            times[i], rngs[i] = t, r
            self.step_input_history.append((t, r))
        return BlockInputs(
            times=jnp.asarray(times), rng_bits=jnp.asarray(rngs),
            epoch=jnp.asarray(self.epoch_id, jnp.int32),
            step0=jnp.asarray(len(self.step_input_history) - k, jnp.int32),
            feeds=self._pull_feeds(k))

    def _notify_block(self) -> None:
        # Uses the last EXECUTED step's time/stamp — the staged epoch path
        # pre-fills step_input_history, so [-1] would be the epoch end.
        if self.block_listeners and self._steps_executed:
            t = self.step_input_history[self._steps_executed - 1][0]
            stamp = self.global_record_stamp()
            for fn in self.block_listeners:
                fn(t, stamp)

    def step(self) -> StepOutputs:
        """Run one superstep on the live path (a K=1 block)."""
        self.carry, outs = self._jit_block(self.carry,
                                           self._next_block_inputs(1))
        self.step_in_epoch += 1
        self._steps_executed += 1
        if self.on_block_outputs is not None:
            self.on_block_outputs(outs, self.epoch_id)
        self._notify_block()
        return StepOutputs(
            sinks={vid: jax.tree_util.tree_map(lambda x: x[0], b)
                   for vid, b in outs.sinks.items()},
            dropped={e: d[0] for e, d in outs.dropped.items()},
            consumed=outs.consumed[0])

    def run_epoch(self) -> Optional[BlockOutputs]:
        """Run the remainder of the current epoch in block programs, then
        roll the epoch (the checkpoint fence lands here)."""
        outs = None
        remaining = self.steps_per_epoch - self.step_in_epoch
        full_blocks = remaining // self.block_steps
        if full_blocks > 1 and not self.compiled.feed_vertices:
            # Stage the full blocks' causal inputs in ONE upload and carry
            # the block cursor on device — per-block transfers cost a
            # tunnel round-trip each.
            n = full_blocks * self.block_steps
            g0 = len(self.step_input_history)
            times = np.empty((n,), np.int32)
            rngs = np.empty((n,), np.int32)
            for i in range(n):
                t = self.time_source.now()
                r = int(self._rng.randint(0, 2 ** 31, dtype=np.int64))
                times[i], rngs[i] = t, r
                self.step_input_history.append((t, r))
            t_all = jnp.asarray(times)
            r_all = jnp.asarray(rngs)
            lo = jnp.asarray(0, jnp.int32)
            epoch = jnp.asarray(self.epoch_id, jnp.int32)
            g0_d = jnp.asarray(g0, jnp.int32)
            for _ in range(full_blocks):
                self.carry, outs, lo = self._jit_staged_run(
                    self.carry, t_all, r_all, lo, epoch, g0_d)
                self.step_in_epoch += self.block_steps
                self._steps_executed += self.block_steps
                if self.on_block_outputs is not None:
                    self.on_block_outputs(outs, self.epoch_id)
                self._notify_block()
        while self.step_in_epoch < self.steps_per_epoch:
            k = min(self.block_steps,
                    self.steps_per_epoch - self.step_in_epoch)
            self.carry, outs = self._jit_block(self.carry,
                                               self._next_block_inputs(k))
            self.step_in_epoch += k
            self._steps_executed += k
            if self.on_block_outputs is not None:
                self.on_block_outputs(outs, self.epoch_id)
            self._notify_block()
        closed = self.epoch_id
        self.epoch_id += 1
        self.step_in_epoch = 0
        from clonos_tpu.obs import get_profiler
        prof = get_profiler()
        if self.spill_logs is not None:
            with prof.section("spill"):
                self._spill_epoch(closed)
        with prof.section("roll"):
            self.carry = self._jit_roll(self.carry, self.epoch_id)
            prof.fence(self.carry.logs)
        return outs

    def _spill_epoch(self, epoch: int) -> None:
        """Move the just-closed epoch's in-flight batches to the host spill
        owner (reference SpillableSubpartitionInFlightLogger writes one file
        per epoch as it closes). Policy AVAILABILITY skips epochs while the
        ring has headroom (reference spill.policy availability) — but a
        skipped epoch is only DEFERRED: before a future ring wrap could
        clobber its steps, it is retroactively spilled (the round-2/3
        advice hole: 'skip forever' silently destroys the only copy and
        recovery fails only at recovery time)."""
        for i, el in enumerate(self.carry.out_rings):
            start = int(ifl.epoch_start_step(el, epoch))
            head = int(el.head)
            n = head - start
            skip = False
            if self.spill_policy == ifl.SpillPolicy.AVAILABILITY:
                occupancy = float(jnp.asarray(ifl.size(el))) / el.ring_steps
                if occupancy < self.spill_logs[i].availability_trigger:
                    skip = True
            if skip:
                if n > 0:
                    self._pending_spill[i].append((epoch, start, n))
            elif n > 0:
                # Device arrays go straight to the spill owner: the
                # device→host copy happens on its writer thread, overlapped
                # with the next epoch's compute (the slice result is a
                # fresh buffer, so the roll's donation cannot alias it).
                batch, count, s0 = ifl.slice_steps(el, start, n)
                self.spill_logs[i].spill_epoch(epoch, int(s0), batch)
            # Retroactive flush: anything a wrap could reach within the
            # next epoch's appends must leave the ring now.
            danger = head + self.steps_per_epoch - el.ring_steps
            keep = []
            for (e, s, m) in self._pending_spill[i]:
                if s < head - el.ring_steps:
                    raise RuntimeError(
                        f"in-flight ring {i}: epoch {e} steps "
                        f"[{s}, {s + m}) were clobbered before spilling "
                        f"(AVAILABILITY policy deferred too long)")
                if s < danger:
                    batch, count, s0 = ifl.slice_steps(el, s, m)
                    self.spill_logs[i].spill_epoch(e, int(s0), batch)
                else:
                    keep.append((e, s, m))
            self._pending_spill[i] = keep
        if self.det_store is not None:
            self._spill_det_epoch(epoch)

    def _spill_det_epoch(self, epoch: int) -> None:
        """Evict the just-closed epoch's determinant windows (every stacked
        log, one fused gather) into the tiered store — called before the
        roll stamps the next epoch's start, so each window is
        ``[epoch_start, head)``, exactly :meth:`epoch_window`'s slice."""
        me = self.compiled.max_epochs
        rows, counts, starts = self._jit_det_window(
            self.carry.logs, epoch % me)
        counts_h = np.asarray(counts)
        starts_h = np.asarray(starts)
        n = int(counts_h.max()) if counts_h.size else 0
        if n > self._det_window_rows:
            # Async-heavy epoch blew past the static gather bound: degrade
            # to the exact host extraction rather than truncate rows.
            win = self.epoch_window(epoch)["logs"]
            padded = np.zeros((len(win), max(n, 1), det.NUM_LANES),
                              np.int32)
            for flat, r in win.items():
                padded[flat, :r.shape[0]] = r
            rows = padded
        elif n < self._det_window_rows:
            rows = rows[:, :max(n, 1)]   # trim ring-garbage padding
        self.det_store.put(
            epoch, int(starts_h.min()) if starts_h.size else 0,
            {"rows": rows, "counts": counts_h, "starts": starts_h})

    def notify_checkpoint_complete(self, epoch: int) -> None:
        """Truncate determinant + in-flight logs for epochs <= ``epoch``."""
        from clonos_tpu.obs import get_profiler, get_tracer
        tr = get_tracer()
        if tr.enabled:
            # checkpoint-cadence, not per-step: the epoch fence ->
            # truncation leg of the epoch lifecycle
            tr.event("epoch.inflight_truncate", epoch=epoch)
        prof = get_profiler()
        with prof.section("truncate"):
            self.carry = self._jit_trunc(self.carry, epoch)
            prof.fence(self.carry.logs)
            if self.spill_logs is not None:
                for sl in self.spill_logs:
                    sl.truncate(epoch)
            if self.det_store is not None:
                self.det_store.truncate(epoch)
        for i, pend in enumerate(self._pending_spill):
            self._pending_spill[i] = [(e, s, m) for (e, s, m) in pend
                                      if e > epoch]
        self.roll_gap_async = {k: v for k, v in self.roll_gap_async.items()
                               if k[1] > epoch}
        self.async_counts = {k: v for k, v in self.async_counts.items()
                             if k[1] > epoch}

    # --- tiered-storage surface (storage/tiered.py) --------------------------

    def _tier_stores(self):
        out = []
        if self.spill_logs is not None:
            out.extend(sl.store for sl in self.spill_logs)
        if self.det_store is not None:
            out.append(self.det_store)
        return out

    def attach_spill_digests(self, epoch: int, dg) -> None:
        """Stamp the sealed epoch's audit fingerprints onto its spilled
        tiers: each ring segment carries its ``ring/v<vid>`` channel
        chain, the determinant segment one fold over the ``log/<flat>``
        chains — the SAME digests the ledger entry pins, so a
        spill/refill round-trip is audit-verifiable for free."""
        if self.spill_logs is not None:
            for i, vid in enumerate(self.compiled.ring_vertices):
                ch = dg.channels.get(f"ring/v{vid}")
                if ch is not None:
                    self.spill_logs[i].attach_digest(epoch, ch[1].hex())
        # clonos: allow(join-discipline): det_store is attached during
        # setup, before any worker thread starts, and never rebound;
        # the tiered store's mutating methods serialize on its own
        # internal lock (the race pass models collaborator method calls
        # as mutations of the holder attribute).
        if self.det_store is not None:
            h = hashlib.blake2b(digest_size=8)
            for name in sorted(dg.channels):
                if name.startswith("log/"):
                    _, state = dg.channels[name]
                    h.update(name.encode() + b"\x00" + state)
            self.det_store.attach_digest(epoch, h.hexdigest())

    def det_rows_for_epoch(self, flat: int, epoch: int) -> np.ndarray:
        """Refill one subtask's determinant-row window for a spilled
        epoch from whichever tier holds it — bit-identical to the
        ``epoch_window(epoch)["logs"][flat]`` slice taken at the seal
        (the spilled-determinant acceptance test pins this)."""
        if self.det_store is None:
            raise RuntimeError("determinant tier disabled (no spool_dir)")
        _, arrs = self.det_store.load_epoch(epoch)
        c = int(np.asarray(arrs["counts"])[flat])
        return np.ascontiguousarray(np.asarray(arrs["rows"])[flat, :c])

    def spill_occupancy(self) -> Dict[str, int]:
        """Tier residency summed across every spill owner (rings + dets)
        — the ``spill.*`` occupancy gauges."""
        agg = {"host_epochs": 0, "host_bytes": 0,
               "disk_epochs": 0, "disk_bytes": 0}
        for st in self._tier_stores():
            for k, v in st.occupancy().items():
                agg[k] += v
        return agg

    def spill_stats(self) -> Dict[str, Any]:
        """Cumulative spill/refill movement counters summed across
        stores (bench ``--spill`` fields)."""
        agg: Dict[str, Any] = {}
        for st in self._tier_stores():
            for k, v in st.stats().items():
                agg[k] = agg.get(k, 0) + v
        return agg

    def drain_spill(self) -> None:
        """Block until every queued segment write is durable (tests,
        pre-kill quiesce in soak)."""
        for st in self._tier_stores():
            st.drain()

    def epoch_window(self, epoch: int) -> Dict[str, Any]:
        """Host snapshot of one CLOSED epoch's causal surface — the single
        extraction path behind the audit digests (obs/audit.py): the live
        seal at the epoch fence and the recovery-time recompute both read
        through here, so their chain chunk boundaries always agree.

        Returns ``{"logs": {flat: rows[n, NUM_LANES]},
        "rings": {vid: [(keys, values, timestamps) per step]}}`` — the
        determinant-row window of every subtask's causal log and, per
        output ring, each step's valid records flattened in the
        deterministic (lane, slot) order. Requires the epoch's rows to
        still be retained (not truncated past) — true at the fence that
        closes it and for every epoch at/after the latest completed
        checkpoint during recovery."""
        c = self.carry
        logs = _slice_log_window(
            epoch, np.asarray(c.logs.rows), np.asarray(c.logs.head),
            np.asarray(c.logs.epoch_starts))
        rings: Dict[int, list] = {}
        for vid, ri in self.compiled.ring_index.items():
            el = c.out_rings[ri]
            rings[vid] = _slice_ring_window(
                epoch, np.asarray(el.keys), np.asarray(el.values),
                np.asarray(el.timestamps), np.asarray(el.valid),
                np.asarray(el.epoch_starts), int(el.head))
        return {"logs": logs, "rings": rings}

    def _health_vector(self, carry: JobCarry) -> jnp.ndarray:
        """Pure: packed int32 [3 + num_rings + 1 + 1] health flags + total
        record count — ONE device value so the per-epoch control-plane
        read costs one host round-trip, not six (the tunnel RTT is the
        per-epoch overhead, not the device work)."""
        logs = carry.logs
        cap = self.compiled.log_capacity
        flags = [
            jnp.any(logs.head - logs.tail > cap),
            jnp.any(logs.latest_epoch - logs.epoch_base + 1
                    > self.compiled.max_epochs),
            jnp.any(clog.near_offset_wrap(logs)),
        ]
        for el in carry.out_rings:
            flags.append(jnp.asarray(ifl.overflowed(el)))
        if self.compiled.plan.num_replicas > 0:
            flags.append(jnp.any(carry.replicas.head - carry.replicas.tail
                                 > cap))
        else:
            flags.append(jnp.zeros((), jnp.bool_))
        vec = jnp.stack([f.astype(jnp.int32) for f in flags])
        # Trailing: total record count, then the per-task log heads — at
        # an epoch fence these ARE the checkpoint's log heads, so the
        # control plane learns them inside the one read it already pays
        # (recovery's patch phase then needs no head round-trip).
        return jnp.concatenate(
            [vec, carry.record_counts.sum()[None], carry.logs.head])

    def health_vector(self) -> np.ndarray:
        if not hasattr(self, "_jit_health"):
            self._jit_health = jax.jit(self._health_vector)
        return np.asarray(self._jit_health(self.carry))

    def _per_shard_health(self, carry: JobCarry) -> jnp.ndarray:
        """Pure: int32 [n_shards, 3] — records processed, live causal-log
        rows, live in-flight ring slots — summed over the task-axis block
        each mesh shard owns. One packed device value, same rationale as
        :meth:`_health_vector`: the control plane pays one read to learn
        which chip is hot, lagging, or about to overflow."""
        n = self.compiled.mesh.shape[self.compiled.task_axis]
        L = self.compiled.L
        g = -(-L // n)                       # block size (ceil for pad)
        pad = g * n - L

        def blocks(x):                       # [L] -> [n] block sums
            return jnp.pad(x, (0, pad)).reshape(n, g).sum(axis=1)

        rec = blocks(carry.record_counts)
        rows = blocks(carry.logs.head - carry.logs.tail)
        ring = jnp.zeros((n,), jnp.int32)
        for el in carry.out_rings:
            p = el.valid.shape[1]
            gp = -(-p // n)
            padp = gp * n - p
            v = jnp.pad(el.valid.astype(jnp.int32),
                        ((0, 0), (0, padp), (0, 0)))
            ring = ring + v.reshape(v.shape[0], n, gp,
                                    v.shape[2]).sum(axis=(0, 2, 3))
        return jnp.stack([rec, rows, ring], axis=1)

    def per_shard_health(self) -> Optional[np.ndarray]:
        """int32 [n_shards, 3] (records, log rows, ring occupancy) per
        mesh shard along the task axis; None without a mesh (the job is
        one implicit shard). Shards are the contiguous task-axis blocks
        the rule-driven PartitionSpec deals to each device."""
        if self.compiled.mesh is None:
            return None
        if not hasattr(self, "_jit_shard_health"):
            self._jit_shard_health = jax.jit(self._per_shard_health)
        return np.asarray(self._jit_shard_health(self.carry))

    def overflow_messages(self, vec: np.ndarray) -> List[str]:
        """Decode :meth:`health_vector` flags into violation strings."""
        out = []
        if vec[0]:
            out.append("causal log ring overflow (appends clobbered "
                       "un-truncated determinants)")
        if vec[1]:
            out.append("causal log epoch index overflow (> max_epochs "
                       "un-truncated epochs)")
        if vec[2]:
            out.append("causal log absolute offsets near int32 wrap "
                       "(rebase required)")
        spilled = self.spill_logs is not None
        for i in range(len(self.carry.out_rings)):
            if not spilled and vec[3 + i]:
                out.append(f"in-flight ring of vertex "
                           f"{self.compiled.ring_vertices[i]} overflowed "
                           f"with spill disabled")
        if vec[3 + len(self.carry.out_rings)]:
            out.append("replica log ring overflow")
        return out

    def check_overflow(self) -> List[str]:
        """Overflow guards the control plane must heed at every epoch roll
        (VERDICT round-1: these existed but had no caller). Returns a list
        of violation descriptions; empty = healthy."""
        return self.overflow_messages(self.health_vector())

    def plan_replicas_overflowed(self) -> bool:
        if self.compiled.plan.num_replicas == 0:
            return False
        reps = self.carry.replicas
        return bool(jnp.any(reps.head - reps.tail
                            > self.compiled.log_capacity))

    @property
    def plan(self):
        return self.compiled.plan

    def append_async_determinant(self, flat_subtask: int,
                                 d: "det.Determinant") -> None:
        """Host path for causal services: append one determinant row to a
        task's device log — and to every replica of that log, preserving
        the replicate-before-visible invariant — between blocks.
        TIMESTAMP/RNG rows get a nonzero record-count stamp so the replayer
        can tell them apart from the per-step sync anchors."""
        self.append_async_many([flat_subtask], d)

    def append_async_many(self, flat_subtasks: Sequence[int],
                          d: "det.Determinant") -> None:
        """Append one determinant row to several subtask logs (and every
        replica of each) in ONE fused device program — the control plane's
        batch path for SOURCE_CHECKPOINT / IGNORE_CHECKPOINT broadcasts
        (reference StreamTask.performCheckpoint:833-840 / :891-915)."""
        row = d.pack().copy()
        if row[det.LANE_RC] == 0 and row[det.LANE_TAG] in (det.TIMESTAMP,
                                                           det.RNG):
            row[det.LANE_RC] = self.global_record_stamp()
        if self.step_in_epoch == 0:
            # Roll-gap append: the epoch already rolled but none of its
            # steps ran, so this row belongs to the NEW epoch even though
            # it precedes the epoch's first TIMESTAMP anchor in the log.
            # Recovery rebuilds the epoch->offset index from those anchors
            # and subtracts this ledger to place the boundary exactly
            # (cluster._patch; SOURCE_CHECKPOINT / IGNORE_CHECKPOINT /
            # service calls between epochs all land here).
            for f in flat_subtasks:
                k = (f, self.epoch_id)
                self.roll_gap_async[k] = self.roll_gap_async.get(k, 0) + 1
        for f in flat_subtasks:
            k = (f, self.epoch_id)
            self.async_counts[k] = self.async_counts.get(k, 0) + 1
        rows1 = np.zeros((self.compiled.L, det.NUM_LANES), np.int32)
        counts = np.zeros((self.compiled.L,), np.int32)
        rows1[list(flat_subtasks)] = row
        counts[list(flat_subtasks)] = 1
        c = self.carry
        from clonos_tpu.obs import get_profiler
        with get_profiler().section("async-append"):
            lr, lh, rr, rh = self._jit_append_many(
                c.logs.rows, c.logs.head, c.replicas.rows, c.replicas.head,
                jnp.asarray(rows1), jnp.asarray(counts))
            self.carry = c._replace(
                logs=c.logs._replace(rows=lr, head=lh),
                replicas=c.replicas._replace(rows=rr, head=rh))

    def global_record_stamp(self) -> int:
        """Monotone nonzero stamp for async rows (1 + supersteps run)."""
        return self._steps_executed + 1

    def async_rows_since(self, flat_subtask: int, from_epoch: int) -> int:
        """How many async determinant rows this task's log holds in epochs
        >= ``from_epoch`` (host ledger — no device read)."""
        return sum(v for (f, e), v in self.async_counts.items()
                   if f == flat_subtask and e >= from_epoch)

    def install_replay_ledgers(self,
                               roll_gap: Dict[Tuple[int, int], int],
                               async_counts: Dict[Tuple[int, int], int]
                               ) -> None:
        """Merge externally re-derived roll-gap / async-row ledgers (the
        standby-host bootstrap derives them from mirrored determinant
        streams, possibly on a worker thread overlapped with replay).
        One atomic-enough install point: callers must invoke this BEFORE
        anything reads the ledgers — recovery's ``_patch`` reads
        ``roll_gap_async`` when rebuilding epoch start offsets, so the
        bootstrap joins its derivation thread at recovery's pre-patch
        join point, not after replay."""
        self.roll_gap_async.update(roll_gap)
        self.async_counts.update(async_counts)

    def first_step_inputs(self) -> BlockInputs:
        """Zeroed host-fed inputs with the FIRST-STEP block program's
        exact avals — what :func:`utils.compile_cache.
        aot_lower_first_step` lowers against (shape/dtype is all
        lowering reads; values never execute)."""
        k = self.block_steps
        return BlockInputs(times=jnp.zeros((k,), jnp.int32),
                           rng_bits=jnp.zeros((k,), jnp.int32),
                           epoch=jnp.zeros((), jnp.int32),
                           step0=jnp.zeros((), jnp.int32), feeds=())

    def fast_forward_host_rng(self, steps: int) -> None:
        """Reset the host RNG to a fresh seeded stream and consume
        exactly one per-step draw for ``steps`` supersteps — the rebuilt
        standby's stream position then matches the never-failed run's,
        so its continuation draws precisely what the original would
        have. Replay reproduces the prefix from RECORDED rng
        determinants without consuming the stream, hence the explicit
        fast-forward. Thread-safe only while nothing else draws (true
        during recovery: the replayer never touches the host RNG)."""
        self._rng = np.random.RandomState(self._seed)
        for _ in range(steps):
            self._rng.randint(0, 2 ** 31, dtype=np.int64)

    def service_factory(self, flat_subtask: int,
                        sidecar: "det.SidecarStore",
                        replay_feed=None, seed: int = 0, clock=None):
        """Per-task causal-service bundle (StreamingRuntimeContext analog:
        user host code gets time/random/external-call wrappers whose values
        record into this task's log and replay after failure)."""
        from clonos_tpu.causal.services import CausalServiceFactory
        return CausalServiceFactory(
            append=lambda d: self.append_async_determinant(flat_subtask, d),
            sidecar=sidecar, epoch_of=lambda: self.epoch_id,
            replay_feed=replay_feed, seed=seed, clock=clock)

    def lean_snapshot(self) -> LeanSnapshot:
        """The fence snapshot handed to the checkpoint coordinator. The
        pieces are DEEP-COPIED on device (one jitted program): the live
        carry's buffers are donated into subsequent block programs, so a
        reference-holding snapshot would be invalidated by the next
        block."""
        if not hasattr(self, "_jit_snap"):
            def _snap(c: JobCarry) -> LeanSnapshot:
                cp = lambda t: jax.tree_util.tree_map(
                    lambda x: jnp.asarray(x).copy(), t)
                return LeanSnapshot(
                    op_states=cp(c.op_states), edge_bufs=cp(c.edge_bufs),
                    rr_offsets=cp(c.rr_offsets),
                    record_counts=cp(c.record_counts),
                    log_heads=cp(c.logs.head),
                    ring_heads=tuple(cp(r.head) for r in c.out_rings))
            self._jit_snap = jax.jit(_snap)
        return self._jit_snap(self.carry)

    def capture_fence(self, with_window: bool = True) -> FenceHandles:
        """Capture the fence surface of the epoch that just closed as
        cheap device-side handles: ONE jitted deep-copy program (the
        fused health vector plus, when the audit seal needs it, the
        causal-log and ring window arrays), then a non-blocking
        ``copy_to_host_async`` on every output. The pipelined fence
        calls this before dispatching the next epoch's compute; the
        fence worker drains the handles into host arrays later without
        touching the live carry. Must run at the fence (right after
        ``run_epoch`` rolls), while ``epoch_starts[closed+1]`` is
        stamped and no new-epoch rows have landed."""
        key = bool(with_window)
        if not hasattr(self, "_jit_capture"):
            self._jit_capture = {}
        if key not in self._jit_capture:
            def _cap(c: JobCarry):
                cp = lambda t: jax.tree_util.tree_map(
                    lambda x: jnp.asarray(x).copy(), t)
                health = self._health_vector(c)
                if not key:
                    return health, None
                window = (
                    cp(c.logs.rows), cp(c.logs.head),
                    cp(c.logs.epoch_starts),
                    tuple((cp(el.keys), cp(el.values), cp(el.timestamps),
                           cp(el.valid), cp(el.epoch_starts), cp(el.head))
                          for el in c.out_rings))
                return health, window
            self._jit_capture[key] = jax.jit(_cap)
        health, window = self._jit_capture[key](self.carry)
        for leaf in jax.tree_util.tree_leaves((health, window)):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return FenceHandles(self.epoch_id - 1, health, window,
                            dict(self.compiled.ring_index))

    def restore(self, carry_host, epoch_id: int) -> None:
        """Adopt a checkpointed carry (standby restore path; reference
        Task.dispatchStateToStandbyTask -> initializeState). The carry must
        be an epoch-``epoch_id``-boundary snapshot; the next step continues
        epoch ``epoch_id``. Leaves are deep-copied: the live carry is
        donated into later programs, and aliasing the stored checkpoint's
        buffers would delete it out of storage on the first step."""
        self.carry = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).copy(), carry_host)
        self.epoch_id = epoch_id
        self.step_in_epoch = 0

    # --- introspection -------------------------------------------------------

    def log_sizes(self) -> np.ndarray:
        return np.asarray(clog.size(self.carry.logs))

    def vertex_state(self, vertex_id: int):
        return jax.device_get(self.carry.op_states[vertex_id])
