"""Superstep executor: the whole job as one jitted program.

This replaces the reference's task plane + stream runtime
(taskexecutor/TaskExecutor.java:422, taskmanager/Task.java:124,
runtime/tasks/StreamTask.java and the OneInputStreamTask.run hot loop,
OneInputStreamTask.java:106) with the TPU-native execution model:

- Every vertex's subtasks are a leading ``[P]`` dim of its state/batches,
  shardable over a ``jax.sharding.Mesh`` axis — the analog of deploying
  subtasks to TaskManagers.
- One **superstep** advances every vertex by one batch concurrently:
  vertex v consumes the batch its upstream routed in the *previous*
  superstep (depth-1 edge buffers). That is pipeline parallelism — all
  stages busy every step — without any queues/threads/backpressure
  machinery; the exchange scatter lowers to ICI all-to-alls under jit.
- The per-superstep causal determinants (TIMESTAMP of the causal time
  input, ORDER of the consumed channel, BUFFER_BUILT with the emitted
  record count — reference CausalBufferOrderService.java:112,
  PipelinedSubpartition buffer cuts) are appended to a **stacked device
  log** ``int32[L, capacity, 8]`` (L = all subtasks) in one fused
  ``vmap(append)`` — the per-record JVM hot path becomes one op.
- Epoch bookkeeping (record counts) is carried as ``int32[L]`` scalars
  (EpochState vectorized over subtasks).

Host Python never touches records: it feeds causal time/RNG scalars in and
reads sink batches out; epochs run as ``lax.scan`` over supersteps.
"""

from __future__ import annotations

import dataclasses
import time as _time
from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.operators import (HostFeedSource, OpContext,
                                      TwoInputOperator)
from clonos_tpu.api.records import RecordBatch, empty, zero_invalid
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import replication as rep
from clonos_tpu.graph.job_graph import JobGraph, PartitionType
from clonos_tpu.inflight import log as ifl
from clonos_tpu.parallel import routing

# Determinants appended per subtask per superstep on the sync path, in this
# fixed order: TIMESTAMP (causal time read), RNG (causal host-RNG draw),
# ORDER (consumed channel), BUFFER_BUILT (emitted batch cut). The fixed
# layout is what lets the replayer parse the log as a [steps, 4, lanes]
# tensor on device.
DETS_PER_STEP = 4


class StepInputs(NamedTuple):
    """Host-fed inputs for one superstep. ``time``/``rng_bits`` are the
    causal-service scalars (recorded as determinants; replayed from the
    log). ``feeds`` carries one RecordBatch per HostFeedSource vertex (in
    vertex-id order) — the external-system boundary (Kafka/socket analog);
    replay re-reads them from the rewindable reader."""

    time: jnp.ndarray
    rng_bits: jnp.ndarray
    feeds: Tuple[RecordBatch, ...] = ()


class JobCarry(NamedTuple):
    """The complete device-resident job state (the jitted step's carry)."""

    op_states: Tuple[Any, ...]          # per-vertex operator state pytrees
    edge_bufs: Tuple[RecordBatch, ...]  # per-edge routed batch [P_dst, cap]
    rr_offsets: Tuple[jnp.ndarray, ...] # per-edge [1] round-robin cursors
    record_counts: jnp.ndarray          # int32[L] records consumed per subtask
    logs: clog.ThreadLogState           # stacked [L, cap, lanes]
    edge_logs: Tuple[ifl.EdgeLogState, ...]  # per-edge in-flight rings
    replicas: clog.ThreadLogState       # stacked [R, cap, lanes] piggyback
                                        # replicas (see causal/replication.py)


class StepOutputs(NamedTuple):
    sinks: Dict[int, RecordBatch]       # vertex_id -> emitted batch
    dropped: Dict[int, jnp.ndarray]     # edge index -> [P_dst] drops
    consumed: jnp.ndarray               # int32[L] records consumed this step


def _det_row(tag: int, rc, payload: List) -> jnp.ndarray:
    """Build one packed determinant row from traced scalars."""
    row = jnp.zeros((det.NUM_LANES,), jnp.int32)
    row = row.at[det.LANE_TAG].set(tag)
    row = row.at[det.LANE_RC].set(jnp.asarray(rc, jnp.int32))
    for i, p in enumerate(payload):
        row = row.at[det.LANE_P + i].set(jnp.asarray(p, jnp.int32))
    return row


@dataclasses.dataclass
class CompiledJob:
    """A job graph lowered to (init_carry, superstep) pure functions."""

    job: JobGraph
    log_capacity: int = 1 << 14
    max_epochs: int = 64
    inflight_ring_steps: int = 64
    mesh: Optional[jax.sharding.Mesh] = None
    task_axis: str = "tasks"
    #: determinant-append path: None = pallas kernel on TPU, XLA scatter
    #: elsewhere; True/False forces. "interpret" runs the pallas kernel in
    #: interpreter mode (CPU tests of the kernel path).
    use_pallas_append: Optional[object] = None

    def __post_init__(self):
        self.job.validate()
        self.topo = self.job.topo_order()
        self.L = self.job.total_subtasks()
        #: vertex ids of host-fed sources, in id order (StepInputs.feeds
        #: positions align with this list).
        self.feed_vertices = [v.vertex_id for v in self.job.vertices
                              if isinstance(v.operator, HostFeedSource)]
        self.plan = rep.ReplicationPlan.from_job(self.job,
                                                 self.job.sharing_depth)
        self._owner_idx = self.plan.owner_index()
        # Per-round delta budget: worst-case per-step log growth with slack
        # to re-converge after epoch-fence bursts.
        self.max_delta = 4 * DETS_PER_STEP

    # --- sharding -----------------------------------------------------------

    def _shard_leading(self, x: jnp.ndarray) -> jnp.ndarray:
        """Constrain a [P, ...] or [L, ...] array to be sharded over the task
        mesh axis when divisible (the subtask->device deployment)."""
        if self.mesh is None:
            return x
        n = self.mesh.shape[self.task_axis]
        if x.ndim == 0 or x.shape[0] % n != 0:
            return x
        spec = jax.sharding.PartitionSpec(self.task_axis,
                                          *(None,) * (x.ndim - 1))
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(self.mesh, spec))

    def _shard_tree(self, tree):
        return jax.tree_util.tree_map(self._shard_leading, tree)

    # --- initialization -----------------------------------------------------

    def init_carry(self) -> JobCarry:
        op_states = tuple(
            v.operator.init_state(v.parallelism) for v in self.job.vertices)
        edge_bufs = tuple(
            empty((self.job.vertices[e.dst].parallelism, e.capacity))
            for e in self.job.edges)
        rr = tuple(jnp.zeros((1,), jnp.int32) for _ in self.job.edges)
        logs = jax.vmap(lambda _: clog.create(self.log_capacity, self.max_epochs)
                        )(jnp.arange(self.L))
        edge_logs = tuple(
            ifl.create(self.inflight_ring_steps,
                       self.job.vertices[e.dst].parallelism, e.capacity,
                       self.max_epochs)
            for e in self.job.edges)
        replicas = rep.create_replicas(self.plan, self.log_capacity,
                                       self.max_epochs)
        carry = JobCarry(op_states, edge_bufs, rr,
                         jnp.zeros((self.L,), jnp.int32), logs, edge_logs,
                         replicas)
        return self._shard_tree(carry)

    # --- the superstep ------------------------------------------------------

    def superstep(self, carry: JobCarry, inputs: StepInputs
                  ) -> Tuple[JobCarry, StepOutputs]:
        job = self.job
        op_states = list(carry.op_states)
        edge_bufs = list(carry.edge_bufs)
        rr_offsets = list(carry.rr_offsets)
        edge_logs = list(carry.edge_logs)
        sinks: Dict[int, RecordBatch] = {}
        dropped: Dict[int, jnp.ndarray] = {}
        consumed_parts: Dict[int, jnp.ndarray] = {}
        det_rows_parts: Dict[int, jnp.ndarray] = {}
        det_counts_parts: Dict[int, jnp.ndarray] = {}

        for vid in self.topo:
            v = job.vertices[vid]
            p = v.parallelism
            in_edges = job.in_edges(vid)
            channel = jnp.zeros((), jnp.int32)
            ctx = OpContext(
                time=inputs.time, epoch=jnp.zeros((), jnp.int32),
                step=jnp.zeros((), jnp.int32), rng_bits=inputs.rng_bits,
                subtask=jnp.arange(p, dtype=jnp.int32),
            )
            # All edge reads take the *previous* superstep's routed batch
            # (depth-1 pipeline): every vertex computes concurrently within
            # a superstep, no intra-step data dependency chain.
            if isinstance(v.operator, TwoInputOperator):
                e0, e1 = in_edges
                left, right = carry.edge_bufs[e0], carry.edge_bufs[e1]
                consumed = left.count() + right.count()
                state, out = v.operator.process2(
                    op_states[vid], left, right, ctx)
            else:
                if in_edges:
                    batch = carry.edge_bufs[in_edges[0]]
                    consumed = batch.count()
                elif vid in self.feed_vertices and inputs.feeds:
                    # Host boundary: externally pulled records.
                    batch = inputs.feeds[self.feed_vertices.index(vid)]
                    consumed = batch.count()
                else:
                    cap = v.operator.out_capacity or 1
                    batch = empty((p, cap))
                    consumed = None
                state, out = v.operator.process(op_states[vid], batch, ctx)
                # Pure generators "consume" what they emit (their record
                # count advances with generated records, like the
                # reference's source loop).
                if consumed is None:
                    consumed = out.count()
            op_states[vid] = self._shard_tree(state)
            out = self._shard_tree(out)
            if in_edges and not job.out_edges(vid):
                sinks[vid] = out
            consumed_parts[vid] = consumed

            # Determinants for this vertex's subtasks: one [P, 3, lanes]
            # block. TIMESTAMP covers the causal-time read; ORDER the channel
            # selection; BUFFER_BUILT the emitted batch cut.
            t_hi = jnp.where(inputs.time < 0, -1, 0)
            ts_row = _det_row(det.TIMESTAMP, 0, [t_hi, inputs.time])
            rng_row = _det_row(det.RNG, 0, [inputs.rng_bits])
            ord_row = _det_row(det.ORDER, 0, [channel])
            emit_counts = out.count()                      # [P]
            bb_rows = jax.vmap(
                lambda n: _det_row(det.BUFFER_BUILT, 0, [n]))(emit_counts)
            block = jnp.stack([
                jnp.broadcast_to(ts_row, (p, det.NUM_LANES)),
                jnp.broadcast_to(rng_row, (p, det.NUM_LANES)),
                jnp.broadcast_to(ord_row, (p, det.NUM_LANES)),
                bb_rows,
            ], axis=1)                                     # [P, 4, lanes]
            det_rows_parts[vid] = block
            det_counts_parts[vid] = jnp.full((p,), DETS_PER_STEP, jnp.int32)

            # Route to downstream edges.
            for eidx in job.out_edges(vid):
                e = job.edges[eidx]
                dst_p = job.vertices[e.dst].parallelism
                if e.partition == PartitionType.HASH:
                    routed, drop = routing.route_hash(
                        out, dst_p, job.num_key_groups, e.capacity)
                elif e.partition == PartitionType.FORWARD:
                    routed, drop = routing.route_forward(out, e.capacity)
                elif e.partition == PartitionType.REBALANCE:
                    routed, drop = routing.route_rebalance(
                        out, dst_p, e.capacity, rr_offsets[eidx][0])
                    rr_offsets[eidx] = (rr_offsets[eidx] + out.count().sum()
                                        ) % jnp.asarray(dst_p, jnp.int32)
                else:
                    routed, drop = routing.route_broadcast(out, dst_p, e.capacity)
                edge_bufs[eidx] = self._shard_tree(routed)
                dropped[eidx] = drop
                # In-flight logging: retain the routed batch for replay
                # (reference PipelinedSubpartition.add -> InFlightLog.log).
                edge_logs[eidx] = ifl.append_step(edge_logs[eidx], routed)

        # Stack per-vertex determinant blocks in vertex-id order -> [L, 3, lanes]
        all_rows = jnp.concatenate(
            [det_rows_parts[v.vertex_id] for v in job.vertices], axis=0)
        all_counts = jnp.concatenate(
            [det_counts_parts[v.vertex_id] for v in job.vertices], axis=0)
        consumed_all = jnp.concatenate(
            [consumed_parts[v.vertex_id] for v in job.vertices], axis=0)
        mode = self.use_pallas_append
        if mode is None:
            mode = jax.default_backend() == "tpu" and self.mesh is None
        if mode:
            from clonos_tpu.ops.log_kernels import ring_append_stacked
            new_rows, new_heads = ring_append_stacked(
                carry.logs.rows, carry.logs.head, all_rows, all_counts,
                interpret=(mode == "interpret"))
            logs = carry.logs._replace(rows=new_rows, head=new_heads)
        else:
            logs = clog.v_append(carry.logs, all_rows, all_counts)
        logs = self._shard_tree(logs)

        # Piggyback replication round: pull every owner's fresh determinant
        # suffix into the downstream replicas (the per-message netty delta
        # becomes one fused step-boundary collective).
        if self.plan.num_replicas > 0:
            replicas, _lag = rep.replicate_step(
                carry.replicas, logs, self._owner_idx, self.max_delta)
            replicas = self._shard_tree(replicas)
        else:
            replicas = carry.replicas

        new_carry = JobCarry(
            tuple(op_states), tuple(edge_bufs), tuple(rr_offsets),
            carry.record_counts + consumed_all, logs, tuple(edge_logs),
            replicas)
        return new_carry, StepOutputs(sinks, dropped, consumed_all)

    def run_steps(self, carry: JobCarry, inputs: StepInputs
                  ) -> Tuple[JobCarry, StepOutputs]:
        """Scan ``superstep`` over stacked inputs (leading dim = steps).
        Outputs are stacked per step — the unit the epoch loop executes."""
        return jax.lax.scan(self.superstep, carry, inputs)


class CausalTimeSource:
    """Host clock for the live path (reference CausalTimeService /
    PeriodicCausalTimeService.java — one amortized read per superstep).
    Produces int32 millis since executor start; values are recorded in every
    task's log as TIMESTAMP determinants by the superstep itself."""

    def __init__(self):
        self._t0 = _time.monotonic()

    def now(self) -> int:
        return int((_time.monotonic() - self._t0) * 1000) & 0x7FFFFFFF


class LocalExecutor:
    """Single-process job driver (MiniCluster analog): owns the compiled
    job, the carry, the causal time/RNG sources, and the epoch loop."""

    def __init__(self, job: JobGraph, steps_per_epoch: int = 16,
                 log_capacity: int = 1 << 14, max_epochs: int = 64,
                 inflight_ring_steps: int = 64,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 spool_dir: Optional[str] = None,
                 seed: int = 0):
        self.compiled = CompiledJob(job, log_capacity=log_capacity,
                                    max_epochs=max_epochs,
                                    inflight_ring_steps=inflight_ring_steps,
                                    mesh=mesh)
        self.job = job
        self.steps_per_epoch = steps_per_epoch
        self.carry = self.compiled.init_carry()
        self.time_source = CausalTimeSource()
        self._rng = np.random.RandomState(seed)
        self.epoch_id = 0
        self.step_in_epoch = 0
        self._jit_step = jax.jit(self.compiled.superstep)
        self._jit_scan = jax.jit(self.compiled.run_steps)

        plan = self.compiled.plan

        def _roll(carry: JobCarry, e) -> JobCarry:
            # Epoch fence: catch-up replication so replica heads equal owner
            # heads, then record the new epoch's start offset on every log,
            # replica, and in-flight ring coherently.
            replicas = carry.replicas
            if plan.num_replicas > 0:
                replicas, _ = rep.replicate_step(
                    replicas, carry.logs, self.compiled._owner_idx,
                    self.compiled.max_delta)
                replicas = rep.sync_replica_epochs(replicas, e)
            return carry._replace(
                logs=clog.v_start_epoch(carry.logs, e),
                # Ring markers sit one step before the fence: the last
                # appended batch is still in flight (see start_epoch_at).
                edge_logs=tuple(
                    ifl.start_epoch_at(el, e, jnp.maximum(el.head - 1, 0))
                    for el in carry.edge_logs),
                replicas=replicas)

        def _trunc(carry: JobCarry, e) -> JobCarry:
            replicas = carry.replicas
            if plan.num_replicas > 0:
                replicas = clog.v_truncate(replicas, e)
            return carry._replace(
                logs=clog.v_truncate(carry.logs, e),
                edge_logs=tuple(ifl.truncate(el, e)
                                for el in carry.edge_logs),
                replicas=replicas)

        self._jit_roll = jax.jit(_roll)
        self._jit_trunc = jax.jit(_trunc)
        # Host-side spill owners, one per edge (None = spill disabled).
        self.spill_logs: Optional[List[ifl.SpillingInFlightLog]] = None
        if spool_dir is not None:
            self.spill_logs = [
                ifl.SpillingInFlightLog(spool_dir, edge_id=i)
                for i in range(len(job.edges))]
        # Epoch 0 starts at log offset 0 for every log.
        self.carry = self._jit_roll(self.carry, 0)
        self.step_input_history: List[Tuple[int, int]] = []
        #: vid -> FeedReader for HostFeedSource vertices
        self.feed_readers: Dict[int, Any] = {}

    def register_feed(self, vertex_id: int, reader) -> None:
        """Attach a rewindable reader (api/feeds.py) to a HostFeedSource
        vertex — the external-system ingestion boundary."""
        if vertex_id not in self.compiled.feed_vertices:
            raise ValueError(f"vertex {vertex_id} is not a HostFeedSource")
        self.feed_readers[vertex_id] = reader

    def _pull_feeds(self) -> Tuple[RecordBatch, ...]:
        from clonos_tpu.api.records import make as make_batch, empty as empty_batch
        feeds = []
        for vid in self.compiled.feed_vertices:
            v = self.job.vertices[vid]
            b = v.operator.batch_size
            reader = self.feed_readers.get(vid)
            if reader is None:
                feeds.append(empty_batch((v.parallelism, b)))
                continue
            rows_k = np.zeros((v.parallelism, b), np.int32)
            rows_v = np.zeros((v.parallelism, b), np.int32)
            valid = np.zeros((v.parallelism, b), bool)
            for s in range(v.parallelism):
                ks, vs = reader.pull(s, b)
                n = len(ks)
                rows_k[s, :n], rows_v[s, :n], valid[s, :n] = ks, vs, True
            feeds.append(RecordBatch(
                jnp.asarray(rows_k), jnp.asarray(rows_v),
                jnp.zeros((v.parallelism, b), jnp.int32),
                jnp.asarray(valid)))
        return tuple(feeds)

    def _next_inputs(self) -> StepInputs:
        t = self.time_source.now()
        r = int(self._rng.randint(0, 2 ** 31, dtype=np.int64))
        self.step_input_history.append((t, r))
        return StepInputs(jnp.asarray(t, jnp.int32), jnp.asarray(r, jnp.int32),
                          self._pull_feeds())

    def step(self) -> StepOutputs:
        """Run one superstep on the live path."""
        self.carry, out = self._jit_step(self.carry, self._next_inputs())
        self.step_in_epoch += 1
        return out

    def run_epoch(self) -> StepOutputs:
        """Run the remainder of the current epoch as one scanned device
        program, then roll the epoch (the checkpoint fence lands here)."""
        n = self.steps_per_epoch - self.step_in_epoch
        if n > 0:
            ins = [self._next_inputs() for _ in range(n)]
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ins)
            self.carry, outs = self._jit_scan(self.carry, stacked)
        else:
            outs = None
        closed = self.epoch_id
        self.epoch_id += 1
        self.step_in_epoch = 0
        if self.spill_logs is not None:
            self._spill_epoch(closed)
        self.carry = self._jit_roll(self.carry, self.epoch_id)
        return outs

    def _spill_epoch(self, epoch: int) -> None:
        """Move the just-closed epoch's in-flight batches to the host spill
        owner (policy EAGER; reference SpillableSubpartitionInFlightLogger
        writes one file per epoch as it closes)."""
        for i, el in enumerate(self.carry.edge_logs):
            start = int(ifl.epoch_start_step(el, epoch))
            n = int(el.head) - start
            if n <= 0:
                continue
            batch, count, s0 = ifl.slice_steps(el, start, n)
            self.spill_logs[i].spill_epoch(epoch, int(s0), jax.device_get(batch))

    def notify_checkpoint_complete(self, epoch: int) -> None:
        """Truncate determinant + in-flight logs for epochs <= ``epoch``."""
        self.carry = self._jit_trunc(self.carry, epoch)
        if self.spill_logs is not None:
            for sl in self.spill_logs:
                sl.truncate(epoch)

    def append_async_determinant(self, flat_subtask: int,
                                 d: "det.Determinant") -> None:
        """Host path for causal services: append one determinant row to a
        task's device log between supersteps. TIMESTAMP/RNG rows get a
        nonzero record-count stamp so the replayer can tell them apart from
        the per-step sync anchors (see recovery.LogReplayer._parse)."""
        row = d.pack().copy()
        if row[det.LANE_RC] == 0 and row[det.LANE_TAG] in (det.TIMESTAMP,
                                                           det.RNG):
            row[det.LANE_RC] = self.global_record_stamp()
        one = jax.tree_util.tree_map(lambda x: x[flat_subtask],
                                     self.carry.logs)
        one = clog.append_one(one, jnp.asarray(row, jnp.int32))
        self.carry = self.carry._replace(logs=jax.tree_util.tree_map(
            lambda s, r: s.at[flat_subtask].set(r), self.carry.logs, one))

    def global_record_stamp(self) -> int:
        """Monotone nonzero stamp for async rows (1 + supersteps run)."""
        return len(self.step_input_history) + 1

    def service_factory(self, flat_subtask: int,
                        sidecar: "det.SidecarStore",
                        replay_feed=None, seed: int = 0, clock=None):
        """Per-task causal-service bundle (StreamingRuntimeContext analog:
        user host code gets time/random/external-call wrappers whose values
        record into this task's log and replay after failure)."""
        from clonos_tpu.causal.services import CausalServiceFactory
        return CausalServiceFactory(
            append=lambda d: self.append_async_determinant(flat_subtask, d),
            sidecar=sidecar, epoch_of=lambda: self.epoch_id,
            replay_feed=replay_feed, seed=seed, clock=clock)

    def restore(self, carry_host, epoch_id: int) -> None:
        """Adopt a checkpointed carry (standby restore path; reference
        Task.dispatchStateToStandbyTask -> initializeState). The carry must
        be an epoch-``epoch_id``-boundary snapshot; the next step continues
        epoch ``epoch_id``."""
        self.carry = jax.tree_util.tree_map(jnp.asarray, carry_host)
        self.epoch_id = epoch_id
        self.step_in_epoch = 0

    # --- introspection -------------------------------------------------------

    def log_sizes(self) -> np.ndarray:
        return np.asarray(clog.size(self.carry.logs))

    def vertex_state(self, vertex_id: int):
        return jax.device_get(self.carry.op_states[vertex_id])
