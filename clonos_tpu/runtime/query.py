"""Queryable state: external point lookups of live keyed state.

Reference: flink-runtime/src/main/java/org/apache/flink/runtime/query/
(KvStateRegistry + QueryableStateClient — an external client resolves
(job, state name, key) to the owning subtask and reads its keyed state).

TPU mapping: keyed state is dense per-key tables ``[P, K]`` on device; a
server thread must not touch device state, so the endpoint serves a
FENCE SNAPSHOT the main loop refreshes (``refresh()`` at epoch
boundaries — the same discipline as HostLogEndpoint). A lookup resolves
the key's owning subtask with the SAME key-group assignment the exchange
uses, so the served value is exactly the owning task's table entry.

Freshness contract (shared with the read-replica tier,
runtime/serve.py): every snapshot is stamped with the runner's **last
sealed epoch** — the epoch whose fence tail (audit seal + checkpoint
trigger) has completed — not the executor's live epoch counter, which
advances the moment the next epoch's compute is dispatched. Reads are
REJECTED until the first seal lands: an unstamped snapshot has no
consistency point to promise. Every response carries ``(epoch,
staleness_epochs)`` so clients can see exactly which fence they read.

The client side owns liveness: ``QueryableStateClient.query`` takes a
per-request timeout with bounded exponential backoff and raises a typed
:class:`QueryTimeoutError` when the budget is exhausted — a hung
endpoint costs the caller a bounded wait, never a wedge.
"""

from __future__ import annotations

import socket
import threading
import time as _time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from clonos_tpu.parallel import transport as tp
from clonos_tpu.parallel.routing import hash32_np, subtask_for_key_group


class QueryTimeoutError(TimeoutError):
    """A query's per-request budget (timeout x bounded retries) ran out
    without a response — the endpoint is hung, unreachable, or
    overloaded. Carries enough context to route around the endpoint."""

    def __init__(self, address, attempts: int, budget_s: float):
        self.address = tuple(address)
        self.attempts = attempts
        self.budget_s = budget_s
        super().__init__(
            f"query to {self.address} timed out after {attempts} "
            f"attempt(s) within {budget_s:.3f}s")


class QueryRejectedError(RuntimeError):
    """The endpoint refused the read — most commonly no epoch has
    sealed yet, so there is no fence-consistent snapshot to serve."""


def owner_subtask_np(keys: np.ndarray, parallelism: int,
                     num_key_groups: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Host twin of the exchange's key->owner map for a whole key batch:
    ``(key_group, owning_subtask)`` per key. The single shared copy of
    the assignment every read path (owner endpoint, replicas, router)
    must agree on — byte-for-byte the device exchange's routing."""
    kg = (hash32_np(np.asarray(keys, np.int64))
          % num_key_groups).astype(np.int64)
    sub = np.asarray(subtask_for_key_group(kg, parallelism,
                                           num_key_groups), np.int64)
    return kg, sub


def _call_with_retry(client: tp.ControlClient, mtype: int,
                     payload: bytes, address, timeout_s: float,
                     retries: int, backoff_s: float):
    """One logical request with bounded exponential backoff: each
    attempt gets the socket timeout the client was built with; transport
    errors retry with ``backoff_s * 2**i`` sleeps (capped count), then
    raise :class:`QueryTimeoutError`. Application errors (ERROR frames)
    pass straight through — only liveness failures retry."""
    t0 = _time.monotonic()
    attempts = 0
    while True:
        attempts += 1
        try:
            return client.call(mtype, payload)
        except (socket.timeout, TimeoutError, OSError):
            # ControlClient already dropped the socket; the next call
            # reconnects. Budget check BEFORE the sleep so a dead
            # endpoint costs at most retries * (timeout + backoff).
            if attempts > retries:
                raise QueryTimeoutError(
                    address, attempts, _time.monotonic() - t0) from None
            _time.sleep(min(backoff_s * (2 ** (attempts - 1)), 1.0))


class QueryableStateEndpoint:
    """Serves (vertex, state_name, key) lookups over the control
    transport, point-wise or batched (one request, many keys)."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0):
        self.runner = runner
        self._lock = threading.Lock()
        self._snap: Dict[Tuple[int, str], np.ndarray] = {}
        self._epoch = -1
        self.reads = 0
        self.refresh()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    def refresh(self, epoch: Optional[int] = None) -> None:
        """Main-thread fence snapshot of every vertex's array states,
        stamped with the runner's LAST SEALED epoch — not the live
        epoch counter, which has already moved on to the epoch being
        computed. Before the first seal the stamp stays -1 and reads
        are rejected (no fence to be consistent with).

        ``epoch`` overrides the stamp for fence-hook callers: with the
        PIPELINED fence the hook fires on the main thread while the
        seal is still in flight on the fence worker, so the runner's
        ``last_sealed_epoch`` trails the fence the snapshot actually
        captures — the hook passes its own ``closed`` epoch instead."""
        sealed = (int(epoch) if epoch is not None
                  else int(getattr(self.runner, "last_sealed_epoch", -1)))
        snap: Dict[Tuple[int, str], np.ndarray] = {}
        if sealed >= 0:
            for v in self.runner.job.vertices:
                st = self.runner.executor.vertex_state(v.vertex_id)
                if not isinstance(st, dict):
                    continue
                for name, arr in st.items():
                    snap[(v.vertex_id, name)] = np.asarray(arr)
        with self._lock:
            self._snap = snap
            self._epoch = sealed

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def _resolve(self, req: dict):
        """Shared request validation: returns (arr, epoch, parallelism)
        or an (ERROR, payload) response tuple."""
        vid = req["vertex"]
        name = req.get("state", "acc")
        with self._lock:
            arr = self._snap.get((vid, name))
            epoch = self._epoch
        if epoch < 0:
            return tp.ERROR, tp.pack_json(
                {"error": "no epoch sealed yet: refresh() ran before "
                          "the first fence tail completed — reads have "
                          "no consistency point", "rejected": True})
        if arr is None:
            return tp.ERROR, tp.pack_json(
                {"error": f"no state ({vid}, {name})"})
        p = self.runner.job.vertices[vid].parallelism
        if arr.ndim < 2 or arr.shape[0] != p:
            return tp.ERROR, tp.pack_json(
                {"error": f"state ({vid}, {name}) of shape "
                          f"{list(arr.shape)} is not keyed"})
        return arr, epoch, p

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype not in (tp.QUERY_STATE, tp.QUERY_BATCH,
                         tp.SERVE_STATUS):
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        if mtype == tp.SERVE_STATUS:
            with self._lock:
                epoch = self._epoch
            return tp.QUERY_RESPONSE, tp.pack_json(
                {"epoch": epoch, "staleness_epochs": 0,
                 "role": "owner", "reads": self.reads})
        req = tp.unpack_json(payload)
        tp.adopt_hlc(req, verb="QUERY_STATE" if mtype == tp.QUERY_STATE
                     else "QUERY_BATCH")
        got = self._resolve(req)
        if len(got) == 2:
            return got
        arr, epoch, p = got
        job = self.runner.job
        if mtype == tp.QUERY_STATE:
            key = req["key"]
            if not 0 <= key < arr.shape[-1]:
                return tp.ERROR, tp.pack_json(
                    {"error": f"key {key} out of range "
                              f"[0, {arr.shape[-1]})"})
            # Host-side (numpy) key->owner math: a server thread must
            # never dispatch device work (jax is main-thread-only on
            # some backends; hash32_np is the exchange hash's host twin,
            # and subtask_for_key_group is the SAME pure assignment the
            # exchange compiles in).
            kg, sub = owner_subtask_np(np.asarray(key), p,
                                       job.num_key_groups)
            self.reads += 1
            val = arr[int(sub), ..., key]
            return tp.QUERY_RESPONSE, tp.pack_json(
                {"value": np.asarray(val).tolist(),
                 "subtask": int(sub), "key_group": int(kg),
                 "epoch": epoch, "staleness_epochs": 0})
        keys = np.asarray(req["keys"], np.int64)
        if keys.size and (keys.min() < 0 or keys.max() >= arr.shape[-1]):
            return tp.ERROR, tp.pack_json(
                {"error": f"key out of range [0, {arr.shape[-1]})"})
        kg, sub = owner_subtask_np(keys, p, job.num_key_groups)
        self.reads += int(keys.size)
        vals = arr[sub, ..., keys]
        return tp.QUERY_BATCH_RESPONSE, tp.pack_json(
            {"values": np.asarray(vals).tolist(),
             "subtasks": sub.tolist(), "key_groups": kg.tolist(),
             "epoch": epoch, "staleness_epochs": 0})

    def close(self) -> None:
        self.server.close()


class QueryableStateClient:
    """External lookup client (QueryableStateClient analog) with a
    per-request timeout and bounded exponential backoff — a hung
    endpoint costs a bounded wait and a typed
    :class:`QueryTimeoutError`, never an indefinite block."""

    def __init__(self, address: Tuple[int, int],
                 timeout_s: float = 5.0, retries: int = 2,
                 backoff_s: float = 0.05):
        self.address = tuple(address)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._client = tp.ControlClient(self.address,
                                        timeout_s=self.timeout_s)

    def _call(self, mtype: int, payload: dict) -> dict:
        if mtype in (tp.QUERY_STATE, tp.QUERY_BATCH):
            tp.attach_hlc(payload,
                          verb="QUERY_STATE" if mtype == tp.QUERY_STATE
                          else "QUERY_BATCH")
        rt, resp = _call_with_retry(
            self._client, mtype, tp.pack_json(payload), self.address,
            self.timeout_s, self.retries, self.backoff_s)
        out = tp.unpack_json(resp)
        if rt == tp.ERROR:
            if out.get("rejected"):
                raise QueryRejectedError(out["error"])
            raise KeyError(out["error"])
        return out

    def query(self, vertex: int, key: int, state: str = "acc") -> dict:
        return self._call(tp.QUERY_STATE,
                          {"vertex": vertex, "state": state, "key": key})

    def query_batch(self, vertex: int, keys: Sequence[int],
                    state: str = "acc") -> dict:
        """Many keys in ONE request/response — the wire half of the
        batched read path (the replica endpoint additionally fuses the
        device reads into one gather; runtime/serve.py)."""
        return self._call(tp.QUERY_BATCH,
                          {"vertex": vertex, "state": state,
                           "keys": [int(k) for k in keys]})

    def status(self) -> dict:
        """Freshness probe: ``{"epoch", "staleness_epochs", ...}``."""
        return self._call(tp.SERVE_STATUS, {})

    def close(self) -> None:
        self._client.close()
