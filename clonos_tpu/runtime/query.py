"""Queryable state: external point lookups of live keyed state.

Reference: flink-runtime/src/main/java/org/apache/flink/runtime/query/
(KvStateRegistry + QueryableStateClient — an external client resolves
(job, state name, key) to the owning subtask and reads its keyed state).

TPU mapping: keyed state is dense per-key tables ``[P, K]`` on device; a
server thread must not touch device state, so the endpoint serves a
FENCE SNAPSHOT the main loop refreshes (``refresh()`` at epoch
boundaries — the same discipline as HostLogEndpoint). A lookup resolves
the key's owning subtask with the SAME key-group assignment the exchange
uses, so the served value is exactly the owning task's table entry. The
snapshot is epoch-stamped: clients see which fence their read is from
(the reference's client reads are similarly only
checkpoint-consistent)."""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from clonos_tpu.parallel import transport as tp
from clonos_tpu.parallel.routing import hash32_np, subtask_for_key_group


class QueryableStateEndpoint:
    """Serves (vertex, state_name, key) lookups over the control
    transport."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0):
        self.runner = runner
        self._lock = threading.Lock()
        self._snap: Dict[Tuple[int, str], np.ndarray] = {}
        self._epoch = -1
        self.refresh()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    def refresh(self) -> None:
        """Main-thread fence snapshot of every vertex's array states."""
        snap: Dict[Tuple[int, str], np.ndarray] = {}
        for v in self.runner.job.vertices:
            st = self.runner.executor.vertex_state(v.vertex_id)
            if not isinstance(st, dict):
                continue
            for name, arr in st.items():
                snap[(v.vertex_id, name)] = np.asarray(arr)
        with self._lock:
            self._snap = snap
            self._epoch = self.runner.executor.epoch_id

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype != tp.QUERY_STATE:
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        req = tp.unpack_json(payload)
        vid = req["vertex"]
        name = req.get("state", "acc")
        key = req["key"]
        with self._lock:
            arr = self._snap.get((vid, name))
            epoch = self._epoch
        if arr is None:
            return tp.ERROR, tp.pack_json(
                {"error": f"no state ({vid}, {name})"})
        job = self.runner.job
        p = job.vertices[vid].parallelism
        if arr.ndim < 2 or arr.shape[0] != p or not (
                0 <= key < arr.shape[-1]):
            return tp.ERROR, tp.pack_json(
                {"error": f"state ({vid}, {name}) of shape "
                          f"{list(arr.shape)} is not keyed or key "
                          f"{key} out of range"})
        # Host-side (numpy) key->owner math: a server thread must never
        # dispatch device work (jax is main-thread-only on some
        # backends; hash32_np is the exchange hash's host twin, and
        # subtask_for_key_group is the SAME pure assignment the exchange
        # compiles in).
        kg = int(hash32_np(np.asarray(key, np.int64))
                 % job.num_key_groups)
        sub = int(subtask_for_key_group(kg, p, job.num_key_groups))
        val = arr[sub, ..., key]
        return tp.QUERY_RESPONSE, tp.pack_json(
            {"value": np.asarray(val).tolist(), "subtask": sub,
             "key_group": kg, "epoch": epoch})

    def close(self) -> None:
        self.server.close()


class QueryableStateClient:
    """External lookup client (QueryableStateClient analog)."""

    def __init__(self, address: Tuple[str, int]):
        self._client = tp.ControlClient(tuple(address))

    def query(self, vertex: int, key: int,
              state: str = "acc") -> dict:
        rt, resp = self._client.call(tp.QUERY_STATE, tp.pack_json(
            {"vertex": vertex, "state": state, "key": key}))
        out = tp.unpack_json(resp)
        if rt == tp.ERROR:
            raise KeyError(out["error"])
        return out

    def close(self) -> None:
        self._client.close()
