"""Multi-tenant dispatcher: one slot pool, many concurrent jobs.

Reference shape (PAPER.md layer 4): ``Dispatcher.submitJob`` spawns a
per-job JobMaster over a shared TaskManager pool — many JobGraphs, one
cluster. Until now the reproduction collapsed this to exactly one job
per cluster: ``SlotPoolScheduler`` assumed it owned every slot, every
checkpoint directory, the one leader lease, and the whole metric
namespace. This module is the missing layer:

- **Job intake** (:meth:`Dispatcher.submit_job`, also served over the
  control wire as SUBMIT_JOB / JOB_STATUS / CANCEL_JOB): a job spec +
  :class:`TenantConfig` mints a deterministic job id
  (``<tenant>-<seq>``) and enters admission.
- **Fair-share admission** (:class:`AdmissionController`): per-tenant
  slot quotas reject over-quota submissions with a TYPED error
  (:class:`QuotaExceededError` — machine-readable over the wire), and a
  full pool queues jobs strict-FIFO; completions and cancellations
  release slots and drain the queue head. FIFO is the fairness rule: a
  large job at the head is never starved by small jobs skipping past
  it.
- **Per-job isolation**: each admitted job gets its own
  ``FileLeaderElection`` (lease scoped by ``leader.job_lease_path`` so
  two jobs' leaders cannot fence each other's DEPLOYs), its own
  ``SlotPoolScheduler`` bound to the SHARED :class:`SlotPool`
  (slot keys job-scoped), a checkpoint/ledger root at
  ``<root>/<job_id>/``, and its own job-tagged tracer — every durable
  and observable artifact is namespaced by job id.
- **Recovery-storm containment**: a worker death strikes every tenant
  placed on it. The dispatcher round-robins ``recover_worker`` calls
  across the affected jobs with ``max_groups =
  TenantConfig.max_concurrent_recoveries`` per call, and the slice
  worker defers rebuild work behind healthy epochs (one rebuild per
  round — ``SliceWorker.step``): between any two causal rebuilds every
  healthy tenant co-hosted on the survivor reaches its next checkpoint
  fence, so one tenant's SIGKILL storm inflates a neighbor's fence
  latency by a bounded factor, not by the whole storm.
- **Per-tenant observability**: ``metrics_extra`` (the JobMaster
  MetricsEndpoint ``extra`` supplier) merges
  ``JobMasterServer.cluster_metrics()`` — which rolls worker keys up
  into ``cluster.job.<jid>.*`` — with ``tenant.<t>.slots-held/quota/
  jobs-running/jobs-queued`` and ``dispatcher.queue-depth``;
  ``clonos_tpu top`` renders the per-job section from the same keys.

Threading: wire handlers (ControlServer threads) only take
``self._lock`` and mutate bookkeeping dicts — all slow work (jax
deploys, recovery, pool mutation) happens on the MAIN thread inside
:meth:`step`, mirroring the slice worker's build-on-main-loop rule. The
shared :class:`SlotPool` is therefore main-thread-only; admission
decisions use the accounting view (live advertised slots minus held)
instead of touching the pool.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

import collections

from clonos_tpu.obs import NullTracer, Tracer, get_tracer
from clonos_tpu.parallel import transport as tp
from clonos_tpu.runtime import remote as rm
from clonos_tpu.runtime.leader import FileLeaderElection, job_lease_path
from clonos_tpu.runtime.scheduler import SlotPool, SlotPoolScheduler


class QuotaExceededError(RuntimeError):
    """A tenant asked for more slots than its quota allows. Typed — the
    wire handler serializes ``error_type`` + the fields so a client can
    distinguish policy rejection from infrastructure failure."""

    error_type = "quota-exceeded"

    def __init__(self, tenant: str, requested: int, quota: int,
                 held: int):
        super().__init__(
            f"tenant {tenant!r}: requesting {requested} slot(s) would "
            f"exceed quota {quota} ({held} already held or queued)")
        self.tenant = tenant
        self.requested = requested
        self.quota = quota
        self.held = held

    def wire_payload(self) -> dict:
        return {"error": str(self), "error_type": self.error_type,
                "tenant": self.tenant, "requested": self.requested,
                "quota": self.quota, "held": self.held}


@dataclasses.dataclass
class TenantConfig:
    """Per-submission tenancy knobs (the SUBMIT_JOB ``tenant_config``
    field; every knob has a safe default so ``{}`` is a valid config).

    ``slots`` is how many slices the job is cut into — each occupies
    one pool slot. ``workers`` is a soft placement hint (slice *i*
    prefers ``workers[i % len]``; allocation falls back to any free
    slot). ``max_concurrent_recoveries`` caps how many of this job's
    groups one recovery round may rebuild — the storm-containment
    knob."""

    tenant: str = "default"
    slots: int = 1
    max_concurrent_recoveries: int = 1
    workers: Optional[List[str]] = None

    def __post_init__(self):
        self.tenant = str(self.tenant)
        # Tenant names embed into job ids, metric keys (split on "."),
        # and lease paths — keep them flat tokens.
        if (not self.tenant or "." in self.tenant or "/" in self.tenant
                or "-" in self.tenant):
            raise ValueError(
                f"tenant name {self.tenant!r} must be non-empty and "
                f"contain no '.', '/' or '-'")
        self.slots = int(self.slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        self.max_concurrent_recoveries = max(
            1, int(self.max_concurrent_recoveries))
        if self.workers is not None:
            self.workers = [str(w) for w in self.workers]

    @classmethod
    def from_any(cls, obj) -> "TenantConfig":
        if obj is None:
            return cls()
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            known = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in obj.items() if k in known})
        raise TypeError(f"tenant_config: expected dict or TenantConfig, "
                        f"got {type(obj).__name__}")


class AdmissionController:
    """Fair-share admission over one slot pool: per-tenant quotas,
    strict-FIFO queueing on a full pool, typed rejection.

    Pure bookkeeping with no lock of its own — the Dispatcher serializes
    every call under its lock. Quota is charged against a tenant's
    RESERVATION (held + queued): a submission that would overflow the
    quota even counting its queued jobs is rejected up front rather
    than admitted later in violation."""

    def __init__(self, quotas: Optional[Dict[str, int]] = None,
                 default_quota: Optional[int] = None):
        self.quotas = {str(t): int(q) for t, q in (quotas or {}).items()}
        self.default_quota = (None if default_quota is None
                              else int(default_quota))
        self._held: Dict[str, int] = {}
        self._queue: Deque[str] = collections.deque()
        self._pending: Dict[str, Tuple[str, int]] = {}
        #: transition observers: ``fn(kind, **fields)`` on every
        #: admission transition (admit/queue/reject/cancel/release) —
        #: the verify conformance layer's observation surface. Called
        #: under the Dispatcher's lock like everything else here.
        self.transition_observers: List = []

    def _observe(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    def quota(self, tenant: str) -> Optional[int]:
        return self.quotas.get(tenant, self.default_quota)

    def held(self, tenant: str) -> int:
        return self._held.get(tenant, 0)

    def total_held(self) -> int:
        return sum(self._held.values())

    def reserved(self, tenant: str) -> int:
        return self.held(tenant) + sum(
            n for t, n in self._pending.values() if t == tenant)

    def queued(self) -> List[str]:
        return list(self._queue)

    def request(self, job_id: str, tenant: str, slots: int,
                free_slots: int) -> str:
        """Admit (``"admitted"``: slots held), queue (``"queued"``: FIFO
        behind earlier arrivals), or raise :class:`QuotaExceededError`.
        A non-empty queue always queues — later arrivals never jump
        earlier ones even when slots happen to be free for them."""
        q = self.quota(tenant)
        if q is not None and self.reserved(tenant) + slots > q:
            self._observe("reject", job_id=job_id, tenant=tenant,
                          slots=slots)
            raise QuotaExceededError(tenant, slots, q,
                                     self.reserved(tenant))
        if self._queue or free_slots < slots:
            self._queue.append(job_id)
            self._pending[job_id] = (tenant, slots)
            self._observe("queue", job_id=job_id, tenant=tenant,
                          slots=slots)
            return "queued"
        self._held[tenant] = self.held(tenant) + slots
        self._observe("admit", job_id=job_id, tenant=tenant,
                      slots=slots)
        return "admitted"

    def admit_queued(self, free_slots: int) -> List[str]:
        """Drain the queue head while slots last — STRICT FIFO: a head
        job too large for the remaining slots blocks the drain (no
        skipping — that is the no-starvation rule). Returns the job ids
        admitted this call, slots now held."""
        out: List[str] = []
        while self._queue:
            tenant, slots = self._pending[self._queue[0]]
            if slots > free_slots:
                break
            jid = self._queue.popleft()
            del self._pending[jid]
            self._held[tenant] = self.held(tenant) + slots
            free_slots -= slots
            self._observe("admit", job_id=jid, tenant=tenant,
                          slots=slots)
            out.append(jid)
        return out

    def cancel_queued(self, job_id: str) -> bool:
        if job_id not in self._pending:
            return False
        tenant, slots = self._pending[job_id]
        del self._pending[job_id]
        self._queue.remove(job_id)
        self._observe("cancel", job_id=job_id, tenant=tenant,
                      slots=slots)
        return True

    def release(self, tenant: str, slots: int) -> None:
        self._held[tenant] = max(0, self.held(tenant) - int(slots))
        self._observe("release", tenant=tenant, slots=int(slots))


#: job lifecycle: QUEUED -> ADMITTED -> DEPLOYING -> RUNNING ->
#: FINISHED, with CANCELLED / FAILED terminal exits and CANCELLING the
#: main-loop handoff for cancelling a deployed job
_ACTIVE_STATES = ("ADMITTED", "DEPLOYING", "RUNNING", "CANCELLING")


@dataclasses.dataclass
class JobRecord:
    job_id: str
    tenant: str
    config: TenantConfig
    job_spec: str
    state: str
    external_feeds: Dict[int, dict]
    target_epochs: int
    scheduler: Optional[SlotPoolScheduler] = None
    election: Optional[FileLeaderElection] = None
    tracer: object = None
    error: Optional[str] = None


class Dispatcher:
    """One dispatcher process: accepts jobs, runs a per-job JobMaster
    state machine (election + scheduler) against one shared slot pool,
    and contains each tenant's failure blast radius. See the module
    docstring for the architecture; the driving loop is
    ``while ...: dispatcher.step()`` on the MAIN thread (jax work and
    pool mutation live there), with submissions arriving from wire
    handler threads at any time."""

    def __init__(self, lease_path: str,
                 checkpoint_root: str = "/tmp/clonos-dispatcher",
                 quotas: Optional[Dict[str, int]] = None,
                 default_quota: Optional[int] = None,
                 runner_kw: Optional[dict] = None, feed_batch: int = 8,
                 target_epochs: int = 8, complete_every: int = 1,
                 deploy_timeout_s: float = 240.0,
                 trace_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout_s: float = 5.0,
                 jm: Optional[rm.JobMasterServer] = None,
                 serve: bool = True):
        self.lease_path = lease_path
        self.checkpoint_root = checkpoint_root
        self.runner_kw = dict(runner_kw or {})
        self.feed_batch = feed_batch
        self.target_epochs = target_epochs
        self.complete_every = complete_every
        self.deploy_timeout_s = deploy_timeout_s
        self.trace_dir = trace_dir
        self.jm = jm if jm is not None else rm.JobMasterServer(
            heartbeat_timeout_s=heartbeat_timeout_s, host=host)
        self._owns_jm = jm is None
        self.pool = SlotPool()              # main-thread-only (see above)
        self.admission = AdmissionController(quotas, default_quota)
        self._jobs: Dict[str, JobRecord] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self.server = (tp.ControlServer(self._handle, host, port)
                       if serve else None)
        self.address = self.server.address if self.server else None

    # --- intake (wire-thread safe) -------------------------------------------

    def _free_slots_locked(self) -> int:
        """Admission's pool view: live advertised slots minus held.
        (The SlotPool itself is main-thread-only; this accounting view
        agrees with it because every admitted job holds exactly
        ``config.slots`` pool slots until release.)"""
        dead = set(self.jm.expired())
        total = sum(n for eid, n in self.jm.slots().items()
                    if eid not in dead)
        return max(0, total - self.admission.total_held())

    def submit_job(self, job_spec: str, tenant_config=None,
                   external_feeds: Optional[Dict[int, dict]] = None,
                   target_epochs: Optional[int] = None) -> dict:
        """Mint a job id and run admission. Returns ``{"job_id",
        "state"}`` (ADMITTED or QUEUED); raises
        :class:`QuotaExceededError` on policy rejection. Deployment
        happens on the next main-loop :meth:`step`."""
        cfg = TenantConfig.from_any(tenant_config)
        feeds = {int(v): dict(spec)
                 for v, spec in (external_feeds or {}).items()}
        with self._lock:
            self._seq += 1
            job_id = f"{cfg.tenant}-{self._seq:03d}"
            verdict = self.admission.request(
                job_id, cfg.tenant, cfg.slots, self._free_slots_locked())
            rec = JobRecord(
                job_id=job_id, tenant=cfg.tenant, config=cfg,
                job_spec=str(job_spec),
                state="ADMITTED" if verdict == "admitted" else "QUEUED",
                external_feeds=feeds,
                target_epochs=int(target_epochs or self.target_epochs))
            self._jobs[job_id] = rec
            return {"job_id": job_id, "state": rec.state}

    def cancel_job(self, job_id: str) -> dict:
        """Cancel a job. Queued jobs leave the queue; admitted-but-
        undeployed jobs release their held slots; deployed jobs are
        handed to the main loop (CANCELLING) which releases their pool
        slots and abandons the deployment — there is no UNDEPLOY wire
        verb, so the workers run the already-shipped slices to their
        epoch target but the slots are free for the next admission."""
        with self._lock:
            rec = self._jobs.get(job_id)
            if rec is None:
                raise KeyError(
                    f"unknown job {job_id!r} (have "
                    f"{sorted(self._jobs)})")
            if rec.state == "QUEUED":
                self.admission.cancel_queued(job_id)
                rec.state = "CANCELLED"
            elif rec.state == "ADMITTED":
                self.admission.release(rec.tenant, rec.config.slots)
                rec.state = "CANCELLED"
            elif rec.state in ("DEPLOYING", "RUNNING"):
                rec.state = "CANCELLING"
            return {"job_id": job_id, "state": rec.state}

    def _job_info_locked(self, rec: JobRecord) -> dict:
        info = {"job_id": rec.job_id, "tenant": rec.tenant,
                "state": rec.state, "slots": rec.config.slots}
        if rec.error:
            info["error"] = rec.error
        if rec.scheduler is not None:
            info["placements"] = {
                str(g): w for g, w in sorted(
                    rec.scheduler.placements.items())}
        return info

    def jobs(self) -> List[dict]:
        with self._lock:
            return [self._job_info_locked(rec)
                    for _, rec in sorted(self._jobs.items())]

    # --- wire surface --------------------------------------------------------

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype == tp.SUBMIT_JOB:
            req = tp.unpack_json(payload)
            try:
                res = self.submit_job(
                    req["job"], req.get("tenant_config"),
                    external_feeds=req.get("external_feeds"),
                    target_epochs=req.get("target_epochs"))
            except QuotaExceededError as e:
                return tp.ERROR, tp.pack_json(e.wire_payload())
            return tp.OK, tp.pack_json(res)
        if mtype == tp.JOB_STATUS:
            req = tp.unpack_json(payload) if payload else {}
            job_id = (req or {}).get("job_id")
            if job_id:
                with self._lock:
                    rec = self._jobs.get(job_id)
                    if rec is None:
                        return tp.ERROR, tp.pack_json(
                            {"error": f"unknown job {job_id!r} (have "
                                      f"{sorted(self._jobs)})"})
                    return tp.OK, tp.pack_json(self._job_info_locked(rec))
            return tp.OK, tp.pack_json({"jobs": self.jobs()})
        if mtype == tp.CANCEL_JOB:
            req = tp.unpack_json(payload)
            return tp.OK, tp.pack_json(self.cancel_job(req["job_id"]))
        return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})

    # --- main loop -----------------------------------------------------------

    def _job_tracer(self, job_id: str):
        """Per-job tracer: file sink under ``trace_dir`` when set, ring
        only when the process tracer is on, Null otherwise (tracing-off
        dispatchers add no wire fields). The trace id is job-tagged —
        every span of this job, on any worker, carries the job id."""
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
            tr = Tracer(f"jm.{job_id}", path=os.path.join(
                self.trace_dir, f"trace-jm.{job_id}.jsonl"))
        elif get_tracer().enabled:
            tr = Tracer(f"jm.{job_id}")
        else:
            return NullTracer()
        tr.trace_id = f"{job_id}:{tr.trace_id}"
        return tr

    def _live_offers(self) -> Dict[str, int]:
        dead = set(self.jm.expired())
        return {eid: n for eid, n in self.jm.slots().items()
                if eid not in dead}

    def _launch(self, rec: JobRecord) -> None:
        """Main thread: election + scheduler + deploy for one admitted
        job. Everything durable lands under ``<root>/<job_id>/`` and
        the lease under ``job_lease_path`` — full per-job namespace."""
        job_id = rec.job_id
        election = FileLeaderElection(
            job_lease_path(self.lease_path, job_id),
            f"dispatcher.{job_id}")
        if not election.try_acquire():
            raise RuntimeError(
                f"job {job_id}: could not acquire its leader lease "
                f"(stale claim under {self.lease_path!r}?)")
        rec.election = election
        rec.tracer = self._job_tracer(job_id)
        rec.scheduler = SlotPoolScheduler(
            self.jm, election, rec.job_spec, runner_kw=self.runner_kw,
            feed_batch=self.feed_batch,
            target_epochs=rec.target_epochs,
            complete_every=self.complete_every,
            checkpoint_root=os.path.join(self.checkpoint_root, job_id),
            deploy_timeout_s=self.deploy_timeout_s,
            job_id=job_id, tenant=rec.tenant, pool=self.pool,
            tracer=rec.tracer)
        self.pool.sync_offers(self._live_offers())
        rec.scheduler.deploy(workers=rec.config.workers,
                             external_feeds=rec.external_feeds,
                             num_slices=rec.config.slots)

    def _teardown(self, rec: JobRecord, state: str,
                  error: Optional[str] = None) -> None:
        """Main thread: release the job's pool slots and admission
        hold, close its scheduler/tracer, move it to a terminal
        state."""
        if rec.scheduler is not None:
            rec.scheduler.release_pool_slots()
            rec.scheduler.close()
        if rec.tracer is not None:
            rec.tracer.close()
        with self._lock:
            self.admission.release(rec.tenant, rec.config.slots)
            rec.state = state
            if error:
                rec.error = error
        if state == "FAILED":
            # Terminal job failure is an incident trigger: the bundle
            # lands before the job's forensic context (scheduler,
            # tracer) is garbage-collected. No-op when the incident
            # plane is disabled.
            from clonos_tpu.obs.incident import get_incidents
            get_incidents().signal(
                "job.failure", job_id=rec.job_id, tenant=rec.tenant,
                error=(error or "")[:200])

    def _deploy_ready(self) -> bool:
        with self._lock:
            ready = [rec for rec in self._jobs.values()
                     if rec.state == "ADMITTED"]
            for rec in ready:
                rec.state = "DEPLOYING"
        for rec in ready:
            try:
                self._launch(rec)
            except Exception as e:
                self._teardown(rec, "FAILED", error=str(e))
                continue
            with self._lock:
                if rec.state == "DEPLOYING":   # not cancelled meanwhile
                    rec.state = "RUNNING"
        return bool(ready)

    def _running(self) -> List[JobRecord]:
        with self._lock:
            return [rec for rec in self._jobs.values()
                    if rec.state == "RUNNING"]

    def _detect_failures(self) -> bool:
        """Round-robin recovery across the jobs a dead worker struck:
        each affected job rebuilds at most
        ``max_concurrent_recoveries`` groups per pass, so no single
        tenant's storm monopolizes the recovery path (worker-side, the
        slice worker additionally admits one rebuild per epoch round —
        fence traffic first)."""
        progressed = False
        for worker in sorted(set(self.jm.expired())):
            while True:
                remaining = False
                for rec in self._running():
                    sched = rec.scheduler
                    if sched is None or worker not in set(
                            sched.placements.values()):
                        continue
                    remaining = True
                    try:
                        sched.recover_worker(
                            worker,
                            max_groups=rec.config
                            .max_concurrent_recoveries)
                    except Exception as e:
                        self._teardown(
                            rec, "FAILED",
                            error=f"recovery from {worker} failed: {e}")
                    progressed = True
                if not remaining:
                    break
        return progressed

    def _reap_finished(self) -> bool:
        progressed = False
        for rec in self._running():
            sched = rec.scheduler
            if sched is None or not sched.placements:
                continue
            done = True
            for group, worker in sched.placements.items():
                st = self.jm.task_state(worker, group, rec.job_id)
                if not st or st.get("state") != "FINISHED":
                    done = False
                    break
            if done:
                self._teardown(rec, "FINISHED")
                progressed = True
        return progressed

    def _reap_cancelling(self) -> bool:
        with self._lock:
            cancelling = [rec for rec in self._jobs.values()
                          if rec.state == "CANCELLING"
                          and rec.scheduler is not None]
        for rec in cancelling:
            self._teardown(rec, "CANCELLED")
        return bool(cancelling)

    def _admit_from_queue(self) -> bool:
        with self._lock:
            admitted = self.admission.admit_queued(
                self._free_slots_locked())
            for job_id in admitted:
                self._jobs[job_id].state = "ADMITTED"
        return bool(admitted)

    def step(self) -> bool:
        """One main-loop round: tear down cancellations, deploy
        admitted jobs, pull every running job's mirrors, recover from
        dead workers (round-robin, capped), reap completions, and drain
        the admission queue into freed slots. Returns whether anything
        changed."""
        progressed = self._reap_cancelling()
        progressed |= self._deploy_ready()
        for rec in self._running():
            if rec.scheduler is not None:
                rec.scheduler.sync()
        progressed |= self._detect_failures()
        progressed |= self._reap_finished()
        progressed |= self._admit_from_queue()
        return progressed

    def run(self, max_seconds: float = 600.0,
            poll_s: float = 0.2) -> None:
        deadline = time.monotonic() + max_seconds
        while time.monotonic() < deadline:
            if not self.step():
                time.sleep(poll_s)
            with self._lock:
                active = any(rec.state in _ACTIVE_STATES or
                             rec.state == "QUEUED"
                             for rec in self._jobs.values())
            if not active and self.server is None:
                return          # embedded mode: nothing left to drive

    # --- observability -------------------------------------------------------

    def metrics_extra(self) -> Dict[str, object]:
        """``MetricsEndpoint(extra=...)`` supplier: the cluster rollup
        (including ``cluster.job.<jid>.*``) plus per-tenant admission
        gauges and dispatcher totals."""
        out: Dict[str, object] = dict(self.jm.cluster_metrics())
        with self._lock:
            counts: Dict[str, Dict[str, int]] = {}
            for rec in self._jobs.values():
                c = counts.setdefault(rec.tenant,
                                      {"running": 0, "queued": 0})
                if rec.state in _ACTIVE_STATES:
                    c["running"] += 1
                elif rec.state == "QUEUED":
                    c["queued"] += 1
            tenants = sorted(set(counts) | set(self.admission.quotas))
            for tenant in tenants:
                out[f"tenant.{tenant}.slots-held"] = \
                    self.admission.held(tenant)
                quota = self.admission.quota(tenant)
                if quota is not None:
                    out[f"tenant.{tenant}.quota"] = quota
                c = counts.get(tenant, {"running": 0, "queued": 0})
                out[f"tenant.{tenant}.jobs-running"] = c["running"]
                out[f"tenant.{tenant}.jobs-queued"] = c["queued"]
            out["dispatcher.queue-depth"] = \
                len(self.admission.queued())
            out["dispatcher.jobs-total"] = len(self._jobs)
        return out

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
        with self._lock:
            recs = list(self._jobs.values())
        for rec in recs:
            if rec.scheduler is not None:
                rec.scheduler.close()
            if rec.tracer is not None:
                rec.tracer.close()
        if self._owns_jm:
            self.jm.close()
