"""Checkpoint coordination: epoch fences, snapshots, truncation, standby push.

Capability parity with the reference's checkpoint layer
(flink-runtime .../checkpoint/CheckpointCoordinator.java — trigger :450, ack
tracking in PendingCheckpoint.java, completion-driven log truncation §3.3,
standby state dispatch :1226-1262, rpcIgnoreUnacknowledgedPendingCheckpoints
:989, recovery backoff of the checkpoint interval :1318-1319) — TPU-native:

- A checkpoint IS an epoch fence: the coordinator triggers at superstep
  boundaries, so there are no in-band barriers to align — the lockstep
  superstep is the aligned barrier (Chandy-Lamport alignment degenerates to
  a step boundary; reference BarrierBuffer.java:54 has no analog to build).
- The snapshot is the executor's **whole functional carry** (operator state,
  edge buffers, cursors, causal logs, replicas, in-flight rings). Because
  the carry is an immutable pytree, "async snapshot" is free: the epoch loop
  keeps stepping on new carries while a writer thread serializes the fenced
  one (the reference needs copy-on-write backend machinery for this;
  functional state gives it by construction).
- Completion truncates causal + in-flight logs back to the fence and pushes
  the completed state to registered standbys (reference
  dispatchLatestCheckpointedStateToStandbyTasks).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import get_tracer


@dataclasses.dataclass
class CompletedCheckpoint:
    """A durable epoch-boundary snapshot."""

    checkpoint_id: int          # == the epoch it fences (epoch e ends here)
    carry: Any                  # host-resident JobCarry pytree
    wall_time: float
    size_bytes: int = 0


class CheckpointStorage:
    """Storage SPI (reference CheckpointStorage / state backends §1 L10)."""

    #: True = the coordinator materializes the carry to host numpy before
    #: write (durable storage). False = the storage accepts device
    #: references: jax arrays are immutable, so holding references IS a
    #: consistent snapshot with zero d2h cost — the right semantics for
    #: the in-process MiniCluster analog, where the epoch fence would
    #: otherwise pay a synchronous multi-hundred-ms tunnel transfer.
    wants_host = True

    def write(self, ckpt: CompletedCheckpoint) -> None:
        raise NotImplementedError

    def read(self, checkpoint_id: int) -> CompletedCheckpoint:
        raise NotImplementedError

    def delete(self, checkpoint_id: int) -> None:
        raise NotImplementedError

    def list_ids(self) -> List[int]:
        raise NotImplementedError

    def _path(self, checkpoint_id: int) -> str:
        raise NotImplementedError

    def mark_complete(self, checkpoint_id: int) -> None:
        """Durable completion marker: snapshots are WRITTEN at trigger,
        but only fully-acked checkpoints are restore points — a standby
        host must be able to tell them apart from the storage alone
        (reference: the coordinator's completed-checkpoint store).
        Shared file-marker implementation for ``_path``-based storages;
        delete() implementations must remove the marker with the
        snapshot."""
        with open(self._path(checkpoint_id) + ".done", "wb"):
            pass

    def completed_ids(self) -> List[int]:
        return sorted(c for c in self.list_ids()
                      if os.path.exists(self._path(c) + ".done"))

    # --- epoch audit ledger (obs/audit.py) -----------------------------------
    # Default: in-memory. Ledger entries are tiny (per-epoch digest
    # summaries) and, unlike snapshots, are NEVER deleted by retention —
    # a later recovery must be able to validate any epoch at/after the
    # restore point, and cross-run diffing wants the whole history.
    # Completion-driven compaction (below) collapses re-sealed
    # duplicates so a long run's ledger stays one line per epoch.

    def write_ledger(self, entry: dict) -> None:
        if not hasattr(self, "_ledger"):
            self._ledger: List[dict] = []
        self._ledger.append(dict(entry))

    def flush_ledger(self) -> None:
        """Make every appended ledger entry durable NOW. Group-commit
        storages (FileCheckpointStorage) defer fsync across a few
        appends; the coordinator calls this at checkpoint completion so
        a completed fence never outruns its sealed entries. In-memory
        default: nothing to do."""

    def read_ledger(self) -> List[dict]:
        return [dict(e) for e in getattr(self, "_ledger", [])]

    def compact_ledger(self, below_epoch: int) -> int:
        """Collapse entries for epochs strictly below ``below_epoch``
        (the latest completed fence) to one per epoch, last-wins — a
        rebuilt runner re-seals replayed epochs, so a long run with
        failures accumulates duplicates the readers resolve last-wins
        anyway. Returns the number of entries dropped."""
        led = getattr(self, "_ledger", None)
        if not led:
            return 0
        compacted = compact_ledger_entries(led, below_epoch)
        dropped = len(led) - len(compacted)
        if dropped:
            self._ledger = compacted
        return dropped


class InMemoryCheckpointStorage(CheckpointStorage):
    wants_host = False

    def __init__(self):
        self._store: Dict[int, CompletedCheckpoint] = {}
        self._complete: set = set()

    def write(self, ckpt: CompletedCheckpoint) -> None:
        self._store[ckpt.checkpoint_id] = ckpt

    def read(self, checkpoint_id: int) -> CompletedCheckpoint:
        return self._store[checkpoint_id]

    def delete(self, checkpoint_id: int) -> None:
        self._store.pop(checkpoint_id, None)
        self._complete.discard(checkpoint_id)

    def list_ids(self) -> List[int]:
        return sorted(self._store)

    def mark_complete(self, checkpoint_id: int) -> None:
        self._complete.add(checkpoint_id)

    def completed_ids(self) -> List[int]:
        return sorted(self._complete & set(self._store))


class FileCheckpointStorage(CheckpointStorage):
    """One file per checkpoint (pickle of the numpy-ified carry). The DFS
    analog; deletion reclaims space like subsumed-checkpoint disposal."""

    #: group-commit width: fsync the ledger every K appends (and at
    #: every checkpoint completion / explicit flush). The widened crash
    #: window is at most K-1 sealed-but-unsynced lines plus one torn
    #: line — all at the tail, which the tolerant reader already drops.
    ledger_group_commit = 8

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._ledger_w = None         # lazy shared durable appender

    def _path(self, cid: int) -> str:
        return os.path.join(self.root, f"chk_{cid}.pkl")

    def write(self, ckpt: CompletedCheckpoint) -> None:
        tmp = self._path(ckpt.checkpoint_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(ckpt, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, self._path(ckpt.checkpoint_id))

    def read(self, checkpoint_id: int) -> CompletedCheckpoint:
        with open(self._path(checkpoint_id), "rb") as f:
            return pickle.load(f)

    def delete(self, checkpoint_id: int) -> None:
        for p in (self._path(checkpoint_id),
                  self._path(checkpoint_id) + ".done"):
            try:
                os.remove(p)
            except OSError:
                pass

    def list_ids(self) -> List[int]:
        out = []
        for fn in os.listdir(self.root):
            if fn.startswith("chk_") and fn.endswith(".pkl"):
                out.append(int(fn[4:-4]))
        return sorted(out)

    def ledger_path(self) -> str:
        return os.path.join(self.root, "ledger.jsonl")

    def write_ledger(self, entry: dict) -> None:
        """Append one JSON line per sealed epoch, group-committed
        through the shared durable appender (utils/jsonl): every line
        is flushed to the OS immediately (a clean process exit loses
        nothing), but the fsync is batched every
        ``ledger_group_commit`` appends — per-entry fsync was the
        dominant fence-tail cost. Completion calls :meth:`flush_ledger`
        so a durable checkpoint never outruns its sealed entries; a
        SIGKILL inside the batch window loses at most the unsynced tail
        lines, which the tolerant reader already handles."""
        if self._ledger_w is None:
            from clonos_tpu.utils.jsonl import JsonlAppender
            self._ledger_w = JsonlAppender(
                self.ledger_path(), sort_keys=True,
                fsync_every=self.ledger_group_commit)
        self._ledger_w.append(entry)

    @property
    def _ledger_unsynced(self) -> int:
        """Sealed-but-unsynced tail lines in the group-commit window
        (0 with no appender open) — the crash-exposure gauge the
        torn-tail tests pin."""
        return self._ledger_w.unsynced if self._ledger_w is not None \
            else 0

    def flush_ledger(self) -> None:
        if self._ledger_w is not None:
            self._ledger_w.sync()

    def _close_ledger(self) -> None:
        if self._ledger_w is not None:
            self._ledger_w.close()
            self._ledger_w = None

    def read_ledger(self) -> List[dict]:
        return read_ledger_file(self.ledger_path())

    def compact_ledger(self, below_epoch: int) -> int:
        """Atomic last-wins rewrite of ledger.jsonl entries below the
        fence (utils/jsonl atomic_rewrite_jsonl: a crash mid-compaction
        leaves the old file or the new one, never a mix). Torn final
        lines are dropped by the tolerant read, which is also a
        compaction."""
        from clonos_tpu.utils.jsonl import atomic_rewrite_jsonl
        path = self.ledger_path()
        self._close_ledger()     # os.replace swaps the inode under us
        entries = read_ledger_file(path)
        if not entries:
            return 0
        compacted = compact_ledger_entries(entries, below_epoch)
        dropped = len(entries) - len(compacted)
        if dropped == 0:
            return 0
        atomic_rewrite_jsonl(path, compacted, sort_keys=True)
        return dropped


def compact_ledger_entries(entries: List[dict],
                           below_epoch: int) -> List[dict]:
    """Pure compaction: entries for epochs < ``below_epoch`` collapse
    to one per epoch (last wins, the readers' resolution rule),
    emitted in epoch order; everything at/above the fence — including
    entries without a parseable epoch — keeps its append order after
    them (later re-seals of live epochs must stay last)."""
    last: Dict[int, dict] = {}
    tail: List[dict] = []
    for e in entries:
        try:
            ep: Optional[int] = int(e["epoch"])
        except (KeyError, TypeError, ValueError):
            ep = None
        if ep is not None and ep < below_epoch:
            last[ep] = e
        else:
            tail.append(e)
    return [last[ep] for ep in sorted(last)] + tail


def read_ledger_file(path: str) -> List[dict]:
    """Read a ledger.jsonl, tolerating a torn final line (SIGKILL mid
    append); a decode failure on any earlier line still raises. Shared
    by FileCheckpointStorage and ``clonos_tpu audit``."""
    from clonos_tpu.utils.jsonl import read_jsonl
    return read_jsonl(path)


def carry_to_host(carry) -> Any:
    """Materialize a device carry as a numpy pytree (the d2h snapshot)."""
    return jax.tree_util.tree_map(np.asarray, jax.device_get(carry))


def carry_nbytes(host_carry) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(host_carry)
               if hasattr(x, "nbytes"))


def snapshot_subtask_slice(snapshot, vertex_id: int, subtask: int) -> Any:
    """The one-subtask slice of a LeanSnapshot's vertex state — what a
    rehydrating standby actually restores under mesh sharding (the failed
    chip's row of the [P, ...] pytree), while healthy shards keep their
    live buffers. Returns a pytree of [1, ...] leaves."""
    return jax.tree_util.tree_map(
        lambda x: x[subtask][None] if getattr(x, "ndim", 0) > 0 else x,
        snapshot.op_states[vertex_id])


def snapshot_subtask_nbytes(snapshot, vertex_id: int, subtask: int) -> int:
    """Bytes of :func:`snapshot_subtask_slice` WITHOUT materializing it:
    one leading-axis row of every vertex-state leaf. The per-shard
    restore cost a RecoveryReport compares against
    :func:`carry_nbytes` of the full snapshot."""
    total = 0
    for x in jax.tree_util.tree_leaves(snapshot.op_states[vertex_id]):
        if not hasattr(x, "nbytes"):
            continue
        n0 = x.shape[0] if getattr(x, "ndim", 0) > 0 else 1
        total += int(x.nbytes) // max(1, n0)
    return total


class CheckpointCoordinator:
    """Host control plane for checkpoints.

    ``subtasks`` is the set of flat subtask ids expected to ack. In the
    single-program executor all healthy subtasks ack at the fence in one
    call; the per-subtask ledger exists so the failure path can leave a
    pending checkpoint un-acked and trigger the ignore/abort logic exactly
    like the reference (CheckpointCoordinator.java:989).
    """

    def __init__(self, storage: CheckpointStorage,
                 num_subtasks: int,
                 max_retained: int = 2,
                 base_interval_steps: int = 16,
                 backoff_multiplier: float = 2.0,
                 max_backoff_steps: int = 256):
        self.storage = storage
        self.num_subtasks = num_subtasks
        self.max_retained = max_retained
        self.base_interval_steps = base_interval_steps
        self.backoff_multiplier = backoff_multiplier
        self.max_backoff_steps = max_backoff_steps
        self._interval_steps = base_interval_steps
        self._pending: Dict[int, Set[int]] = {}       # cid -> missing acks
        self._ignored: Set[int] = set()
        self._completed_ids: List[int] = []
        self._listeners: List[Callable[[CompletedCheckpoint], None]] = []
        self._complete_listeners: List[Callable[[int], None]] = []
        self._writer_lock = threading.Lock()
        #: guards _pending/_ignored/_completed_ids/_trigger_wall/
        #: completion_latency_s — all touched from the caller thread,
        #: the fence worker, AND the async writer threads. Never held
        #: while calling listeners or storage (those take _writer_lock
        #: or arbitrary user code).
        self._state_lock = threading.Lock()
        self._async_threads: List[threading.Thread] = []
        #: transition observers: ``fn(kind, **fields)`` on every
        #: protocol-visible transition (trigger/ack/complete/ignore/
        #: discard). The verify conformance layer replays model traces
        #: against these; keep callbacks cheap — completion fires them
        #: on the async writer thread too.
        self.transition_observers: List[Callable[..., None]] = []
        self._trigger_wall: Dict[int, float] = {}     # cid -> trigger time
        #: cid -> trigger→complete latency (read by the runner's
        #: ``checkpoint.trigger-to-complete-ms`` histogram hook)
        self.completion_latency_s: Dict[int, float] = {}

    def _observe(self, kind: str, **fields) -> None:
        for fn in self.transition_observers:
            fn(kind, **fields)

    # --- listener registration ----------------------------------------------

    def subscribe_completed_state(
            self, fn: Callable[[CompletedCheckpoint], None]) -> None:
        """Standby state dispatch (reference :1226): ``fn`` receives every
        newly completed checkpoint."""
        self._listeners.append(fn)

    def subscribe_completion(self, fn: Callable[[int], None]) -> None:
        """Log-truncation hook: ``fn(checkpoint_id)`` after durability."""
        self._complete_listeners.append(fn)

    # --- trigger / ack / complete -------------------------------------------

    def trigger(self, checkpoint_id: int, carry,
                async_write: bool = True, owned: bool = False) -> None:
        """Fence checkpoint ``checkpoint_id`` over the given carry. The
        carry must be the state exactly at the epoch boundary.

        ``owned=True`` promises the caller passed buffers nothing else
        will mutate or donate (e.g. executor.lean_snapshot's deep copy);
        otherwise device-kept storage defensively copies — the executor
        donates its live carry into later programs, which would delete
        referenced buffers out from under the checkpoint."""
        with self._state_lock:
            if checkpoint_id in self._ignored:
                return
            self._pending[checkpoint_id] = set(
                range(self.num_subtasks))
            # clonos: allow(wallclock): trigger->complete latency metric
            self._trigger_wall[checkpoint_id] = time.time()
        self._observe("trigger", cid=checkpoint_id)
        get_tracer().event("checkpoint.trigger", cid=checkpoint_id,
                           subtasks=self.num_subtasks)
        snap_start = time.monotonic()
        if not self.storage.wants_host and not owned:
            # The defensive copy must happen BEFORE returning to the
            # caller: with async_write the executor's next block would
            # donate (delete) the referenced buffers while the writer
            # thread still points at them.
            carry = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x).copy(), carry)

        def _write():
            # clonos: allow(join-discipline): `storage` is assigned once
            # at construction and never rebound; every mutation OF the
            # storage object holds _writer_lock, and this bare deref
            # only reads the immutable wants_host capability flag.
            host = (carry_to_host(carry) if self.storage.wants_host
                    else carry)
            ckpt = CompletedCheckpoint(
                checkpoint_id=checkpoint_id, carry=host,
                wall_time=snap_start, size_bytes=carry_nbytes(host))
            with self._writer_lock:
                self.storage.write(ckpt)
            self._on_written(checkpoint_id)

        if async_write:
            t = threading.Thread(target=_write, daemon=True)
            self._async_threads.append(t)
            t.start()
        else:
            _write()

    def _on_written(self, checkpoint_id: int) -> None:
        # Written but completion still waits for acks.
        self._maybe_complete(checkpoint_id)

    def ack(self, checkpoint_id: int, subtask: int) -> None:
        with self._state_lock:
            missing = self._pending.get(checkpoint_id)
            if missing is None:
                return
            missing.discard(subtask)
        self._observe("ack", cid=checkpoint_id, subtask=subtask)
        self._maybe_complete(checkpoint_id)

    def ack_all(self, checkpoint_id: int,
                except_subtasks: Tuple[int, ...] = ()) -> None:
        with self._state_lock:
            missing = self._pending.get(checkpoint_id)
            if missing is None:
                return
            acked = missing - set(except_subtasks)
            missing.intersection_update(except_subtasks)
        for subtask in sorted(acked):
            self._observe("ack", cid=checkpoint_id,
                          subtask=subtask)
        self._maybe_complete(checkpoint_id)

    def discard_pending_through(self, checkpoint_id: int) -> List[int]:
        """Abandon every pending checkpoint at or below
        ``checkpoint_id``: they can never complete (their fence has been
        superseded by a newer completed one, so completing them late
        would regress every completion listener — standby refresh, ring
        truncation). The soak driver's pre-kill barrier: a fence that
        leaves ZERO pending checkpoints means a kill in the next epoch
        recovers without ignoring anything, so no IGNORE_CHECKPOINT
        determinants land in healthy logs and the digest chain stays
        byte-comparable with a fault-free control run. Returns the
        abandoned ids."""
        # The state lock closes the window the old key-snapshot comment
        # hedged around: with the pipelined fence the worker thread may
        # trigger() a NEWER checkpoint concurrently — always above
        # ``checkpoint_id``, so the result is unaffected.
        with self._state_lock:
            cids = sorted(c for c in list(self._pending)
                          if c <= checkpoint_id)
            for cid in cids:
                self._ignored.add(cid)
                del self._pending[cid]
        for cid in cids:
            self._observe("discard", cid=cid)
        return cids

    def _maybe_complete(self, checkpoint_id: int) -> None:
        with self._state_lock:
            if self._pending.get(checkpoint_id):
                return
        try:
            with self._writer_lock:
                ckpt = self.storage.read(checkpoint_id)
        except (KeyError, FileNotFoundError):
            return  # write not durable yet; _on_written will retry
        # The atomic check-and-remove elects exactly one completer:
        # _maybe_complete runs on the caller thread, the fence worker,
        # AND the async writer thread, and a double pop here would fire
        # every completion listener twice.
        with self._state_lock:
            if checkpoint_id not in self._pending:
                return
            del self._pending[checkpoint_id]
            self._completed_ids.append(checkpoint_id)
            trig = self._trigger_wall.pop(checkpoint_id, None)
        self._observe("complete", cid=checkpoint_id)
        # mark_complete rewrites storage metadata; every other
        # storage mutation (write/delete/compact_ledger) holds
        # _writer_lock. The ledger group commit settles first: a
        # durable completion marker must never outrun the sealed
        # entries it certifies.
        with self._writer_lock:
            self.storage.flush_ledger()
            try:
                self.storage.mark_complete(checkpoint_id)
            except NotImplementedError:      # custom storages
                pass
        tr = get_tracer()
        if trig is not None:
            # clonos: allow(wallclock): completion latency metric
            lat = time.time() - trig
            with self._state_lock:
                self.completion_latency_s[checkpoint_id] = lat
                while len(self.completion_latency_s) > 64:
                    del self.completion_latency_s[
                        min(self.completion_latency_s)]
            tr.complete("checkpoint", lat, cid=checkpoint_id,
                        size_bytes=ckpt.size_bytes)
        # clonos: allow(join-discipline): completion listeners are
        # registered during wiring, before the fence/writer threads
        # start (pre-start publication across functions, which the race
        # pass only models within the spawning function); the list is
        # append-only and never mutated after start.
        for fn in self._complete_listeners:
            fn(checkpoint_id)
        tr.event("checkpoint.truncate", cid=checkpoint_id)
        # clonos: allow(join-discipline): truncation listeners are
        # registered during wiring, before any worker thread exists;
        # append-only, never mutated after start.
        for fn in self._listeners:
            fn(ckpt)
        self._retain()
        # Completion == truncation time: collapse re-sealed ledger
        # duplicates below this fence so the ledger stays one line
        # per epoch for the life of the job.
        with self._writer_lock:
            self.storage.compact_ledger(checkpoint_id)

    def _retain(self) -> None:
        with self._state_lock:
            old = []
            while len(self._completed_ids) > self.max_retained:
                old.append(self._completed_ids.pop(0))
        for cid in old:
            with self._writer_lock:
                self.storage.delete(cid)

    def drain(self) -> None:
        for t in self._async_threads:
            t.join()
        self._async_threads.clear()

    # --- epoch audit ledger --------------------------------------------------

    def record_ledger(self, entry: dict) -> None:
        """Persist one sealed epoch digest next to the checkpoints (the
        JobMaster-side epoch ledger; obs/audit.py). Runs at trigger time
        — a checkpoint that later completes certifies the epoch the
        entry describes, and entries survive snapshot retention."""
        with self._writer_lock:
            self.storage.write_ledger(entry)

    def read_ledger(self) -> List[dict]:
        """All persisted ledger entries in append order. Duplicate
        epochs (a rebuilt runner re-sealing after replay) resolve
        last-wins at the consumer."""
        with self._writer_lock:
            return self.storage.read_ledger()

    # --- failure-path hooks --------------------------------------------------

    def mark_ignored(self, checkpoint_ids) -> None:
        """Adopt replayed IGNORE_CHECKPOINT determinants (standby
        bootstrap): these ids must never trigger or complete here."""
        with self._state_lock:
            self._ignored.update(checkpoint_ids)

    def ignore_unacked_for(self, failed_subtasks: Set[int]) -> List[int]:
        """A task died: any pending checkpoint still missing one of its acks
        can never complete — mark ignored so healthy tasks skip it
        (reference rpcIgnoreUnacknowledgedPendingCheckpointsFor :989).
        Returns the ignored checkpoint ids (to be broadcast as
        IGNORE_CHECKPOINT determinants)."""
        with self._state_lock:
            dead = [cid for cid, missing in self._pending.items()
                    if missing & failed_subtasks]
            for cid in dead:
                self._ignored.add(cid)
                del self._pending[cid]
        for cid in dead:
            self._observe("ignore", cid=cid)
        return sorted(dead)

    def backoff(self) -> int:
        """Stretch the checkpoint interval during recovery (reference
        restartBackoffCheckpointScheduler :1318). Returns the new interval
        in supersteps."""
        self._interval_steps = min(
            int(self._interval_steps * self.backoff_multiplier),
            self.max_backoff_steps)
        return self._interval_steps

    def reset_interval(self) -> int:
        self._interval_steps = self.base_interval_steps
        return self._interval_steps

    @property
    def interval_steps(self) -> int:
        return self._interval_steps

    @property
    def latest_completed_id(self) -> Optional[int]:
        with self._state_lock:
            return (self._completed_ids[-1]
                    if self._completed_ids else None)

    def latest_completed(self) -> Optional[CompletedCheckpoint]:
        with self._state_lock:
            cid = (self._completed_ids[-1]
                   if self._completed_ids else None)
        if cid is None:
            return None
        with self._writer_lock:
            return self.storage.read(cid)
