"""Cluster runner: failure detection, standby management, causal recovery.

This is the control-plane layer tying the executor, checkpoint coordinator,
replication plan, and recovery FSM together — capability parity with the
reference's JobMaster-side machinery:

- ``HeartbeatMonitor``   <-  runtime/heartbeat (JobMaster.java:258-266)
- ``StandbyPool``        <-  ExecutionVertex.addStandbyExecution /
                             CheckpointCoordinator state dispatch (:1226)
- ``ClusterRunner``      <-  RunStandbyTaskStrategy.onTaskFailure
                             (failover/RunStandbyTaskStrategy.java:85):
                             remove failed, ignore unacked checkpoints,
                             back off the checkpoint interval, run the
                             standby through the recovery FSM (§3.4)

Failure model (TPU deployment semantics): the unit of loss is a subtask's
device-resident state — its operator-state slice, its thread causal log
row, the replica rows it holds for others, AND its shard of its vertex's
in-flight output ring (the producer's subpartition log dies with the
producer, exactly the reference's PipelinedSubpartition ownership).
Recovery rebuilds the lost ring shard from the replayed operator's
re-emitted batches — reconstruction, not just verification (reference
buildAndLogBuffer, PipelinedSubpartition.java:536-599).

"Local recovery instead of global rollback" (README.md:13-20): healthy
subtasks are never rolled back — the failed subtask alone is rebuilt from
the last checkpoint plus determinant replay, then patched into the live
carry. The proof obligation (and the test): the patched carry is
bit-identical to a never-failed run on the canonical (logically-live)
state — executor.canonical_carry.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time as _time
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import recovery as rec
from clonos_tpu.causal import replication as rep
from clonos_tpu.graph.job_graph import JobGraph, PartitionType
from clonos_tpu.inflight import log as ifl
from clonos_tpu.parallel import routing
from clonos_tpu.runtime import checkpoint as cp
from clonos_tpu.obs import get_tracer
from clonos_tpu.storage import SegmentCorruptError, StorageError
from clonos_tpu.runtime.executor import (DETS_PER_STEP, JobCarry,
                                         LeanSnapshot, LocalExecutor,
                                         LogicalTimeSource)


class HeartbeatMonitor:
    """Deadline-based liveness tracking (reference runtime/heartbeat)."""

    def __init__(self, subtasks: Sequence[int], timeout_s: float = 5.0,
                 clock=_time.monotonic):
        self._clock = clock
        self.timeout_s = timeout_s
        self._last: Dict[int, float] = {s: clock() for s in subtasks}
        self._dead: Set[int] = set()
        #: injected per-subtask heartbeat delay (seconds): a gray-failed
        #: worker's beats ARRIVE this much late — the worker is alive and
        #: making (slow) progress, so the monitor must classify it as
        #: degraded, not dead. Written by the chaos injector
        #: (soak/driver.py); empty in production.
        self.lag: Dict[int, float] = {}

    def beat(self, subtask: int) -> None:
        if subtask not in self._dead:
            self._last[subtask] = (self._clock()
                                   - self.lag.get(subtask, 0.0))

    def beat_all_except(self, dead: Set[int]) -> None:
        now = self._clock()
        for s in self._last:
            if s not in dead and s not in self._dead:
                self._last[s] = now - self.lag.get(s, 0.0)

    def mark_dead(self, subtask: int) -> None:
        self._dead.add(subtask)

    def expired(self) -> List[int]:
        now = self._clock()
        out = [s for s, t in self._last.items()
               if s not in self._dead and now - t > self.timeout_s]
        return sorted(out)

    def degraded(self, grace_s: float = 0.0) -> List[int]:
        """Subtasks whose beats arrive late but inside the death
        timeout: gray failures. Lateness is measured against the
        FRESHEST live beat, not wall time — between beat rounds every
        worker's last beat ages identically, and only a worker lagging
        its peers by more than ``grace_s`` is actually degraded.
        Disjoint from :meth:`expired` by construction — a worker is
        degraded OR dead, never both."""
        alive = {s: t for s, t in self._last.items()
                 if s not in self._dead}
        if not alive:
            return []
        freshest = max(alive.values())
        now = self._clock()
        out = [s for s, t in alive.items()
               if freshest - t > grace_s and now - t <= self.timeout_s]
        return sorted(out)

    def ages_ms(self) -> Dict[int, float]:
        """Per-subtask beat age behind the FRESHEST live beat, in ms —
        the peer-relative evidence the gray-failure detector scores
        (obs/detect.py). 0.0 for the freshest worker; empty when no one
        is alive."""
        alive = {s: t for s, t in self._last.items()
                 if s not in self._dead}
        if not alive:
            return {}
        freshest = max(alive.values())
        return {s: (freshest - t) * 1e3 for s, t in alive.items()}

    def revive(self, subtask: int) -> None:
        self._dead.discard(subtask)
        self.lag.pop(subtask, None)
        self._last[subtask] = self._clock()


class StandbyPool:
    """Holds the state standbys restore from: the latest completed
    checkpoint, refreshed on every completion (the reference re-dispatches
    state to STANDBY executions on each checkpoint, Execution.java:373)."""

    def __init__(self, num_standby_per_vertex: int = 1):
        self.num_standby_per_vertex = num_standby_per_vertex
        self.latest: Optional[cp.CompletedCheckpoint] = None
        self.dispatch_count = 0

    def on_completed_checkpoint(self, ckpt: cp.CompletedCheckpoint) -> None:
        # Monotonic: async writes can complete out of order, and a
        # stale completion must never regress the restore point behind
        # state (ring truncation) that has already moved past it.
        if self.latest is None \
                or ckpt.checkpoint_id >= self.latest.checkpoint_id:
            self.latest = ckpt
        self.dispatch_count += 1

    def has_state(self) -> bool:
        return self.latest is not None


class LatencyMarkers:
    """Latency markers, TPU-first (reference RecordWriter.randomEmit
    routing markers through RandomService so replay reproduces them,
    RecordWriter.java:131-137 + LatencyMarker):

    Marker STEPS are chosen by the per-step causal RNG draw
    (``rng % every == 0``). Those draws are recorded determinants, so a
    recovered task re-derives the SAME marker schedule — replay-stable
    by construction. A record emitted at source step ``s`` reaches the
    sink at step ``s + depth`` (the depth-1 superstep pipeline), so the
    marker's latency is the causal-time delta between those two steps'
    inputs — pipeline transit time as experienced by the data, reacting
    to stalls exactly like the reference's markers. Feeds the
    ``latency-ms`` registry histogram."""

    def __init__(self, runner: "ClusterRunner", every: int):
        self.runner = runner
        self.every = every
        job = runner.job
        # Pipeline depth: longest source->sink path in edges.
        depth = {v.vertex_id: 0 for v in job.vertices}
        for vid in job.topo_order():
            for e in job.in_edges(vid):
                depth[vid] = max(depth[vid],
                                 depth[job.edges[e].src] + 1)
        self.depth = max(depth.values()) if depth else 0
        self.hist = runner.metrics.group(
            f"job.{job.name}").histogram("latency-ms")
        self._seen = 0
        #: recent ``(source step, latency)`` pairs behind the histogram —
        #: the raw series coordinated-omission correction needs (the
        #: histogram forgets WHEN a sample happened, so queueing delay
        #: can't be re-attributed from it). Bounded: keeps the newest
        #: ``max_samples``.
        self.samples: List[Tuple[int, float]] = []
        self.max_samples = 8192

    @staticmethod
    def schedule(rngs, every: int):
        """Marker steps for a given rng-draw stream (pure — recovery
        tests re-derive it from recovered determinant rows)."""
        return [i for i, r in enumerate(rngs) if r % every == 0]

    def observe(self) -> None:
        hist = self.runner.executor.step_input_history
        upto = len(hist) - self.depth
        for s in range(self._seen, max(upto, 0)):
            t, r = hist[s]
            if r % self.every == 0:
                lat = hist[s + self.depth][0] - t
                self.hist.update(lat)
                self.samples.append((s, float(lat)))
        if len(self.samples) > self.max_samples:
            del self.samples[:len(self.samples) - self.max_samples]
        self._seen = max(self._seen, upto, 0)


@dataclasses.dataclass
class RecoveryReport:
    """What one failure's recovery did (metrics + test surface)."""

    failed_subtasks: Tuple[int, ...]
    from_epoch: int
    steps_replayed: int
    determinants_replayed: int
    records_replayed: int
    ignored_checkpoints: Tuple[int, ...]
    recovery_ms: float
    managers: Tuple[rec.RecoveryManager, ...]
    #: wall-clock per recovery phase (fetch_determinants / inputs / replay /
    #: patch / replica_rebuild) — the cold-recovery cost breakdown.
    phase_ms: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: True for failover rehearsals (failover_drill): excluded from the
    #: recovery metrics and the reports ledger.
    drill: bool = False
    #: bytes the shard-local restore actually moved: the failed subtasks'
    #: checkpoint slices + fetched determinant rows + replayed input
    #: windows. The paper's local-recovery claim in one number —
    #: ``restore_bytes < checkpoint_bytes`` says healthy shards kept
    #: their live buffers instead of rolling back.
    restore_bytes: int = 0
    #: bytes of the FULL checkpointed carry a global rollback would have
    #: re-loaded (the denominator for restore_bytes).
    checkpoint_bytes: int = 0


class OverflowError_(RuntimeError):
    """An un-checkpointed log/ring overflow was detected — the state is no
    longer recoverable and the control plane must not keep running."""


class ClusterRunner:
    """Single-process cluster (MiniCluster analog) with failure injection.

    Drives epochs; at every epoch fence triggers a checkpoint, collects
    acks from healthy subtasks, and on completion truncates logs and
    refreshes standbys."""

    def __init__(self, job: JobGraph, steps_per_epoch: int = 8,
                 num_standby: int = 1, heartbeat_timeout_s: float = 5.0,
                 checkpoint_dir: Optional[str] = None,
                 incremental_checkpoints: bool = False,
                 incremental_base_every: int = 8,
                 prewarm: bool = False,
                 recovery_block_steps: Optional[int] = None,
                 latency_marker_every: Optional[int] = None,
                 audit: Optional[bool] = None,
                 audit_on_divergence: Optional[str] = None,
                 lineage=None,
                 compile_cache_dir: Optional[str] = None,
                 overlap_recovery: bool = True,
                 overlap_epoch: bool = False,
                 **executor_kw):
        self.job = job
        #: persistent XLA compile cache, namespaced by mesh+spec
        #: fingerprints (utils/compile_cache.py): the standby's
        #: AOT-lowered first-step executable (and every program compiled
        #: during construction/prewarm) survives a process restart, so a
        #: rebooted standby's finalize.first-step-recompile is a cache
        #: hit. Enabled BEFORE the executor builds — construction
        #: compiles the expensive block/staged programs a restart most
        #: wants to hit; only the mesh is known here, so those land in
        #: the mesh-keyed namespace and the cache is re-pointed at the
        #: refined mesh+spec namespace once the carry exists. Both
        #: steps are deterministic from ctor inputs, so a restarted
        #: process replays the same namespace sequence and hits both.
        self._compile_cache_dir: Optional[str] = None
        if compile_cache_dir:
            from clonos_tpu.utils.compile_cache import enable_compile_cache
            self._compile_cache_dir = enable_compile_cache(
                compile_cache_dir, mesh=executor_kw.get("mesh"))
        self.executor = LocalExecutor(job, steps_per_epoch=steps_per_epoch,
                                      **executor_kw)
        #: overlapped finalize pipeline default for recover() — the
        #: sequential escape hatch (False) is the bit-identity control
        #: bench/soak diff the overlapped path against.
        self.overlap_recovery = overlap_recovery
        #: pipelined fence default for run_epoch(): True hands the
        #: fence tail (health drain, audit seal, ledger append,
        #: checkpoint write) to a worker thread that overlaps the next
        #: epoch's compute, joining before the next fence — at most one
        #: tail in flight. Defaults to False (today's strict order):
        #: overlap defers checkpoint completion/truncation and ledger
        #: visibility by one fence, which callers must opt into. The
        #: sequential control run never writes the fence.overlap-saved
        #: key — its absence marks the control.
        self.overlap_epoch = overlap_epoch
        #: in-flight fence tail (pipelined fence): None, or a dict with
        #: the worker thread + its captured handles/results. Joined at
        #: the next fence, before any failure injection, and before
        #: recover() — never survives past one epoch.
        self._fence_tail: Optional[dict] = None
        #: fence attribution of the last joined/sequential fence:
        #: fence.* sub-spans (true walls), "fence-tail" (critical-path
        #: wall the epoch actually waited), and — overlapped only —
        #: "fence.overlap-saved", preserving
        #: sum(fence.*) - overlap-saved == fence-tail.
        self.last_fence_phases: Dict[str, float] = {}
        #: cumulative fence.overlap-saved milliseconds (bench reads it)
        self.fence_overlap_saved_total_ms = 0.0
        self._fence_headroom_checked = False
        if compile_cache_dir:
            mesh0 = self.executor.compiled.mesh
            if mesh0 is not None:
                self._compile_cache_dir = enable_compile_cache(
                    compile_cache_dir, mesh=mesh0,
                    specs=self.executor.compiled.carry_partition_spec(
                        self.executor.carry))
        if incremental_checkpoints:
            if checkpoint_dir is None:
                raise ValueError(
                    "incremental_checkpoints requires checkpoint_dir")
            from clonos_tpu.runtime.incremental import (
                IncrementalCheckpointStorage)
            storage: cp.CheckpointStorage = IncrementalCheckpointStorage(
                checkpoint_dir, base_every=incremental_base_every)
        elif checkpoint_dir:
            storage = cp.FileCheckpointStorage(checkpoint_dir)
        else:
            storage = cp.InMemoryCheckpointStorage()
        self.coordinator = cp.CheckpointCoordinator(
            storage, num_subtasks=job.total_subtasks(),
            base_interval_steps=steps_per_epoch)
        self.standbys = StandbyPool(num_standby)
        self.coordinator.subscribe_completed_state(
            self.standbys.on_completed_checkpoint)
        self.coordinator.subscribe_completion(
            self.executor.notify_checkpoint_complete)
        # Durable-connector contract: a completed checkpoint commits the
        # feed offsets it captured (FlinkKafkaConsumerBase
        # .notifyCheckpointComplete), letting bounded-retention readers
        # release history below them — recovery only ever re-reads from
        # the latest completed checkpoint's offsets.
        self.coordinator.subscribe_completed_state(self._commit_feed_offsets)
        self.heartbeats = HeartbeatMonitor(
            range(job.total_subtasks()), timeout_s=heartbeat_timeout_s)
        self.failed: Set[int] = set()
        # Fence hooks run at every epoch fence BEFORE checkpoint
        # completion truncates the logs and rings — the window where an
        # edge export (runtime/scheduler.py) must snapshot the producer
        # rings' fresh steps or lose them to the truncation.
        self.fence_hooks: List = []
        #: read-replica delta feeds (runtime/serve.py): ``fn(epoch,
        #: window)`` fires when an epoch seals, with the SAME extracted
        #: causal-surface window the audit digests — standbys tail it to
        #: keep their restored checkpoint fence-fresh. Runs on the fence
        #: worker when the fence is pipelined: subscribers must be
        #: host-only and thread-safe, like the auditor.
        self.serve_feeds: List = []
        #: the last epoch whose fence tail SEALED (digest when audit is
        #: on, fence persistence either way) — the freshness stamp every
        #: queryable-state snapshot carries. -1 until the first seal:
        #: endpoints reject reads rather than serve an unstamped view.
        self.last_sealed_epoch = -1
        self.global_step = 0
        self._fence_step: Dict[int, int] = {}   # epoch -> global step at start
        self._fence_step[0] = 0
        self.plan = self.executor.compiled.plan
        self.reports: List[RecoveryReport] = []
        # Observability (reference MetricRegistryImpl + Clonos determinant
        # watchdog; see utils/metrics.py).
        from clonos_tpu.utils import metrics as met
        self.metrics = met.MetricRegistry()
        g = self.metrics.group(f"job.{job.name}")
        self._m_steps = g.counter("supersteps")
        self._m_records = g.meter("records-per-sec")
        self._m_epochs = g.counter("epochs")
        self._m_ckpt_bytes = g.gauge(
            "checkpoint.latest-bytes",
            lambda: (self.standbys.latest.size_bytes
                     if self.standbys.latest else 0))
        self._m_recovery_ms = g.histogram("recovery.duration-ms")
        self._m_recovered_records = g.counter("recovery.records-replayed")
        self._m_epoch_steps_ms = g.histogram("epoch.steps-ms")
        self._m_epoch_fence_ms = g.histogram("epoch.fence-ms")
        self._m_ckpt_latency_ms = g.histogram(
            "checkpoint.trigger-to-complete-ms")
        self.coordinator.subscribe_completion(
            lambda cid: self._m_ckpt_latency_ms.update(
                self.coordinator.completion_latency_s.get(cid, 0.0) * 1e3))
        self._mgroup = g
        # Exactly-once audit plane (obs/audit.py): ``audit=None``
        # inherits the process-global stance (set by config/CLI or
        # adopted from the JobMaster's DEPLOY via transport.adopt_audit);
        # the default is the zero-overhead NullAuditor — no digest reads,
        # no ledger writes, no wire fields.
        from clonos_tpu.obs import audit as _audit_mod
        #: partition shape stamped into every sealed digest so ledger
        #: diffs across a live re-cut know which epochs need the
        #: layout-invariant comparison (obs/audit.diff_ledgers_cross).
        self._audit_layout = tuple(
            (v.vertex_id, v.parallelism) for v in job.vertices)
        if audit is None:
            audit = _audit_mod.get_auditor().enabled
        if audit:
            self.auditor: _audit_mod.NullAuditor = _audit_mod.Auditor(
                on_divergence=(audit_on_divergence
                               or _audit_mod.get_auditor().on_divergence))
        else:
            self.auditor = _audit_mod.NullAuditor()
        self._m_audit_sealed = g.counter("audit.epochs-sealed")
        self._m_audit_matches = g.counter("audit.epochs-validated")
        self._m_audit_div = g.counter("audit.divergences")
        # Overhead attribution (obs/profile.py): the runner inherits the
        # process-global profiler (set by config/CLI). Binding routes
        # the overhead.<section>-ms histograms and overhead.ft-fraction
        # gauge into this registry so they ride the heartbeat piggyback;
        # the default NullProfiler binds to nothing and fences nothing.
        from clonos_tpu.obs import profile as _prof_mod
        self.profiler = _prof_mod.get_profiler()
        if self.profiler.enabled:
            self.profiler.bind(g)
        g.gauge("audit.enabled", lambda: int(self.auditor.enabled))
        g.gauge("audit.last-sealed-epoch", lambda: self.auditor.last_epoch)
        # Incident forensics plane (obs/incident.py): when the process
        # has a live IncidentManager its capture counters ride the same
        # heartbeat piggyback; the NullIncidentManager default registers
        # nothing — zero wire fields.
        from clonos_tpu.obs import incident as _inc_mod
        _inc = _inc_mod.get_incidents()
        if _inc.enabled:
            _inc.register_gauges(self.metrics)
        # Record-level lineage plane (obs/lineage.py): per-runner
        # binding like the auditor — ``lineage=None`` inherits the
        # process-global plane (set by CLI/soak arming or adopted from
        # a DEPLOY header via transport.adopt_lineage); callers that
        # run twins in one process (the soak control) pass distinct
        # planes so each runner's observations land in its own file.
        # The NullLineage default scans nothing and registers nothing.
        from clonos_tpu.obs import lineage as _lin_mod
        self.lineage = (lineage if lineage is not None
                        else _lin_mod.get_lineage())
        if self.lineage.enabled:
            self.lineage.register_gauges(self.metrics)
        #: vertex id -> parallelism, for the lineage plane's
        #: key-group/subtask attribution at the seal scan.
        self._lineage_topology = {v.vertex_id: v.parallelism
                                  for v in job.vertices}
        # Live exactly-once health: how hard the in-flight rings are
        # holding un-truncated history (backpressure proxy — rings only
        # grow when checkpoints lag), and how many supersteps a failure
        # RIGHT NOW would replay (the recovery-cost exposure).
        g.gauge("backpressure.inflight-occupancy", self._inflight_occupancy)
        g.gauge("recovery.replay-lag-steps", self._replay_lag_steps)
        # Tiered-storage residency + movement (storage/tiered.py), summed
        # over every spill owner (in-flight rings + determinant tier).
        # Zero when spilling is disabled; `clonos_tpu top` renders the
        # spill.* suffix as its SPILL column.
        if self.executor.spill_logs is not None:
            g.gauge("spill.host-epochs",
                    lambda: self.executor.spill_occupancy()["host_epochs"])
            g.gauge("spill.disk-epochs",
                    lambda: self.executor.spill_occupancy()["disk_epochs"])
            g.gauge("spill.host-bytes",
                    lambda: self.executor.spill_occupancy()["host_bytes"])
            g.gauge("spill.disk-bytes",
                    lambda: self.executor.spill_occupancy()["disk_bytes"])
            g.gauge("spill.bytes-spilled",
                    lambda: self.executor.spill_stats()
                    .get("bytes_spilled", 0))
            g.gauge("spill.bytes-refilled",
                    lambda: self.executor.spill_stats()
                    .get("bytes_refilled", 0))
        self.watchdog = met.LogOccupancyWatchdog(self.executor, g)
        # Per-mesh-shard health (mesh-sharded fused blocks): one gauge
        # triple per task-axis shard, fed from the executor's packed
        # [n, 3] per-shard read, cached per epoch so a metrics scrape
        # costs at most one device round-trip per fence.
        self._shard_health: Optional[np.ndarray] = None
        self._shard_health_epoch = -1
        mesh_ = self.executor.compiled.mesh
        if mesh_ is not None:
            n_sh = mesh_.shape[self.executor.compiled.task_axis]
            g.gauge("mesh.shards", lambda n_sh=n_sh: n_sh)
            for i in range(n_sh):
                g.gauge(f"shard.{i}.records",
                        lambda i=i: int(self.per_shard_health()[i, 0]))
                g.gauge(f"shard.{i}.log-rows",
                        lambda i=i: int(self.per_shard_health()[i, 1]))
                g.gauge(f"shard.{i}.ring-slots",
                        lambda i=i: int(self.per_shard_health()[i, 2]))
        #: compiled recovery programs, keyed by (kind, params) — populated
        #: lazily and by prewarm_recovery() (warm standby: no XLA compile
        #: in the failure path).
        self._rjit: Dict[Any, Any] = {}
        import threading as _threading
        self._rjit_lock = _threading.Lock()
        #: routed edge-window cache, scoped to one vertex's failed
        #: subtasks within one recover() call (the exchange output is
        #: consumer-independent; see _replay_inputs). Populated only
        #: when the current vertex has >= 2 failed subtasks — the
        #: all-lane blocks are P-times a lane's size, so caching buys
        #: nothing for the common single-subtask failure.
        self._route_cache: Dict[Any, Any] = {}
        self._route_cache_enabled = False
        #: observability/test hook: cache hits in the last recover()
        self._route_cache_hits = 0
        self._last_records_total = 0
        #: checkpoint id -> np [L] log heads at that fence, harvested from
        #: the per-epoch health read (recovery's patch phase reads them
        #: here instead of round-tripping the device on the failure path).
        #: Inserted by the fence tail (worker thread when pipelined),
        #: pruned by the completion hook (async writer thread), read by
        #: recovery — every touch holds _ck_heads_lock.
        self._ck_log_heads: Dict[int, np.ndarray] = {}
        self._ck_heads_lock = threading.Lock()
        #: host mirror of the in-flight ring offsets: heads advance one
        #: per superstep (== global_step), tails move only at checkpoint
        #: completion (ifl.truncate to the completed epoch's end fence).
        #: Lets recover() make its routing coverage decisions without a
        #: device read; the device bounds are still compared against the
        #: mirror in recovery's final packed read (fail-loud, not trust).
        self._ring_tail_mirror = 0
        self._ring_mirror_valid = True
        self.coordinator.subscribe_completion(self._update_ring_mirror)
        # Host epoch control plane (reference EpochTrackerImpl): the
        # listener bus + record counting driven from the fused per-epoch
        # health read; checkpoint completions fan out through it.
        from clonos_tpu.causal.epoch import EpochTracker
        self.epoch_tracker = EpochTracker()
        self.coordinator.subscribe_completion(
            self.epoch_tracker.notify_checkpoint_complete)
        #: flat subtask -> ProcessingTimeService; timers fire at block
        #: boundaries on causal time and log TIMER_TRIGGER determinants
        #: (reference SystemProcessingTimeService.java:50,79-114).
        self.timer_services: Dict[int, Any] = {}
        self.executor.block_listeners.append(self._advance_timers)
        #: latency markers through the causal RNG path (RecordWriter
        #: .randomEmit analog); None = off.
        self.latency = (LatencyMarkers(self, latency_marker_every)
                        if latency_marker_every else None)
        #: source subtasks (no input edges): their logs record
        #: SOURCE_CHECKPOINT determinants at every trigger
        #: (StreamTask.performCheckpoint:833-840).
        self._source_flats = [
            self.job.subtask_base(v.vertex_id) + s
            for v in self.job.vertices if not self.job.in_edges(v.vertex_id)
            for s in range(v.parallelism)]
        # Transactional sinks: 2PC egress (runtime/txn.py). Emissions tap
        # the per-block outputs; transactions seal at fences and commit on
        # checkpoint completion.
        from clonos_tpu.api.operators import TransactionalSinkOperator
        from clonos_tpu.runtime.txn import TransactionLog
        self.txn_logs: Dict[int, TransactionLog] = {
            v.vertex_id: TransactionLog(v.vertex_id)
            for v in job.vertices
            if isinstance(v.operator, TransactionalSinkOperator)}
        if self.txn_logs:
            self.executor.on_block_outputs = self._absorb_sink_outputs
            self.coordinator.subscribe_completion(
                lambda e: [tl.commit(e) for tl in self.txn_logs.values()])
        #: recovery chunk size: larger than the live block trades a bigger
        #: prewarm compile for fewer per-chunk dispatches on the failure
        #: path (each costs ~2-10ms of tunnel latency).
        self._recovery_ch = min(
            recovery_block_steps or self.executor.block_steps,
            self.executor.compiled.inflight_ring_steps,
            self.executor.compiled.log_capacity // DETS_PER_STEP)
        if prewarm:
            self.prewarm_recovery()

    def _commit_feed_offsets(self, ckpt) -> None:
        for vid, reader in self.executor.feed_readers.items():
            off = np.asarray(ckpt.carry.op_states[vid]["offset"])
            reader.notify_checkpoint_complete([int(x) for x in off])

    def _absorb_sink_outputs(self, outs, epoch: int) -> None:
        for vid, tl in self.txn_logs.items():
            b = outs.sinks.get(vid)
            if b is not None:
                tl.absorb(epoch, np.asarray(b.keys), np.asarray(b.values),
                          np.asarray(b.timestamps), np.asarray(b.valid))

    # --- live health gauges (heartbeat-piggybacked; runtime/remote.py) -------

    def _inflight_occupancy(self) -> float:
        """Fraction of the in-flight rings' capacity holding
        un-truncated steps — the host-mirror backpressure proxy (rings
        retain exactly the steps a failure would need to re-route; a
        rising value means checkpoint completion is lagging the fences)."""
        if not self.executor.carry.out_rings:
            return 0.0
        cap = self.executor.compiled.inflight_ring_steps
        held = self.global_step - self._ring_tail_mirror
        return min(max(held, 0) / cap, 1.0)

    def _replay_lag_steps(self) -> int:
        """Supersteps a failure occurring NOW would replay (distance from
        the latest completed checkpoint's fence) — the live recovery-cost
        exposure."""
        ck = self.standbys.latest
        if ck is None:
            return self.global_step
        f = self._fence_step.get(ck.checkpoint_id + 1)
        return self.global_step - f if f is not None else 0

    def per_shard_health(self) -> Optional[np.ndarray]:
        """int32 [n_shards, 3] (records, live log rows, live ring slots)
        per task-axis mesh shard, cached per epoch (the shard.<i>.*
        gauges all read through this, so a full metrics scrape costs one
        device round-trip, not 3n). None without a mesh."""
        if self.executor.compiled.mesh is None:
            return None
        if self._shard_health_epoch != self.executor.epoch_id \
                or self._shard_health is None:
            self._shard_health = self.executor.per_shard_health()
            self._shard_health_epoch = self.executor.epoch_id
        return self._shard_health

    # --- compiled recovery programs ------------------------------------------

    def _jitted(self, key, make, donate=()):
        f = self._rjit.get(key)
        if f is None:
            with self._rjit_lock:
                f = self._rjit.get(key)
                if f is None:
                    f = jax.jit(make(), donate_argnums=donate)
                    self._rjit[key] = f
        return f

    def _chunk(self) -> int:
        return self._recovery_ch

    def _fetch_fn(self):
        cap = self.executor.compiled.log_capacity
        return self._jitted(("fetch",), lambda: (
            lambda replicas, r, from_epoch: clog.get_determinants(
                jax.tree_util.tree_map(lambda x: x[r], replicas),
                from_epoch, cap)))

    def _fetch_meta_fn(self, h: int):
        """(count, start) of every holder's response in one device call —
        holders are bit-identical replicas by construction, so the host
        merge reduces to verifying the counts agree and pulling ONE body."""
        cap = self.executor.compiled.log_capacity

        def make():
            def f(replicas, rs, from_epoch):
                def one(r):
                    rep_one = jax.tree_util.tree_map(
                        lambda x: x[r], replicas)
                    off = clog.epoch_start_offset(rep_one, from_epoch)
                    cnt = jnp.clip(rep_one.head - off, 0, cap)
                    return jnp.stack([cnt, off])
                return jax.vmap(one)(rs)          # [h, 2]
            return f
        return self._jitted(("fetch_meta", h), make)

    def _pad_steps(self) -> int:
        ch = self._recovery_ch
        return -(-self.executor.compiled.inflight_ring_steps // ch) * ch

    def _device_parse_fn(self):
        """Parse a consistent replica's determinant stream ON DEVICE:
        locate the per-step sync anchors, extract the time/rng/expected
        lanes (padded to the replayer's fixed stream length), and report
        whether the stream is 'clean' (pure sync rows, exact layout).
        Only ~16 bytes of metadata cross the host link — the multi-MB
        log body stays on device (it IS the replica; the restore path
        copies it device-side too). Reference contrast: the JVM replayer
        walks the byte log on-heap (LogReplayerImpl.java:36-157)."""
        cap = self.executor.compiled.log_capacity
        maxn = self._pad_steps()
        k = DETS_PER_STEP

        def make():
            def f(replicas, r, from_epoch):
                buf, count, start = clog.get_determinants(
                    jax.tree_util.tree_map(lambda x: x[r], replicas),
                    from_epoch, cap)
                tags = buf[:, det.LANE_TAG]
                rowmask = jnp.arange(cap) < count
                cond = (rowmask & (tags == det.TIMESTAMP)
                        & (buf[:, det.LANE_RC] == 0))
                n_anchors = cond.sum().astype(jnp.int32)
                ids = jnp.nonzero(cond, size=maxn,
                                  fill_value=cap - k)[0].astype(jnp.int32)
                amask = jnp.arange(maxn) < n_anchors
                layout = jnp.all(
                    ~amask
                    | ((tags[ids + 1] == det.RNG)
                       & (tags[ids + 2] == det.ORDER)
                       & (tags[ids + 3] == det.BUFFER_BUILT)))
                clean = layout & (count == n_anchors * k)
                last = jnp.maximum(n_anchors - 1, 0)
                t_raw = buf[ids, det.LANE_P + 1]
                r_raw = buf[ids + 1, det.LANE_P]
                times = jnp.where(amask, t_raw, t_raw[last])
                rngs = jnp.where(amask, r_raw, r_raw[last])
                expected = jnp.where(amask, buf[ids + 3, det.LANE_P], 0)
                small = jnp.stack([count, start, n_anchors,
                                   clean.astype(jnp.int32)])
                return times, rngs, expected, small
            return f
        return self._jitted(("device_parse",), make)

    def _ring_bounds_dev(self):
        """Device [R, 2] (tail, head) of every in-flight ring — dispatch
        only; recover() folds the transfer into its packed reads."""
        if not self.executor.carry.out_rings:
            return None
        fn = self._jitted(("ring_bounds",), lambda: (
            lambda rings: jnp.stack(
                [jnp.stack([el.tail, el.head]) for el in rings])))
        return fn(self.executor.carry.out_rings)

    def _ring_bounds(self) -> Dict[int, Tuple[int, int]]:
        """(tail, head) of every in-flight ring in ONE device read — ring
        offsets don't move during recovery (write-backs change contents
        only), so recover() reads them once instead of twice per chunk."""
        dev = self._ring_bounds_dev()
        if dev is None:
            return {}
        arr = np.asarray(dev)
        return {ri: (int(arr[ri, 0]), int(arr[ri, 1]))
                for ri in range(arr.shape[0])}

    def _update_ring_mirror(self, completed_epoch: int) -> None:
        """Checkpoint-completion hook: advance the host ring-tail mirror
        to the completed epoch's end fence (matches ifl.truncate). A
        completion whose fence the runner never saw (executor driven
        directly, e.g. by a test) invalidates the mirror — recover()
        then reads the device bounds instead of trusting stale ones."""
        f = self._fence_step.get(completed_epoch + 1)
        if f is None:
            self._ring_mirror_valid = False
        else:
            self._ring_tail_mirror = max(self._ring_tail_mirror, f)
        # Recovery only ever restores from the latest completed
        # checkpoint — drop older fence-head entries (bounded ledger).
        # Under the lock: this hook runs on the async writer thread
        # while the fence tail inserts the next epoch's heads.
        with self._ck_heads_lock:
            self._ck_log_heads = {
                k: v for k, v in self._ck_log_heads.items()
                if k >= completed_epoch}

    def _ring_chunk_fn(self, ri: int, m: int):
        return self._jitted(("ring_chunk", ri, m), lambda: (
            lambda el, start: ifl.slice_steps(el, start, m)))

    def _route_chunk_fn(self, eidx: int, m: int, all_lanes: bool = False):
        """Read + route one [m]-step window of edge ``eidx``'s producer
        ring — one program with the loop state (window start, leading
        skip, rebalance offset, remaining needed steps) carried ON
        DEVICE: per-chunk host scalars would cost a ~8ms device_put each
        over the tunnel.

        Two variants, both prewarmed:
        - fused (default): the consumer's lane is selected INSIDE the
          program. Crucial for the single-failure case: XLA then scatters
          only that lane's rows (a general scatter runs ~row-at-a-time
          on TPU, so materializing all P lanes costs ~P times more).
        - ``all_lanes``: the full [m, P, cap] routed block — the routing
          is consumer-independent, so a connected multi-subtask failure
          routes each window ONCE and lane-selects per consumer (the
          reference re-serves the in-flight log per requesting channel;
          here the exchange is the expensive part and it is shared).

        Replay windows are UNIFORM: every window is m steps, the first
        starting one slot before the fence (that dead slot is masked by
        ``lead`` and later replaced by the checkpointed edge buffer) —
        one compiled program serves every chunk instead of a first-chunk
        (m-1) shape variant doubling the prewarm. ``need_left`` masks
        steps past the replay range invalid (the replay-padding
        contract); ``lead`` masks the leading dead slot of window 0."""
        def make():
            if all_lanes:
                body = self._route_body(eidx, m)

                def f(el, start, rr0, need_left, lead):
                    raw = ifl.slice_steps_at(el, start, m)
                    routed, cnt = body(raw, rr0, need_left, lead)
                    return (routed, start + m, rr0 + cnt, need_left - m,
                            jnp.zeros_like(lead))
            else:
                body = self._route_body_lane(eidx, m)

                def f(el, start, sub, rr0, need_left, lead):
                    raw = ifl.slice_steps_at(el, start, m)
                    lane, cnt = body(raw, sub, rr0, need_left, lead)
                    return (lane, start + m, rr0 + cnt, need_left - m,
                            jnp.zeros_like(lead))
            return f
        return self._jitted(("route_chunk", eidx, m, all_lanes), make)

    def _lane_select_fn(self, eidx: int, m: int):
        """Select one consumer lane of a routed [m, P, cap] block."""
        return self._jitted(("lane_select", eidx, m), lambda: (
            lambda routed, sub: jax.tree_util.tree_map(
                lambda x: x[:, sub], routed)))

    def _route_body(self, eidx: int, m: int):
        """The shared exchange-replay body: mask the ``lead`` leading
        slots and steps past ``need_left`` invalid, then route to all
        destination lanes."""
        e = self.job.edges[eidx]
        dst_p = self.job.vertices[e.dst].parallelism
        compiled = self.executor.compiled

        def body(raw, rr0, need_left, lead):
            need = jnp.clip(need_left, 0, m)
            idx = jnp.arange(m, dtype=jnp.int32)
            live = (idx >= lead) & (idx < need)
            raw = raw._replace(valid=raw.valid & live[:, None, None])
            if eidx in compiled.static_route:
                r, _ = compiled.static_route[eidx].apply(raw)
            elif e.partition == PartitionType.HASH:
                r, _ = routing.route_hash_block(
                    raw, dst_p, self.job.num_key_groups, e.capacity)
            elif e.partition == PartitionType.FORWARD:
                r, _ = routing.route_forward_block(raw, e.capacity)
            elif e.partition == PartitionType.REBALANCE:
                counts = raw.count().sum(axis=1)
                offs = rr0 + jnp.cumsum(counts) - counts
                r, _ = routing.route_rebalance_block(
                    raw, dst_p, e.capacity, offs)
            else:
                r, _ = routing.route_broadcast_block(raw, dst_p, e.capacity)
            return r, raw.count().sum()
        return body

    def _route_body_lane(self, eidx: int, m: int):
        """Single-consumer-lane exchange replay: compute the routed lane
        ``sub`` DIRECTLY (routing._block_to_target_lane — a [m, n]
        running count instead of the [m, n, T+1] one-hot), bit-identical
        to the full route's lane. Keeps the single-failure replay on the
        counting path at whole-window m where the full exchange falls
        back to the flat sort."""
        e = self.job.edges[eidx]
        dst_p = self.job.vertices[e.dst].parallelism
        compiled = self.executor.compiled

        def body(raw, sub, rr0, need_left, lead):
            need = jnp.clip(need_left, 0, m)
            idx = jnp.arange(m, dtype=jnp.int32)
            live = (idx >= lead) & (idx < need)
            raw = raw._replace(valid=raw.valid & live[:, None, None])
            if eidx in compiled.static_route:
                r, _ = compiled.static_route[eidx].apply(raw)
                lane = jax.tree_util.tree_map(lambda x: x[:, sub], r)
            elif e.partition == PartitionType.HASH:
                lane = routing.route_hash_block_lane(
                    raw, sub, dst_p, self.job.num_key_groups, e.capacity)
            elif e.partition == PartitionType.FORWARD:
                lane = routing.route_forward_block_lane(
                    raw, sub, e.capacity)
            elif e.partition == PartitionType.REBALANCE:
                counts = raw.count().sum(axis=1)
                offs = rr0 + jnp.cumsum(counts) - counts
                lane = routing.route_rebalance_block_lane(
                    raw, sub, dst_p, e.capacity, offs)
            else:
                lane = routing.route_broadcast_block_lane(
                    raw, sub, e.capacity)
            return lane, raw.count().sum()
        return body

    def _route_raw_fn(self, eidx: int, m: int, all_lanes: bool = False):
        """Spill-path twin of :meth:`_route_chunk_fn`: routes a
        host-assembled raw chunk instead of reading the device ring,
        advancing the same device-carried loop state."""
        def make():
            if all_lanes:
                body = self._route_body(eidx, m)

                def f(raw, start, rr0, need_left, lead):
                    routed, cnt = body(raw, rr0, need_left, lead)
                    return (routed, start + m, rr0 + cnt, need_left - m,
                            jnp.zeros_like(lead))
            else:
                body = self._route_body_lane(eidx, m)

                def f(raw, start, sub, rr0, need_left, lead):
                    lane, cnt = body(raw, sub, rr0, need_left, lead)
                    return (lane, start + m, rr0 + cnt, need_left - m,
                            jnp.zeros_like(lead))
            return f
        return self._jitted(("route_raw", eidx, m, all_lanes), make)

    def _replica_copy_fn(self):
        return self._jitted(("replica_copy",), lambda: (
            lambda replicas, logs, ri, oi: jax.tree_util.tree_map(
                lambda s, l: s.at[ri].set(l[oi], mode="drop"),
                replicas, logs)), donate=(0,))

    def _first_chunk_fn(self, eidx: int):
        """Replace the first window's dead leading slot with the
        checkpointed depth-1 edge buffer (replay step 0 consumes it)."""
        return self._jitted(("first_chunk", eidx), lambda: (
            lambda buf_sub, routed: jax.tree_util.tree_map(
                lambda a, b: b.at[0].set(a[0]), buf_sub, routed)))

    # --- timers / epoch services ---------------------------------------------

    def timer_service(self, flat_subtask: int):
        """The per-task processing-time timer service (lazily created);
        registered callbacks fire at block boundaries on causal time and
        their TIMER_TRIGGER determinants replay after a failure."""
        svc = self.timer_services.get(flat_subtask)
        if svc is None:
            from clonos_tpu.runtime.timers import ProcessingTimeService
            svc = ProcessingTimeService(
                append=lambda d, f=flat_subtask:
                    self.executor.append_async_determinant(f, d))
            self.timer_services[flat_subtask] = svc
        return svc

    def _advance_timers(self, now: int, stamp: int) -> None:
        if self.profiler.enabled and self.timer_services:
            with self.profiler.section("timer-advance"):
                for flat, svc in self.timer_services.items():
                    if flat not in self.failed:
                        svc.advance(now, stamp)
            return
        for flat, svc in self.timer_services.items():
            if flat not in self.failed:
                svc.advance(now, stamp)

    @classmethod
    def from_config(cls, job: JobGraph, config=None, **overrides
                    ) -> "ClusterRunner":
        """Build a runner from the typed Configuration surface
        (config/defaults.py — the reference's flink-conf.yaml /
        ExecutionConfig path). Explicit ``overrides`` win."""
        from clonos_tpu.config import defaults as D
        from clonos_tpu.config.options import Configuration
        cfg = config or Configuration()
        job.sharing_depth = cfg.get(D.DETERMINANT_SHARING_DEPTH)
        kw: Dict[str, Any] = dict(
            steps_per_epoch=cfg.get(D.CHECKPOINT_INTERVAL_STEPS),
            num_standby=(cfg.get(D.NUM_STANDBY_TASKS)
                         if cfg.get(D.FAILOVER_STRATEGY) == "standbytask"
                         else 0),
            heartbeat_timeout_s=cfg.get(D.HEARTBEAT_TIMEOUT_MS) / 1e3,
            log_capacity=cfg.get(D.DETERMINANT_LOG_CAPACITY),
            max_epochs=cfg.get(D.DETERMINANT_MAX_EPOCHS),
            inflight_ring_steps=cfg.get(D.INFLIGHT_CAPACITY_BATCHES),
        )
        if cfg.get(D.INFLIGHT_TYPE) == "spillable":
            kw["spool_dir"] = os.path.join(cfg.get(D.CHECKPOINT_DIR),
                                           "spill")
            kw["spill_policy"] = cfg.get(D.INFLIGHT_SPILL_POLICY)
            kw["spill_host_budget_epochs"] = cfg.get(
                D.INFLIGHT_HOST_BUDGET_EPOCHS)
        if cfg.contains(D.CHECKPOINT_DIR):
            kw["checkpoint_dir"] = cfg.get(D.CHECKPOINT_DIR)
        if cfg.get(D.AUDIT_ENABLED):
            kw["audit"] = True
            kw["audit_on_divergence"] = cfg.get(D.AUDIT_ON_DIVERGENCE)
        if cfg.get(D.PROFILE_ENABLED):
            from clonos_tpu.obs import profile as _prof
            if not _prof.get_profiler().enabled:
                _prof.configure_profile()
        kw.update(overrides)
        runner = cls(job, **kw)
        runner.coordinator.backoff_multiplier = cfg.get(
            D.CHECKPOINT_BACKOFF_MULTIPLIER)
        return runner

    @classmethod
    def bootstrap_standby(cls, job: JobGraph, checkpoint_dir: str,
                          mirror_rows: Dict[int, Tuple[np.ndarray, int]],
                          ignored_checkpoints: Sequence[int] = (),
                          feed_readers: Optional[Dict[int, object]] = None,
                          **runner_kw
                          ) -> Tuple["ClusterRunner", RecoveryReport]:
        """Standby-HOST failover: rebuild the ENTIRE job in a fresh
        process after a whole-host loss, from (a) the durable checkpoint
        and (b) a RemoteReplicaMirror's determinant rows — the mirrors
        are the determinant source intra-chip replicas cannot be when
        the chip died with the host (reference: standby TaskManagers +
        DeterminantResponseEvent over the wire;
        RunStandbyTaskStrategy.java:186-227, Task.java:1290).

        Every subtask is recovered through the normal causal protocol in
        topological order — sources replay from their recorded rng/time
        streams, their rebuilt in-flight rings feed downstream routing —
        so the rebuilt cluster's state is bit-identical to the dead
        worker's at its last mirrored fence, verified by the replay's
        output-cut asserts against the mirrored BUFFER_BUILT rows.

        Requirements: ``mirror_rows`` must cover every flat subtask and
        end at an epoch fence (mirrors refresh at fences); rebalance
        edges are not yet reconstructible (their round-robin cursors are
        not in the lean snapshot's fence state).

        ``feed_readers`` maps HostFeedSource vertex ids to rewindable
        readers (api/feeds.py contract); they are registered BEFORE the
        replay so the feed re-read path (`_reread_feed`) can serve the
        recorded offset windows — required when the rebuilt job has
        host-boundary sources (e.g. a scheduler slice whose cut in-edges
        arrive over the wire)."""
        for e in job.edges:
            if e.partition == PartitionType.REBALANCE:
                raise rec.RecoveryError(
                    "bootstrap_standby: rebalance edges not supported "
                    "(post-replay round-robin cursors are not "
                    "reconstructible from the fence snapshot)")
        # Rebuild-stage sub-attribution: the stages around recover() are
        # the standby-host analog of the finalize phase (everything that
        # must happen besides replay before the job resumes). Each stage
        # emits a recovery.finalize.<stage> complete under the adopted
        # recovery trace id and folds into the report's phase_ms.
        tr = get_tracer()
        sub_ms: Dict[str, float] = {}
        t_sub = _time.monotonic()

        def _stage(name: str) -> None:
            nonlocal t_sub
            now = _time.monotonic()
            sub_ms[name] = sub_ms.get(name, 0.0) + (now - t_sub) * 1e3
            tr.complete(f"recovery.{name}", now - t_sub)
            t_sub = now

        runner = cls(job, checkpoint_dir=checkpoint_dir, **runner_kw)
        for vid, reader in (feed_readers or {}).items():
            runner.executor.register_feed(vid, reader)
        storage = runner.coordinator.storage
        ignored = set(ignored_checkpoints)
        # Only fully-ACKED checkpoints are restore points; triggered-but-
        # unacked snapshots also sit in storage (written at the fence).
        ids = [i for i in storage.completed_ids() if i not in ignored]
        if not ids:
            raise rec.RecoveryError(
                "bootstrap_standby: no durable completed non-ignored "
                f"checkpoint in {checkpoint_dir}")
        ckpt = storage.read(max(ids))
        runner.standbys.on_completed_checkpoint(ckpt)
        runner.coordinator.mark_ignored(ignored)
        spe = runner.executor.steps_per_epoch
        from_epoch = ckpt.checkpoint_id + 1
        L = job.total_subtasks()
        missing = [f for f in range(L) if f not in mirror_rows]
        if missing:
            raise rec.RecoveryError(
                f"bootstrap_standby: mirror rows missing for subtasks "
                f"{missing}")

        # The absolute superstep at the fence: the lean snapshot's ring
        # heads ARE step counts (one append per superstep). A job with
        # no rings (single vertex, no edges) carries no such counter,
        # but checkpoint cadence pins it anyway: checkpoint id e seals
        # epochs 0..e, so its fence sits at exactly (e + 1) *
        # steps_per_epoch supersteps — the same invariant `ring_heads[0]`
        # encodes when rings exist (one append per superstep from step
        # 0). Deriving it makes edge-less jobs bootstrappable past epoch
        # 0 instead of refusing (ADVICE round 5: the old silent
        # `global_step = 0` default replayed from the wrong offset).
        if ckpt.carry.ring_heads:
            fence = int(np.asarray(ckpt.carry.ring_heads[0]))
        else:
            fence = (ckpt.checkpoint_id + 1) * spe

        # Steps replayed = sync-anchor count of the mirrored streams
        # (lockstep supersteps: every log advances together, and the
        # mirror snapshot is prefix-consistent across flats).
        anchors_by_flat: Dict[int, np.ndarray] = {
            flat: det.sync_anchors(rows)
            for flat, (rows, _start) in mirror_rows.items()}
        ns = {len(a) for a in anchors_by_flat.values()}
        if len(ns) != 1:
            raise rec.RecoveryError(
                f"bootstrap_standby: mirror streams disagree on the "
                f"replayed step count: {sorted(ns)}")
        n_steps = ns.pop()
        if n_steps % spe != 0:
            raise rec.RecoveryError(
                f"bootstrap_standby: mirrored {n_steps} steps is not a "
                f"whole number of {spe}-step epochs (mirrors refresh at "
                f"fences)")
        k = n_steps // spe

        # Control-plane bookkeeping the dead worker would have had.
        runner.global_step = fence + n_steps
        runner.executor._steps_executed = fence + n_steps
        # Step-input ledger: per-step (time, rng) inputs are global
        # across the lockstep supersteps, so any subtask's recorded
        # stream reproduces them; pre-fence entries are placeholders
        # (nothing replays below a completed fence).
        a0 = anchors_by_flat[0]
        rows0 = np.asarray(mirror_rows[0][0], np.int32)
        hist = [(0, 0)] * fence
        for j in range(n_steps):
            hist.append((int(rows0[a0[j], det.LANE_P + 1]),
                         int(rows0[a0[j] + 1, det.LANE_P])))
        runner.executor.step_input_history = hist
        if runner.latency is not None:
            # Placeholder entries (rng=0) would all read as markers and
            # flood the histogram with zero samples — markers resume at
            # the first post-rebuild step.
            runner.latency._seen = len(hist)
        runner.executor.epoch_id = from_epoch + k
        runner.executor.step_in_epoch = 0
        for j in range(k + 1):
            runner._fence_step[from_epoch + j] = fence + j * spe
        runner._ring_tail_mirror = fence
        with runner._ck_heads_lock:
            runner._ck_log_heads[ckpt.checkpoint_id] = np.asarray(
                ckpt.carry.log_heads).astype(np.int64)
        _stage("finalize.state-rehydrate")

        # Overlapped finalize (the tentpole restructure): the roll-gap /
        # async ledger derivation (listener-reattach) is a pure function
        # of the mirrored streams, and the host-RNG fast-forward +
        # first-step AOT warm (first-step-recompile) touch nothing the
        # device replay mutates — all of it runs on ONE worker thread
        # concurrently with recover()'s replay instead of serially
        # around it. Join points are explicit: the ledgers install at
        # recover()'s pre-patch join (the earliest read site — _patch
        # rebuilds epoch offsets from roll_gap_async), the warm work
        # joins before bootstrap returns (= before the first live
        # step). Ring-reregister CANNOT move: recover() captures the
        # carry and dispatches its ring-bounds read at entry, and the
        # final packed read asserts those device bounds — the offsets
        # must already be in place.
        ov: Dict[str, Any] = {"derive_ms": 0.0, "warm_ms": 0.0,
                              "rg": {}, "ac": {}, "err": None}
        derived = threading.Event()

        def _overlap_work() -> None:
            # Roll-gap / async ledgers, re-derived from the mirrored
            # streams: rows between one epoch's last sync block and the
            # next epoch's first anchor are that next epoch's roll-gap
            # appends (exact when between-epoch appends happen only at
            # rolls — fence SOURCE_CHECKPOINTs, ignore broadcasts; see
            # executor.roll_gap_async).
            t_d = _time.monotonic()
            try:
                rg: Dict[Tuple[int, int], int] = {}
                ac: Dict[Tuple[int, int], int] = {}
                for flat, (rows, _start) in mirror_rows.items():
                    rows = np.asarray(rows, np.int32)
                    a = anchors_by_flat[flat]
                    for j in range(k + 1):
                        if j == 0:
                            gap = int(a[0]) if len(a) else rows.shape[0]
                        else:
                            prev_end = int(a[j * spe - 1]) + DETS_PER_STEP
                            nxt = (int(a[j * spe]) if j < k
                                   else rows.shape[0])
                            gap = nxt - prev_end
                        if gap > 0:
                            rg[(flat, from_epoch + j)] = gap
                    # async totals per epoch (cleanness ledger for
                    # FUTURE failures of the rebuilt cluster).
                    for j in range(k):
                        lo = int(a[j * spe])
                        hi = (int(a[(j + 1) * spe]) if j + 1 < k
                              else rows.shape[0])
                        async_n = (hi - lo) - spe * DETS_PER_STEP
                        lead_gap = rg.get((flat, from_epoch + j), 0)
                        total_async = async_n + (lead_gap if j == 0
                                                 else 0)
                        if total_async > 0:
                            ac[(flat, from_epoch + j)] = total_async
                ov["rg"], ov["ac"] = rg, ac
            except Exception as err:          # re-raised at the join
                ov["err"] = err
            finally:
                ov["derive_ms"] = (_time.monotonic() - t_d) * 1e3
                derived.set()
            if ov["err"] is not None:
                return
            # Off the join path: the host RNG is a seeded per-run
            # stream, one draw per executed superstep; replay reproduces
            # the prefix from RECORDED rng determinants without
            # consuming it, so fast-forward a fresh stream past the
            # prefix (replay never draws, so the thread owns the RNG).
            # Then warm the first-step executable — with a persistent
            # compile cache (compile_cache_dir) this is a cache HIT
            # from the pre-failure prewarm, not a full XLA compile.
            t_w = _time.monotonic()
            try:
                runner.executor.fast_forward_host_rng(fence + n_steps)
                from clonos_tpu.utils.compile_cache import (
                    aot_lower_first_step)
                aot_lower_first_step(runner.executor, runner._mgroup)
            except Exception as err:
                ov["err"] = err
            ov["warm_ms"] = (_time.monotonic() - t_w) * 1e3

        worker = threading.Thread(target=_overlap_work,
                                  name="bootstrap-finalize-overlap")
        worker.start()

        def _join_ledgers() -> None:
            derived.wait()
            if ov["err"] is not None:
                raise ov["err"]
            runner.executor.install_replay_ledgers(ov["rg"], ov["ac"])

        # In-flight ring offsets/epoch index as the dead worker had them:
        # content is rebuilt by the per-vertex ring write-backs during
        # recover(); offsets must already read (tail=fence, head=fence+n)
        # for the topological routing to see its coverage.
        c = runner.executor.carry
        new_rings = []
        for el in c.out_rings:
            starts = np.asarray(el.epoch_starts)
            me = starts.shape[0]
            starts = starts.copy()
            for j in range(k + 1):
                starts[(from_epoch + j) % me] = fence + j * spe
            new_rings.append(el._replace(
                head=jnp.asarray(fence + n_steps, jnp.int32),
                tail=jnp.asarray(fence, jnp.int32),
                epoch_starts=jnp.asarray(starts, jnp.int32),
                latest_epoch=jnp.asarray(from_epoch + k, jnp.int32),
                epoch_base=jnp.asarray(from_epoch, jnp.int32)))
        runner.executor.carry = c._replace(out_rings=tuple(new_rings))
        _stage("finalize.ring-reregister")

        # Everything is failed; recover() rebuilds it all from the
        # checkpoint + mirror rows, in topological order. The ledger
        # derivation rides inside the replay window; recover() joins it
        # at the pre-patch point and bills only the blocked remainder.
        runner.failed = set(range(L))
        for f in range(L):
            runner.heartbeats.mark_dead(f)
        report = runner.recover(host_rows=mirror_rows,
                                pre_patch_join=_join_ledgers)
        t_sub = _time.monotonic()    # recover() attributes its own time

        # The depth-1 edge buffers (the in-flight batch produced at step
        # fence+n-1, consumed by the NEXT live step) are not part of
        # replay's input range — route that one step from the rebuilt
        # rings now.
        if n_steps > 0:
            c = runner.executor.carry
            ch = runner._chunk()
            bufs = list(c.edge_bufs)
            for eidx, e in enumerate(job.edges):
                ri = runner.executor.compiled.ring_index[e.src]
                z = jnp.asarray(0, jnp.int32)
                routed, *_ = runner._route_chunk_fn(
                    eidx, ch, all_lanes=True)(
                    c.out_rings[ri],
                    jnp.asarray(fence + n_steps - 1, jnp.int32),
                    z, jnp.asarray(1, jnp.int32), z)
                bufs[eidx] = jax.tree_util.tree_map(
                    lambda x: x[0], routed)
            runner.executor.carry = c._replace(edge_bufs=tuple(bufs))
        else:
            # Nothing replayed: the completed fence IS the rebuild point,
            # and the lean snapshot's depth-1 edge buffers (produced at
            # step fence-1, consumed by the next live step) are the only
            # copy of that in-flight batch — the rings below the fence
            # were truncated on completion and are not rebuilt.
            c = runner.executor.carry
            bufs = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x).copy(), ckpt.carry.edge_bufs)
            runner.executor.carry = c._replace(edge_bufs=tuple(bufs))
        _stage("finalize.edge-rehydrate")

        # Join the overlap worker (host-RNG fast-forward + first-step
        # AOT warm) — the guarantee the first live step needs: the RNG
        # stream sits exactly past the replayed prefix and the block
        # executable is compiled. Only the blocked remainder extends
        # the critical path; the rest overlapped replay.
        t_j2 = _time.monotonic()
        worker.join()
        if ov["err"] is not None:
            raise ov["err"]
        warm_blocked_ms = (_time.monotonic() - t_j2) * 1e3

        # Fold the rebuild stages into the report: they extend the
        # finalize phase (everything-after-replay). Overlap is
        # attributed, never hidden — each finalize.* sub-span keeps its
        # TRUE wall (the derivation/warm thread time), only the blocked
        # remainders extend the finalize total, and the difference is
        # credited to finalize.overlap-saved, preserving the invariant
        # sum(finalize.* sub-spans) - overlap-saved == finalize.
        for name, ms in sub_ms.items():
            report.phase_ms[name] = report.phase_ms.get(name, 0.0) + ms
            report.phase_ms["finalize"] = (
                report.phase_ms.get("finalize", 0.0) + ms)
            runner._mgroup.histogram(f"recovery.{name}-ms").update(ms)
        reattach_blocked_ms = report.phase_ms.get(
            "finalize.listener-reattach", 0.0)   # recover()'s join wait
        report.phase_ms["finalize.listener-reattach"] = ov["derive_ms"]
        report.phase_ms["finalize.first-step-recompile"] = (
            report.phase_ms.get("finalize.first-step-recompile", 0.0)
            + ov["warm_ms"])
        report.phase_ms["finalize"] = (
            report.phase_ms.get("finalize", 0.0)
            + reattach_blocked_ms + warm_blocked_ms)
        report.phase_ms["finalize.overlap-saved"] = (
            report.phase_ms.get("finalize.overlap-saved", 0.0)
            + max(ov["derive_ms"] - reattach_blocked_ms, 0.0)
            + max(ov["warm_ms"] - warm_blocked_ms, 0.0))
        for name in ("finalize.listener-reattach",
                     "finalize.first-step-recompile",
                     "finalize.overlap-saved"):
            runner._mgroup.histogram(f"recovery.{name}-ms").update(
                report.phase_ms[name])
        return runner, report

    @classmethod
    def restore_rescaled(cls, job_new: JobGraph, job_old: JobGraph,
                         ckpt: cp.CompletedCheckpoint,
                         **runner_kw) -> "ClusterRunner":
        """Restore a completed checkpoint into a job whose keyed vertices
        run at a DIFFERENT parallelism (the planned-rescale restart;
        reference CheckpointCoordinator.restoreSavepoint ->
        StateAssignmentOperation with KeyGroupRangeAssignment). Dense
        keyed state splits/merges by key-group ownership
        (Operator.rescale_keyed_state); checkpointed depth-1 edge
        buffers re-route through the hash exchange at the new
        parallelism. The restored incarnation starts a fresh causal-log
        epoch 0 — a rescale is a planned restart at a completed fence,
        so there is nothing to replay.

        Constraints: topology (vertex count, operator types, edge
        partition kinds) must match; rescaled vertices' input edges must
        be HASH (key ownership defines the split); vertices without a
        keyed rescaling story must keep their parallelism."""
        if len(job_new.vertices) != len(job_old.vertices) or \
                len(job_new.edges) != len(job_old.edges):
            raise rec.RecoveryError(
                "restore_rescaled: topology mismatch between jobs")
        runner = cls(job_new, **runner_kw)
        cpy = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).copy(), t)
        snap = ckpt.carry
        carry = runner.executor.carry
        ops = list(carry.op_states)
        for v_new, v_old in zip(job_new.vertices, job_old.vertices):
            if type(v_new.operator) is not type(v_old.operator):
                raise rec.RecoveryError(
                    f"restore_rescaled: vertex {v_new.vertex_id} operator "
                    f"type changed")
            vid = v_new.vertex_id
            st = cpy(snap.op_states[vid])
            if v_new.parallelism == v_old.parallelism:
                ops[vid] = st
            else:
                for eidx in job_new.in_edges(vid):
                    if job_new.edges[eidx].partition != PartitionType.HASH:
                        raise rec.RecoveryError(
                            f"restore_rescaled: vertex {vid} rescaled but "
                            f"input edge {eidx} is not HASH-partitioned")
                ops[vid] = v_new.operator.rescale_keyed_state(
                    st, v_new.parallelism, job_new.num_key_groups)
        bufs = list(carry.edge_bufs)
        for eidx, (e_new, e_old) in enumerate(zip(job_new.edges,
                                                  job_old.edges)):
            if e_new.partition != e_old.partition:
                raise rec.RecoveryError(
                    f"restore_rescaled: edge {eidx} partition changed")
            old_buf = cpy(snap.edge_bufs[eidx])
            dst_p = job_new.vertices[e_new.dst].parallelism
            if e_new.partition == PartitionType.HASH:
                raw = jax.tree_util.tree_map(lambda x: x[None], old_buf)
                routed, dropped = routing.route_hash_block(
                    raw, dst_p, job_new.num_key_groups, e_new.capacity)
                # Rescaling DOWN concentrates old lanes' records; an
                # overflow here would silently lose in-flight records
                # and break the identical-output contract — fail loud.
                if int(np.asarray(dropped).sum()) > 0:
                    raise rec.RecoveryError(
                        f"restore_rescaled: edge {eidx} buffer overflows "
                        f"capacity {e_new.capacity} at parallelism "
                        f"{dst_p} — widen the edge capacity of the "
                        f"rescaled job")
                bufs[eidx] = jax.tree_util.tree_map(
                    lambda x: x[0], routed)
            else:
                want = bufs[eidx].keys.shape
                if old_buf.keys.shape != want:
                    raise rec.RecoveryError(
                        f"restore_rescaled: edge {eidx} buffer shape "
                        f"{old_buf.keys.shape} != {want} and the edge is "
                        f"not HASH-rescalable")
                bufs[eidx] = old_buf
        runner.executor.carry = carry._replace(
            op_states=tuple(ops), edge_bufs=tuple(bufs))
        return runner

    def rescale_live(self, job_new: JobGraph,
                     observers: Sequence = (),
                     feed_readers: Optional[Dict[int, object]] = None,
                     **runner_kw
                     ) -> Tuple["ClusterRunner", Dict[str, Any]]:
        """Elastic re-cut under live traffic: at THIS runner's completed
        checkpoint fence, stand up a new incarnation of the job at a
        different keyed parallelism and hand off exactly once — no
        record lost, none duplicated. The verified protocol
        (verify/models.RepartitionModel) is fence → drain → migrate →
        redirect, driven through a
        :class:`~clonos_tpu.runtime.scheduler.RescaleCoordinator` whose
        ``transition_observers`` conformance hooks fire at every step.

        fence    — the latest COMPLETED checkpoint is the handoff point
                   (the caller just ran ``run_epoch``, so the fence
                   seals every epoch up to ``epoch_id - 1``; the ledger
                   certifies them).
        drain    — the old lanes' in-flight edge buffers were captured
                   IN that checkpoint; counting them into the migration
                   payload is the drain (nothing is dropped on the
                   floor: route_hash_block re-cuts them below).
        migrate  — keyed state splits/merges by key-group ownership and
                   the drained buffers re-route at the new parallelism
                   (``restore_rescaled``); the old↔new group directory
                   comes from the audit layer
                   (obs/audit.key_group_directory) — the same mapping
                   ``audit A --diff B`` uses, built once and reused.
        redirect — the new incarnation adopts the epoch cursor, ledger
                   and RNG stream mid-run (the ``bootstrap_standby``
                   zero-replay surgery) and the OLD incarnation is
                   fenced off: its subtasks are marked failed so a
                   stale ``run_epoch``/``step`` raises instead of
                   double-applying records.

        Returns ``(new_runner, stats)``; the caller rebinds its handle
        (and re-homes any read tier: ``ServeTier.rehome``). ``stats``
        reports the fence checkpoint, drained record count, moved key
        groups per rescaled vertex, and the observed protocol
        transitions."""
        from clonos_tpu.obs import audit as _audit_mod
        from clonos_tpu.runtime.scheduler import RescaleCoordinator
        if self.failed:
            raise rec.RecoveryError(
                f"rescale_live: failed subtasks {sorted(self.failed)} — "
                f"recover() first; a re-cut needs a healthy fence")
        self.drain_fence()
        if self.executor.step_in_epoch != 0:
            raise rec.RecoveryError(
                f"rescale_live: mid-epoch (step {self.executor.step_in_epoch}"
                f"/{self.executor.steps_per_epoch}) — a re-cut happens at "
                f"an epoch fence; finish the epoch first")
        ids = self.coordinator.storage.completed_ids()
        if not ids:
            raise rec.RecoveryError(
                "rescale_live: no completed checkpoint — the fence the "
                "re-cut hands off at does not exist yet")
        ckpt = self.coordinator.storage.read(max(ids))
        if ckpt.checkpoint_id != self.executor.epoch_id - 1:
            raise rec.RecoveryError(
                f"rescale_live: latest completed checkpoint "
                f"{ckpt.checkpoint_id} is not the current fence "
                f"(epoch {self.executor.epoch_id - 1}) — run the epoch "
                f"to completion (complete_checkpoint=True) first")
        tr = get_tracer()
        job_old = self.job

        # The re-cut's control plane: one group per OLD lane of each
        # rescaled vertex. Guards on the coordinator refuse exactly the
        # orderings the model's seeded bugs inject.
        rescaled = [(v_new, v_old)
                    for v_new, v_old in zip(job_new.vertices,
                                            job_old.vertices)
                    if v_new.parallelism != v_old.parallelism]
        lanes: List[Tuple[int, int]] = []   # (vertex_id, old lane)
        for v_new, v_old in rescaled:
            lanes += [(v_old.vertex_id, s)
                      for s in range(v_old.parallelism)]
        coord = RescaleCoordinator(len(lanes))
        events: List[tuple] = []
        coord.transition_observers.append(
            lambda kind, **f: events.append((kind, tuple(sorted(f.items())))))
        coord.transition_observers.extend(observers)

        # Per-old-lane in-flight counts: the depth-1 edge buffers the
        # fence checkpoint captured (the records "in the pipe" at the
        # handoff point).
        inflight = [0] * len(lanes)
        for g, (vid, lane) in enumerate(lanes):
            for eidx in job_old.in_edges(vid):
                buf = ckpt.carry.edge_bufs[eidx]
                inflight[g] += int(np.asarray(buf.valid)[lane].sum())
            if inflight[g]:
                coord.note_inflight(g, inflight[g])
        coord.fence(ckpt.checkpoint_id)

        # Migration: keyed-state surgery + edge-buffer re-route at the
        # new parallelism, from the SAME fence checkpoint.
        t_mig = _time.monotonic()
        runner = type(self).restore_rescaled(job_new, job_old, ckpt,
                                             **runner_kw)
        for vid, reader in (feed_readers or {}).items():
            runner.executor.register_feed(vid, reader)
        directories = {
            v_old.vertex_id: _audit_mod.key_group_directory(
                v_old.parallelism, v_new.parallelism,
                job_new.num_key_groups)
            for v_new, v_old in rescaled}
        for g, (vid, lane) in enumerate(lanes):
            if inflight[g]:
                coord.drain(g, inflight[g])
            coord.migrate(g)
        migrate_ms = (_time.monotonic() - t_mig) * 1e3

        # Epoch-continuity surgery (bootstrap_standby's zero-replay
        # recipe): the new incarnation resumes at the fence — same
        # epoch cursor, same global step, same host-RNG position — so
        # its next sealed epoch continues the adopted ledger.
        spe = runner.executor.steps_per_epoch
        from_epoch = ckpt.checkpoint_id + 1
        if ckpt.carry.ring_heads:
            fence = int(np.asarray(ckpt.carry.ring_heads[0]))
        else:
            fence = from_epoch * spe
        runner.global_step = fence
        runner.executor._steps_executed = fence
        runner.executor.step_input_history = [(0, 0)] * fence
        if runner.latency is not None:
            runner.latency._seen = fence
        runner.executor.epoch_id = from_epoch
        runner.executor.step_in_epoch = 0
        runner._fence_step[from_epoch] = fence
        runner._ring_tail_mirror = fence
        with runner._ck_heads_lock:
            runner._ck_log_heads[ckpt.checkpoint_id] = np.asarray(
                runner.executor.carry.logs.head).astype(np.int64)
        c = runner.executor.carry
        new_rings = []
        for el in c.out_rings:
            starts = np.asarray(el.epoch_starts).copy()
            starts[from_epoch % starts.shape[0]] = fence
            new_rings.append(el._replace(
                head=jnp.asarray(fence, jnp.int32),
                tail=jnp.asarray(fence, jnp.int32),
                epoch_starts=jnp.asarray(starts, jnp.int32),
                latest_epoch=jnp.asarray(from_epoch, jnp.int32),
                epoch_base=jnp.asarray(from_epoch, jnp.int32)))
        runner.executor.carry = c._replace(out_rings=tuple(new_rings))
        runner.executor.fast_forward_host_rng(fence)
        # The causal-time source is a live host object: the new
        # incarnation keeps ticking the OLD one's stream (a fresh
        # source would replay timestamps from zero and shift every
        # window fire). EXCEPT logical time, which is bound to its
        # executor's step_input_history — the new incarnation's own
        # (history rebuilt to the fence above) already resumes at the
        # right step, while the old one's is frozen at the fence.
        if not isinstance(self.executor.time_source, LogicalTimeSource):
            runner.executor.time_source = self.executor.time_source

        # Ledger adoption: the new incarnation carries the pre-re-cut
        # seals forward, so one continuous audit chain spans the
        # re-cut — post-re-cut epochs diff against pre-re-cut ones via
        # the group directory (diff_ledgers_cross), which is what makes
        # "no record lost or duplicated" checkable after the fact.
        if runner.auditor.enabled and self.auditor.enabled:
            runner.auditor.adopt(self.auditor.ledger())
        runner.last_sealed_epoch = max(runner.last_sealed_epoch,
                                       self.last_sealed_epoch)

        # Durable restore point in the NEW shape: re-fence the handoff
        # checkpoint over the re-cut carry, so a failure in the first
        # post-re-cut epoch recovers at the new parallelism instead of
        # finding an old-shaped snapshot.
        runner.coordinator.trigger(ckpt.checkpoint_id,
                                   runner.executor.lean_snapshot(),
                                   async_write=False, owned=True)
        runner.coordinator.ack_all(ckpt.checkpoint_id)

        # Redirect: every group is migrated (the coordinator verifies),
        # traffic belongs to the new incarnation, and the old one is
        # fenced off — a stale writer raises instead of double-applying.
        coord.redirect()
        self.failed = set(range(job_old.total_subtasks()))
        for f in self.failed:
            self.heartbeats.mark_dead(f)

        stats = {
            "fence_checkpoint": ckpt.checkpoint_id,
            "from_epoch": from_epoch,
            "groups": len(lanes),
            "drained_records": int(sum(inflight)),
            "moved_key_groups": {
                vid: len(_audit_mod.moved_key_groups(d))
                for vid, d in directories.items()},
            "migrate_ms": migrate_ms,
            "transitions": events,
        }
        tr.event("rescale.redirect", **{k: v for k, v in stats.items()
                                        if k != "transitions"})
        return runner, stats

    def attach_file_sink(self, vertex_id: int, root: str, election=None,
                         token: int = 0):
        """Back a transactional sink with durable part files
        (runtime/filesink.py — the StreamingFileSink analog): pendings
        persist at every epoch seal, commits are atomic renames, and
        stale pendings of a dead incarnation are swept now.

        ``election`` (a ``runtime.leader.FileLeaderElection`` or any
        object with ``is_leader()``) fences every mutating sink
        operation on leadership: when two incarnations share ``root``
        (the standby-takeover deployment this sink exists for), a
        fenced-off incarnation attaching here must NOT run the startup
        sweep — it would delete the healthy writer's in-progress
        pendings.

        ``token`` is the writer's fencing token (monotone incarnation
        number — e.g. bump it on each live re-cut); the startup sweep
        only ever deletes parts at or below it, so a stale incarnation
        attaching to a shared root cannot destroy a newer writer's
        in-progress parts even without a leadership handle."""
        from clonos_tpu.runtime.filesink import FileSystemSink
        if vertex_id not in self.txn_logs:
            raise ValueError(
                f"vertex {vertex_id} is not a transactional sink")
        fs = FileSystemSink(root, fencing=election, token=token)
        tl = self.txn_logs[vertex_id]
        tl.pre_committer = fs.write_pending
        tl.committer = fs.commit
        fs.sweep_pending(keep_epochs=tl.pending_epochs())
        return fs

    def state_digest(self) -> str:
        """Canonical digest of the recoverable job state: operator
        states, record counts, log heads and each log's live row window.
        A standby-host rebuild (bootstrap_standby) must reproduce the
        dead worker's digest at its last mirrored fence EXACTLY — the
        cross-process bit-identity check (reference: state handle
        equality on restore)."""
        import hashlib
        h = hashlib.sha1()
        for vid in range(len(self.job.vertices)):
            st = self.executor.vertex_state(vid)
            for k in sorted(st):
                h.update(np.asarray(st[k]).tobytes())
        c = self.executor.carry
        heads = np.asarray(c.logs.head)
        tails = np.asarray(c.logs.tail)
        rows = np.asarray(c.logs.rows)
        cap = rows.shape[1]
        h.update(heads.tobytes())
        for flat in range(rows.shape[0]):
            pos = np.arange(int(tails[flat]), int(heads[flat])) & (cap - 1)
            h.update(rows[flat][pos].tobytes())
        h.update(np.asarray(c.record_counts).tobytes())
        return h.hexdigest()

    # --- steady state --------------------------------------------------------

    def run_epoch(self, complete_checkpoint: bool = True,
                  overlap_fence: Optional[bool] = None) -> None:
        """Run to the next epoch fence and trigger its checkpoint.

        ``complete_checkpoint=False`` leaves the checkpoint pending (no
        acks): logs keep accumulating across epochs — the large-checkpoint-
        interval regime the spillable in-flight log exists for, and the
        setup for multi-epoch recovery gaps.

        ``overlap_fence`` (default: the runner's ``overlap_epoch``)
        selects the pipelined fence: the closed epoch's fence state is
        captured as device-side handles (async health d2h, epoch-window
        copies, lean snapshot) and the tail — health drain, audit seal,
        group-committed ledger append, async checkpoint write, spill
        digests — drains on a single fence-worker thread while the NEXT
        epoch's compute runs; the worker joins at the next fence, so at
        most one tail is ever in flight. Deferred with it, by at most
        one epoch, are the overflow check (re-run from the async health
        read before the ring can wrap twice; one epoch of ring headroom
        is asserted once), checkpoint completion/truncation, and ledger
        visibility — ``drain_fence()`` settles all of it on demand.
        ``overlap_fence=False`` keeps today's strict order and never
        writes the ``fence.overlap-saved`` attribution key — its
        absence marks a sequential control run."""
        if self.failed:
            raise rec.RecoveryError(
                f"cannot run with failed subtasks {sorted(self.failed)}; "
                f"call recover() first")
        overlap = (self.overlap_epoch if overlap_fence is None
                   else overlap_fence)
        if overlap and not self._fence_headroom_checked:
            self._check_fence_headroom()
        # A mode switch settles strictly — and so does spill, whose
        # host store the in-flight worker (attach_spill_digests) and
        # this epoch's spill hook would otherwise race: join BEFORE
        # dispatching this epoch's compute.
        if self._fence_tail is not None and (
                not overlap or self.executor.spill_logs is not None):
            self._join_fence_tail()
        closed = self.executor.epoch_id
        n = self.executor.steps_per_epoch - self.executor.step_in_epoch
        tr = get_tracer()
        prof = self.profiler
        epoch_span = tr.span("epoch", epoch=closed, steps=n)
        epoch_span.__enter__()
        try:
            t0 = _time.monotonic()
            self.executor.run_epoch()
            if not overlap:
                # Enabled profiler: fence the carry so "compute"
                # measures execution, not dispatch (the fused block
                # program = user compute + in-program causal/ring
                # appends). Never on the overlapped path — this block
                # would serialize exactly the window the pipeline
                # hides, so overlapped "compute" is dispatch wall only.
                prof.fence(self.executor.carry)
            steps_s = _time.monotonic() - t0
            self._m_epoch_steps_ms.update(steps_s * 1e3)
            tr.complete("epoch.steps", steps_s, epoch=closed, steps=n)
            prof.observe("compute", steps_s, kind="compute")
            # The PREVIOUS epoch's tail joins here: after this epoch's
            # compute is dispatched (the tail overlapped it), before any
            # of this fence's state is touched. The join re-raises
            # worker errors, runs the deferred overflow check, and
            # acks/truncates its checkpoint on this (the main) thread.
            self._join_fence_tail()
            t_fence = _time.monotonic()
            self.global_step += n
            self._fence_step[self.executor.epoch_id] = self.global_step
            self.heartbeats.beat_all_except(self.failed)
            self._m_steps.inc(n)
            self._m_epochs.inc()
            if self.latency is not None:
                self.latency.observe()
            if overlap:
                self._begin_fence_tail(closed, complete_checkpoint, prof)
            else:
                self._run_fence_tail_inline(
                    closed, complete_checkpoint, t_fence, tr, prof)
            # Close the attribution window: FT seconds / (FT + compute)
            # since the previous fence -> the overhead.ft-fraction
            # gauge (a no-op returning 0.0 on the NullProfiler).
            prof.rollup()
        except BaseException as e:
            epoch_span.__exit__(type(e), e, e.__traceback__)
            raise
        epoch_span.__exit__(None, None, None)

    def _absorb_fence_health(self, closed: int, vec: np.ndarray) -> int:
        """Fold one fence's drained health vector into the host mirrors
        (runs inline on the sequential path, on the fence worker when
        pipelined). Returns the epoch's record delta."""
        nf = 4 + len(self.executor.carry.out_rings)
        total_records = int(vec[nf])
        # The heads at this fence ARE checkpoint ``closed``'s log
        # heads (the SOURCE_CHECKPOINT appends come after and belong
        # to the new epoch) — recovery's patch phase reads them from
        # here instead of paying a device round-trip on the failure
        # path.
        # Bounded even when checkpoints never complete (the completion
        # hook prunes harder). Epochs arrive in monotonic order, so
        # evicting in insertion order is oldest-first and O(1) — a
        # pruned-but-needed entry only costs the patch fallback's one
        # device read.
        with self._ck_heads_lock:
            self._ck_log_heads[closed] = vec[nf + 1:].astype(np.int64)
            while len(self._ck_log_heads) > 128:
                self._ck_log_heads.pop(
                    next(iter(self._ck_log_heads)))
        delta_records = total_records - self._last_records_total
        self._m_records.mark(delta_records)
        self._last_records_total = total_records
        return delta_records

    def _seal_and_trigger(self, closed: int, window_fn, snap_fn,
                          phases: Dict[str, float], prof,
                          async_write: bool) -> None:
        """The fence tail's persistence half, shared verbatim by both
        modes: audit seal over the closed epoch's causal surface,
        ledger append, spill digests, seal fan-out, checkpoint trigger.
        ``window_fn``/``snap_fn`` abstract WHERE the state comes from —
        the live carry (sequential) or captured device handles
        (pipelined) — so the digests are byte-identical either way."""
        # One window extraction feeds BOTH planes: the audit digest and
        # the read-replica delta feeds (runtime/serve.py) read the same
        # causal surface, so a serving-only run (audit off) still pays
        # exactly one extraction and a dual run pays no second one.
        win = (window_fn()
               if self.auditor.enabled or self.serve_feeds
               or self.lineage.enabled else None)
        if self.auditor.enabled:
            from clonos_tpu.obs import audit as _audit_mod
            t = _time.monotonic()
            with prof.section("digest-seal"):
                dg = _audit_mod.digest_epoch_window(
                    closed, win, layout=self._audit_layout)
                self.auditor.seal(dg)
            phases["fence.digest-seal"] = (_time.monotonic() - t) * 1e3
            t = _time.monotonic()
            with prof.section("ledger-write"):
                self.coordinator.record_ledger(dg.to_entry())
            phases["fence.ledger-write"] = (_time.monotonic() - t) * 1e3
            if self.executor.spill_logs is not None:
                # Segment index entries inherit the ledger's channel
                # fingerprints — spill/refill round-trips become
                # audit-verifiable (storage/tiered.py docstring).
                self.executor.attach_spill_digests(closed, dg)
            self.epoch_tracker.notify_epoch_sealed(closed, dg)
            self._m_audit_sealed.inc()
        # The seal stamp advances in both modes — the fence tail IS the
        # seal event queryable-state freshness is measured against.
        # max(): the pipelined fence may run this on the worker while a
        # drain-ordering edge case replays an older epoch's tail.
        self.last_sealed_epoch = max(self.last_sealed_epoch, closed)
        from clonos_tpu.obs import get_timeline
        tl = get_timeline()
        if tl.enabled:
            tl.record("epoch.seal", epoch=int(closed),
                      audited=bool(self.auditor.enabled))
        if self.serve_feeds:
            t = _time.monotonic()
            for fn in list(self.serve_feeds):
                fn(closed, win)
            phases["fence.serve-feed"] = (_time.monotonic() - t) * 1e3
        # Lineage capture at the seal (obs/lineage.py): scan the same
        # extracted window for dyed keys — plus the epoch's sink
        # transaction shards for termini (complete at the fence in
        # both modes; the pipelined path seals them on the main thread
        # before this worker starts). Null plane: no scan, no file.
        if self.lineage.enabled and win is not None:
            t = _time.monotonic()
            self.lineage.observe_epoch(
                closed, win,
                num_key_groups=self.job.num_key_groups,
                topology=self._lineage_topology,
                parts={vid: tl.pending_shards(closed)
                       for vid, tl in self.txn_logs.items()})
            phases["fence.lineage-observe"] = (
                _time.monotonic() - t) * 1e3
        # Checkpoint at the fence: the lean fence snapshot (op state
        # + offsets; logs/rings are truncated on completion, not
        # persisted).
        t = _time.monotonic()
        with prof.section("snapshot"):
            self.coordinator.trigger(closed, snap_fn(),
                                     async_write=async_write, owned=True)
            if async_write:
                self.coordinator.drain()
        phases["fence.snapshot"] = (_time.monotonic() - t) * 1e3

    def _append_source_fence_determinant(self, closed: int,
                                         phases: Dict[str, float],
                                         prof) -> None:
        """The checkpoint-trigger RPC arrival is nondeterministic in
        the reference and logged by every source
        (StreamTask.performCheckpoint:833-840); fence-aligned here, but
        the determinant is still recorded for replay/wire parity — one
        fused device append for all sources, AFTER the fence capture /
        lean snapshot so the checkpointed log heads stay aligned with
        the fence offsets (the rows belong to the new epoch)."""
        if not self._source_flats:
            return
        t_ms = (self.executor.step_input_history[-1][0]
                if self.executor.step_input_history else 0)
        t = _time.monotonic()
        with prof.section("source-append"):
            self.executor.append_async_many(
                self._source_flats,
                det.SourceCheckpointDeterminant(
                    record_count=self.executor.global_record_stamp(),
                    checkpoint_id=closed, timestamp=t_ms))
            prof.fence(self.executor.carry.logs)
        phases["fence.source-append"] = (_time.monotonic() - t) * 1e3

    def _run_fence_tail_inline(self, closed: int,
                               complete_checkpoint: bool,
                               t_fence: float, tr, prof) -> None:
        """Today's strict fence order, inline on the calling thread —
        the sequential control. Phases land in ``last_fence_phases``
        under the same ``fence.*`` keys as the pipelined path, minus
        the overlap key (its absence marks the control run)."""
        phases: Dict[str, float] = {}
        # One fused device read per epoch: overflow flags + record
        # total + fence log heads (the tunnel round-trip is the cost
        # unit here, not device work).
        t = _time.monotonic()
        with prof.section("health-read"):
            vec = self.executor.health_vector()
        phases["fence.health-read"] = (_time.monotonic() - t) * 1e3
        delta_records = self._absorb_fence_health(closed, vec)
        # Overflow guards at every roll: an un-truncated ring that
        # wrapped has silently clobbered recovery state — fail
        # loudly, never limp.
        violations = self.executor.overflow_messages(vec)
        if violations:
            raise OverflowError_("; ".join(violations))
        # Host epoch control plane mirrors the fence.
        self.epoch_tracker.inc_record_count(delta_records)
        self.epoch_tracker.start_new_epoch(self.executor.epoch_id)
        # Audit seal at the fence (obs/audit.py): digest the closed
        # epoch's causal surface while its log/ring windows are
        # still resident (completion below truncates them), persist
        # the ledger entry next to the checkpoint, and fan out on
        # the epoch tracker's seal bus. The SOURCE_CHECKPOINT
        # appends after the snapshot land past this epoch's window
        # end, so the seal is fence-exact.
        self._seal_and_trigger(
            closed, lambda: self.executor.epoch_window(closed),
            self.executor.lean_snapshot, phases, prof, async_write=False)
        self._append_source_fence_determinant(closed, phases, prof)
        for tl in self.txn_logs.values():
            tl.seal(closed)
        # Before completion: ack_all truncates rings up to this
        # fence, so anything reading their fresh steps (edge
        # exports) goes now.
        for hook in self.fence_hooks:
            hook(closed)
        if complete_checkpoint:
            self.coordinator.ack_all(closed)
        fence_s = _time.monotonic() - t_fence
        phases["fence-tail"] = fence_s * 1e3
        self.last_fence_phases = phases
        self._m_epoch_fence_ms.update(fence_s * 1e3)
        tr.complete("epoch.fence", fence_s, epoch=closed)

    def _check_fence_headroom(self) -> None:
        """One epoch of ring headroom, asserted once: the pipelined
        fence defers the overflow check to the NEXT fence, so the
        in-flight rings must absorb one extra epoch of steps before
        wrapping — otherwise a wrap inside the deferral window silently
        clobbers the recovery state the check exists to protect.
        Spill-enabled runs are exempt (ring overflow is the spill
        tiers' concern, not the check's)."""
        self._fence_headroom_checked = True
        if self.executor.spill_logs is not None:
            return
        rings = self.executor.carry.out_rings
        if not rings:
            return
        min_steps = min(r.ring_steps for r in rings)
        spe = self.executor.steps_per_epoch
        if min_steps < 2 * spe:
            raise ValueError(
                f"overlap_epoch needs one epoch of ring headroom: "
                f"inflight_ring_steps={min_steps} < 2*steps_per_epoch="
                f"{2 * spe} — raise inflight_ring_steps or use the "
                f"sequential fence (overlap_epoch=False)")

    def _begin_fence_tail(self, closed: int, complete_checkpoint: bool,
                          prof) -> None:
        """Capture this fence's state as device-side handles and hand
        the tail to the single fence worker. Everything inside the
        overlap window stays dispatch-only — no host synchronization
        (lint rule overlap-window enforces it), so the next epoch's
        compute can be dispatched immediately behind it."""
        t = _time.monotonic()
        phases: Dict[str, float] = {}
        # clonos: overlap-window-begin
        handles = self.executor.capture_fence(
            with_window=self.auditor.enabled or bool(self.serve_feeds)
            or self.lineage.enabled)
        snap = self.executor.lean_snapshot()
        self._append_source_fence_determinant(closed, phases, prof)
        # clonos: overlap-window-end
        for tl in self.txn_logs.values():
            tl.seal(closed)
        for hook in self.fence_hooks:
            hook(closed)
        pre_ms = (_time.monotonic() - t) * 1e3
        phases["fence.capture"] = max(
            0.0, pre_ms - phases.get("fence.source-append", 0.0))
        tail = {"epoch": closed, "complete": complete_checkpoint,
                "handles": handles, "snap": snap, "phases": phases,
                "pre_ms": pre_ms, "vec": None, "err": None}
        th = threading.Thread(target=self._fence_worker, args=(tail,),
                              name="fence-tail", daemon=True)
        tail["thread"] = th
        self._fence_tail = tail
        th.start()

    def _fence_worker(self, tail: dict) -> None:
        """Fence-tail drain, off the critical path: drain the async
        health d2h, fold the host mirrors, advance the epoch control
        plane, then seal + ledger + checkpoint from the captured
        handles and make the snapshot durable (coordinator.drain before
        exit). Errors are held and re-raised at the join; the overflow
        check on the drained health vector is ALSO deferred to the join
        — it must run on the main thread, like the checkpoint ack whose
        completion listeners mutate executor state."""
        from clonos_tpu.obs import profile as _prof_mod
        closed = tail["epoch"]
        phases = tail["phases"]
        try:
            t = _time.monotonic()
            vec = tail["handles"].health()
            phases["fence.health-read"] = (_time.monotonic() - t) * 1e3
            tail["vec"] = vec
            delta_records = self._absorb_fence_health(closed, vec)
            self.epoch_tracker.inc_record_count(delta_records)
            # By value, not executor.epoch_id: the main thread may have
            # dispatched further epochs by the time this runs.
            self.epoch_tracker.start_new_epoch(closed + 1)
            self._seal_and_trigger(
                closed, tail["handles"].window, lambda: tail["snap"],
                phases, _prof_mod.NullProfiler(), async_write=True)
        except BaseException as e:      # re-raised at the join
            tail["err"] = e

    def _join_fence_tail(self) -> None:
        """Join the in-flight fence tail. Main thread only: the
        deferred overflow check and the checkpoint ack — whose
        completion listeners truncate logs/rings by replacing
        ``executor.carry`` — must interleave with steps, never with
        them. Also closes the tail's attribution: sub-spans keep their
        true walls, ``fence-tail`` is the critical-path wall actually
        paid (capture + join), and the difference is credited to
        ``fence.overlap-saved``, preserving
        sum(fence.*) - overlap-saved == fence-tail."""
        tail = self._fence_tail
        if tail is None:
            return
        self._fence_tail = None
        t = _time.monotonic()
        tail["thread"].join()
        joined_ms = (_time.monotonic() - t) * 1e3
        phases = tail["phases"]
        tail_ms = tail["pre_ms"] + joined_ms
        spans = sum(v for k, v in phases.items()
                    if k.startswith("fence."))
        saved = max(0.0, spans - tail_ms)
        phases["fence-tail"] = tail_ms
        phases["fence.overlap-saved"] = saved
        self.fence_overlap_saved_total_ms += saved
        self.last_fence_phases = phases
        prof = self.profiler
        for key, legacy in (("fence.health-read", "health-read"),
                            ("fence.digest-seal", "digest-seal"),
                            ("fence.ledger-write", "ledger-write"),
                            ("fence.snapshot", "snapshot")):
            if key in phases:
                prof.observe(legacy, phases[key] / 1e3)
        self._m_epoch_fence_ms.update(tail_ms)
        get_tracer().complete("epoch.fence", tail_ms / 1e3,
                              epoch=tail["epoch"])
        if tail["err"] is not None:
            raise tail["err"]
        violations = self.executor.overflow_messages(tail["vec"])
        if violations:
            raise OverflowError_(
                f"deferred fence check (pipelined fence, epoch "
                f"{tail['epoch']}): " + "; ".join(violations))
        if tail["complete"]:
            self.coordinator.ack_all(tail["epoch"])

    def fence_tail_in_flight(self) -> bool:
        """True while a pipelined fence tail is still unjoined."""
        return self._fence_tail is not None

    def drain_fence(self) -> None:
        """Settle the pipelined fence completely: join the in-flight
        tail (running its deferred overflow check and checkpoint ack)
        and wait out async checkpoint writes — after this, ledger,
        completion, and truncation state match what a sequential run
        would show at the same fence."""
        self._join_fence_tail()
        self.coordinator.drain()

    def step(self) -> None:
        if self.failed:
            raise rec.RecoveryError("failed subtasks present; recover() first")
        self.executor.step()
        self.global_step += 1
        self._m_steps.inc()
        self.heartbeats.beat_all_except(self.failed)

    # --- failure injection ---------------------------------------------------

    def _inject_fn(self, vid: int):
        """One fused kill program per vertex class (the eager per-array
        zeroing cost ~10 full-carry copies per kill over the tunnel)."""
        compiled = self.executor.compiled
        nr = compiled.plan.num_replicas

        def make():
            def f(carry, sub, flat, held_idx):
                fresh = clog.create(compiled.log_capacity,
                                    compiled.max_epochs)
                ops = list(carry.op_states)
                ops[vid] = jax.tree_util.tree_map(
                    lambda x: x.at[sub].set(jnp.zeros_like(x[sub])),
                    ops[vid])
                logs = jax.tree_util.tree_map(
                    lambda s, fr: s.at[flat].set(fr), carry.logs, fresh)
                replicas = carry.replicas
                if nr > 0:
                    replicas = jax.tree_util.tree_map(
                        lambda s, fr: s.at[held_idx].set(
                            jnp.broadcast_to(
                                fr, held_idx.shape + fr.shape),
                            mode="drop"),
                        replicas, fresh)
                rings = list(carry.out_rings)
                if vid in compiled.ring_index:
                    ri = compiled.ring_index[vid]
                    el = rings[ri]
                    rings[ri] = el._replace(
                        keys=el.keys.at[:, sub].set(0),
                        values=el.values.at[:, sub].set(0),
                        timestamps=el.timestamps.at[:, sub].set(0),
                        valid=el.valid.at[:, sub].set(False))
                return carry._replace(
                    op_states=tuple(ops), logs=logs, replicas=replicas,
                    out_rings=tuple(rings),
                    record_counts=carry.record_counts.at[flat].set(0))
            return f
        return self._jitted(("inject", vid), make, donate=(0,))

    def inject_failure(self, flat_subtasks: Sequence[int]) -> None:
        """Kill subtasks: zero their device state — operator slice, causal
        log row, held replica rows, and their shard of the vertex's
        in-flight output ring (the producer's subpartition log dies with
        the producer). (Fault-injection API the reference delegates to
        Jepsen, flink-jepsen/.)"""
        # A kill landing mid-pipelined-fence DRAINS the in-flight seal
        # deterministically: the tail belongs to an epoch every victim
        # completed healthy, so joining it first (seal + ledger +
        # checkpoint ack all land) makes the post-kill storage state a
        # pure function of the kill point — recovery then sees either a
        # completed fence or a cleanly pending one, never a half-sealed
        # epoch.
        self._join_fence_tail()
        carry = self.executor.carry
        nr = self.executor.compiled.plan.num_replicas
        for flat in flat_subtasks:
            self.failed.add(flat)
            self.heartbeats.mark_dead(flat)
            vid, sub = self._vertex_of(flat)
            held = np.full((max(nr, 1),), max(nr, 1), np.int32)
            hl = self.plan.replicas_held_by(flat)
            held[:len(hl)] = hl
            carry = self._inject_fn(vid)(
                carry, jnp.asarray(sub, jnp.int32),
                jnp.asarray(flat, jnp.int32), jnp.asarray(held))
        self.executor.carry = carry

    def _vertex_of(self, flat: int) -> Tuple[int, int]:
        for v in self.job.vertices:
            base = self.job.subtask_base(v.vertex_id)
            if base <= flat < base + v.parallelism:
                return v.vertex_id, flat - base
        raise ValueError(f"no subtask {flat}")

    # --- recovery (reference §3.4 signature path) ----------------------------

    def detect_failures(self) -> List[int]:
        return self.heartbeats.expired()

    def recover(self, drill: bool = False,
                host_rows: Optional[Dict[int, Tuple[np.ndarray, int]]]
                = None,
                overlap_finalize: Optional[bool] = None,
                pre_patch_join: Optional[Callable[[], None]] = None
                ) -> RecoveryReport:
        """Public entry for :meth:`_recover_impl` that additionally
        lands an incident bundle (obs/incident.py) when the protocol
        itself fails — a recovery that cannot complete is exactly the
        moment the forensic state (ledgers, determinant windows, HLC
        timeline) is about to become unreachable. No-op passthrough
        when the incident plane is disabled."""
        try:
            return self._recover_impl(
                drill=drill, host_rows=host_rows,
                overlap_finalize=overlap_finalize,
                pre_patch_join=pre_patch_join)
        except Exception as e:
            from clonos_tpu.obs.incident import get_incidents
            get_incidents().signal(
                "recovery.failure",
                epoch=int(getattr(self.auditor, "last_epoch", -1)),
                error=f"{type(e).__name__}: {str(e)[:200]}",
                drill=bool(drill),
                failed=sorted(self.failed))
            raise

    def _recover_impl(self, drill: bool = False,
                      host_rows: Optional[Dict[int, Tuple[np.ndarray, int]]]
                      = None,
                      overlap_finalize: Optional[bool] = None,
                      pre_patch_join: Optional[Callable[[], None]] = None
                      ) -> RecoveryReport:
        """Run the full causal-recovery protocol for all failed subtasks,
        in topological order (an upstream's reconstructed ring shard feeds
        its downstream's replay — the reference's staged
        WaitingConnections/in-flight-request ordering).

        ``drill=True`` (failover rehearsal) runs the identical replay
        protocol but makes none of the failure-handling *decisions* —
        pending checkpoints are not ignored (they may yet complete),
        no IGNORE_CHECKPOINT determinants are logged, the checkpoint
        interval is not backed off, and recovered timer effects are not
        re-fired — so the job state is bit-identical afterwards.

        ``host_rows`` maps flat subtask -> (rows, abs_start): an external
        determinant source that replaces the on-device replica fetch for
        those subtasks — the standby-HOST path, where the rows come from
        a RemoteReplicaMirror after a whole-host loss (reference
        DeterminantResponseEvent arriving over the wire instead of the
        local piggyback channel).

        ``overlap_finalize`` selects the finalize pipeline: overlapped
        (the default, via ``self.overlap_recovery``) drains the final
        packed barrier-read on a worker thread while the main thread
        runs the audit validator, with an explicit join +
        deferred-assert check before returning; revive bookkeeping
        runs only after the join and state-verify pass (the same
        safety order as the control — a failed verify leaves the
        subtasks marked dead, and an audit divergence is re-raised
        after verify). ``False`` is the strictly-sequential control
        (barrier-read → state-verify → revive → audit) that bench/soak
        diff the overlapped path's ledger against.

        ``pre_patch_join`` is the bootstrap-overlap hook: a callable
        joined (once) immediately before the FIRST ``_patch`` call —
        the earliest point recovery reads the roll-gap/async ledgers a
        bootstrap derives on a worker thread concurrently with this
        replay. Its blocked wall is attributed to
        ``finalize.listener-reattach``, not to the patch phase."""
        if not self.failed:
            raise rec.RecoveryError("no failed subtasks")
        # Defensive: inject_failure already drains the pipelined fence,
        # but recovery must never run against a half-sealed tail.
        self._join_fence_tail()
        if not self.standbys.has_state():
            raise rec.RecoveryError(
                "no completed checkpoint to restore standbys from")
        t0 = _time.monotonic()
        topo_pos = {vid: i for i, vid in
                    enumerate(self.executor.compiled.topo)}
        failed = tuple(sorted(
            self.failed, key=lambda f: (topo_pos[self._vertex_of(f)[0]], f)))

        # (1) RunStandbyTaskStrategy.onTaskFailure: ignore checkpoints the
        # dead tasks never acked; back off the checkpoint interval.
        ignored: Tuple[int, ...] = ()
        if not drill:
            ignored = tuple(self.coordinator.ignore_unacked_for(set(failed)))
            self.coordinator.backoff()
            # Healthy tasks log the ignore decision (reference
            # StreamTask.ignoreCheckpoint:891-915 — the RPC arrival is a
            # determinant so their own later recoveries replay it).
            healthy = [f for f in range(self.job.total_subtasks())
                       if f not in self.failed]
            for cid in ignored:
                self.executor.append_async_many(
                    healthy, det.IgnoreCheckpointDeterminant(
                        record_count=self.executor.global_record_stamp(),
                        checkpoint_id=cid))

        ckpt = self.standbys.latest
        from_epoch = ckpt.checkpoint_id + 1
        fence = self._fence_step[from_epoch]
        n_steps = self.global_step - fence
        snap: LeanSnapshot = jax.tree_util.tree_map(jnp.asarray, ckpt.carry)
        managers: List[rec.RecoveryManager] = []
        total_dets = 0
        total_records = 0
        # Shard-local restore accounting: bytes each failed subtask's
        # rehydration actually moves vs the full snapshot a global
        # rollback would re-load (the paper's local-recovery claim as a
        # measurable ratio; surfaces on the RecoveryReport).
        restore_bytes = 0
        checkpoint_bytes = (int(getattr(ckpt, "size_bytes", 0) or 0)
                            or cp.carry_nbytes(ckpt.carry))
        phases: Dict[str, float] = {}

        def _clock(name: str, since: float) -> float:
            now = _time.monotonic()
            phases[name] = phases.get(name, 0.0) + (now - since) * 1e3
            get_tracer().complete(f"recovery.{name}", now - since,
                                  drill=drill)
            return now

        patched = self.executor.carry
        # Ring bounds for routing coverage decisions: the host mirror
        # (tails move only at checkpoint completion, heads advance one
        # per superstep == global_step) when valid, else one device read.
        # The device values recovery actually used are re-checked in the
        # final packed read either way (fail-loud, not trust).
        bounds_dev = self._ring_bounds_dev()
        nrings = len(patched.out_rings)
        if self._ring_mirror_valid:
            # Heads advance once per superstep wherever the executor is
            # driven from; its own step counter is the authoritative one.
            head_m = self.executor._steps_executed
            self._bounds_cache = {
                ri: (self._ring_tail_mirror, head_m)
                for ri in range(nrings)}
        else:
            barr = (np.asarray(bounds_dev) if nrings
                    else np.zeros((0, 2), np.int32))
            self._bounds_cache = {ri: (int(barr[ri, 0]), int(barr[ri, 1]))
                                  for ri in range(nrings)}
        self._route_cache = {}
        self._route_cache_hits = 0
        vid_failed_counts: Dict[int, int] = {}
        for flat in failed:
            v_of = self._vertex_of(flat)[0]
            vid_failed_counts[v_of] = vid_failed_counts.get(v_of, 0) + 1
        prev_vid = None
        tp = _clock("restore", t0)

        # ---- phase A: determinant metadata for ALL failed subtasks ----
        # Dispatch every per-subtask parse/meta program up front, then pay
        # at most ONE host read for the whole failure set. Subtasks whose
        # cleanness the host can derive itself (no async rows since the
        # fence — executor.async_counts ledger — and fence log heads in
        # hand) skip even that: their metadata becomes deferred asserts
        # in the final packed read, and their replay defers its sync too.
        # On a tunneled device the round-trips ARE the warm recovery cost
        # (~100ms each vs a 133ms replay — r4's protocol bottleneck).
        with self._ck_heads_lock:
            ck_heads = self._ck_log_heads.get(ckpt.checkpoint_id)
        from clonos_tpu.api.operators import HostFeedSource
        prep: Dict[int, Dict[str, Any]] = {}
        slow_reads: List[Tuple[int, str, Any]] = []
        for flat in failed:
            vid_a, _sub_a = self._vertex_of(flat)
            v_a = self.job.vertices[vid_a]
            if host_rows is not None and flat in host_rows:
                # External determinant source (standby-host mirror):
                # no device fetch/parse to dispatch at all.
                prep[flat] = {"holders": [], "fast": False, "host": True}
                continue
            holders_a = [
                (r, h) for r, (o, h) in enumerate(self.plan.pairs)
                if o == flat and h not in self.failed]
            p: Dict[str, Any] = {"holders": holders_a}
            eligible = (bool(holders_a) and n_steps > 0
                        and v_a.operator.replay_pad_safe
                        and not isinstance(v_a.operator, HostFeedSource)
                        and n_steps <= self._pad_steps())
            if eligible:
                t_d, r_d, e_d, small_d = self._device_parse_fn()(
                    patched.replicas,
                    jnp.asarray(holders_a[0][0], jnp.int32),
                    jnp.asarray(from_epoch, jnp.int32))
                p["det_device"] = (t_d, r_d, e_d)
                p["small_d"] = small_d
            if holders_a:
                hidx_a = jnp.asarray([r for r, _ in holders_a], jnp.int32)
                p["meta_d"] = self._fetch_meta_fn(len(holders_a))(
                    patched.replicas, hidx_a,
                    jnp.asarray(from_epoch, jnp.int32))
            p["fast"] = (eligible and ck_heads is not None
                         and vid_a not in self.txn_logs
                         and self.executor.async_rows_since(
                             flat, from_epoch) == 0)
            if not p["fast"]:
                if "small_d" in p:
                    slow_reads.append((flat, "small", p["small_d"]))
                if "meta_d" in p:
                    slow_reads.append((flat, "meta", p["meta_d"]))
            prep[flat] = p
        slow_vals: Dict[Tuple[int, str], np.ndarray] = {}
        if slow_reads:
            packed_a = np.asarray(jnp.concatenate(
                [d.reshape(-1).astype(jnp.int32)
                 for _f, _k, d in slow_reads]))
            off_a = 0
            for flat, kind, d in slow_reads:
                nsz = int(np.prod(d.shape))
                slow_vals[(flat, kind)] = packed_a[
                    off_a: off_a + nsz].reshape(d.shape)
                off_a += nsz
        tp = _clock("fetch_determinants", tp)

        for flat in failed:
            vid, sub = self._vertex_of(flat)
            if vid != prev_vid:
                # Routed windows are valid only while the upstream rings
                # they read are final — scope the share to one vertex's
                # consumers (upstream vertices were patched earlier in
                # topological order). The cache holds full [m, P, cap]
                # blocks, so bound its bytes: past the budget every
                # consumer takes the fused per-lane path instead of an
                # OOM mid-recovery.
                self._route_cache = {}
                share = vid_failed_counts[vid] >= 2
                if share and n_steps > 0:
                    ch_ = self._chunk()
                    nblocks_ = -(-n_steps // ch_)
                    est = sum(
                        nblocks_ * ch_
                        * self.job.vertices[self.job.edges[e2].dst
                                            ].parallelism
                        * self.job.edges[e2].capacity * 4 * 4
                        for e2 in self.job.in_edges(vid))
                    share = est <= (1 << 30)
                self._route_cache_enabled = share
                prev_vid = vid
            v = self.job.vertices[vid]
            mgr = rec.RecoveryManager(vid, sub, flat,
                                      self._make_replayer(vid, sub))
            managers.append(mgr)
            in_edges = self.job.in_edges(vid)
            out_edges = self.job.out_edges(vid)

            # FSM: standby -> connections re-established + state restored.
            mgr.notify_start_recovery(in_edges, out_edges)
            mgr.notify_state_restoration_complete()
            for e in in_edges:
                mgr.notify_new_input_channel(e)
            for e in out_edges:
                mgr.notify_new_output_channel(e)

            # DeterminantRequest flood to surviving holders of this log
            # (programs were dispatched in phase A; values arrive either
            # from the phase-A packed read or — fast path — as deferred
            # asserts in the final one).
            p = prep[flat]
            holders = p["holders"]
            fast = p["fast"]
            synthesized = False
            if p.get("host"):
                # Mirror-sourced determinants (whole-host loss): the rows
                # arrived over the wire; everything downstream of the
                # fetch (merge, replay, verify, patch) is identical.
                rows_h, start_h = host_rows[flat]
                mgr.expect_determinant_responses(1)
                mgr.notify_determinant_response(
                    np.asarray(rows_h, np.int32), int(start_h))
            elif not holders and n_steps > 0:
                if out_edges:
                    raise rec.RecoveryError(
                        f"subtask {flat}: no surviving replica holds its "
                        f"determinant log (sharing depth / replication "
                        f"factor too shallow for this failure pattern)")
                # Pure sink: nobody downstream replicates its log. Its
                # inputs replay exactly from the upstream ring; its own
                # nondeterminism (time/rng step inputs) is re-synthesized
                # from the coordinator's input ledger. (The reference has
                # the same boundary: sink exactly-once needs transactional
                # sinks, TwoPhaseCommitSinkFunction.)
                synthesized = True
            r_best = None
            det_device = None
            clean_n = None
            if p.get("host"):
                pass          # responses already delivered above
            elif fast:
                # Host-derived cleanness: zero async rows since the fence
                # means the log holds exactly n_steps k-row sync blocks
                # starting at the checkpointed head. Everything the old
                # metadata read returned is therefore known here; the
                # device parse/meta values become deferred asserts.
                ck_head_f = int(ck_heads[flat])
                det_device = p["det_device"]
                clean_n, clean_start = DETS_PER_STEP * n_steps, ck_head_f
                r_best = holders[0][0]
                mgr.expect_determinant_responses(1)
                mgr.notify_determinant_response(
                    np.zeros((0, det.NUM_LANES), np.int32), clean_start)
            elif holders:
                # Holders are bit-identical replicas by construction, so
                # when their metadata agrees the merge is "pull one body"
                # (saves H-1 multi-MB transfers + 2(H-1) round-trips).
                meta = slow_vals[(flat, "meta")]
                consistent = (len(np.unique(meta[:, 0])) == 1
                              and len(np.unique(meta[:, 1])) == 1)
                # Clean path off the ledger fast lane: the device parse
                # (phase A) says whether the stream is pure sync rows; if
                # so the multi-MB body never crosses the host link.
                if consistent and (flat, "small") in slow_vals:
                    cnt_s, start_s, nanch, cleanflag = (
                        int(x) for x in slow_vals[(flat, "small")])
                    if cleanflag and nanch == n_steps:
                        det_device = p["det_device"]
                        clean_n, clean_start = cnt_s, start_s
                        mgr.expect_determinant_responses(1)
                        mgr.notify_determinant_response(
                            np.zeros((0, det.NUM_LANES), np.int32),
                            start_s)
                if det_device is None:
                    use = ([holders[0]] if consistent else holders)
                    mgr.expect_determinant_responses(len(use))
                    fetch = self._fetch_fn()
                    for j, (r, _h) in enumerate(use):
                        buf, count, start = fetch(
                            patched.replicas, jnp.asarray(r, jnp.int32),
                            jnp.asarray(from_epoch, jnp.int32))
                        mgr.notify_determinant_response(
                            np.asarray(buf)[: int(meta[j, 0])],
                            int(meta[j, 1]))
                # A single consistent replica's device bytes can restore
                # the log directly; disagreeing holders must go through
                # the host merge (r_best None -> chunked upload path).
                r_best = holders[0][0] if consistent else None
            else:
                mgr.expect_determinant_responses(0)
            if synthesized:
                rows = self._synthesize_det_rows(fence, n_steps)
                start = (int(ck_heads[flat]) if ck_heads is not None
                         else int(np.asarray(snap.log_heads[flat])))
            elif det_device is not None:
                rows = np.zeros((0, det.NUM_LANES), np.int32)
                start = clean_start
            else:
                rows, start = mgr.merged_determinants()
            total_dets += clean_n if clean_n is not None else len(rows)
            tp = _clock("fetch_determinants", tp)

            # Lost inputs: the checkpointed edge buffer (the depth-1 batch
            # spanning the fence) + the upstream rings' raw outputs,
            # re-routed through the deterministic exchange. Upstream ring
            # shards zeroed by a connected failure were rebuilt earlier in
            # this loop (topological order).
            from clonos_tpu.api.operators import (HostFeedSource,
                                                  TwoInputOperator)
            input_steps = None
            if isinstance(v.operator, TwoInputOperator):
                input_steps = list(zip(
                    self._replay_inputs(patched, snap, in_edges[0], sub,
                                        fence, n_steps),
                    self._replay_inputs(patched, snap, in_edges[1], sub,
                                        fence, n_steps)))
            elif in_edges:
                input_steps = self._replay_inputs(patched, snap, in_edges[0],
                                                  sub, fence, n_steps)
            elif isinstance(v.operator, HostFeedSource) and n_steps > 0:
                input_steps = self._reread_feed(vid, sub, snap, rows, n_steps)
            tp = _clock("inputs", tp)

            plan = rec.ReplayPlan(
                vertex_id=vid, subtask=sub, flat_subtask=flat,
                from_epoch=from_epoch, input_steps=input_steps,
                det_rows=rows, det_start=start,
                checkpoint_op_state=snap.op_states[vid],
                n_steps=n_steps, verify_outputs=not synthesized,
                det_device=det_device)
            restore_bytes += rec.plan_restore_nbytes(plan)
            # Fast path: replay dispatches only — output-cut verification
            # and the consumed total ride the final packed read.
            result = mgr.run_replay(plan, defer_sync=fast)
            if not result.deferred:
                total_records += result.records_replayed
            # Re-fire recovered timer effects (rows are already spliced
            # into the rebuilt log; only the callback side-effects re-run —
            # reference LogReplayerImpl.triggerAsyncEvent:102).
            svc = self.timer_services.get(flat)
            if svc is not None and not drill:
                for _step_i, ad in result.async_events:
                    if isinstance(ad, det.TimerTriggerDeterminant):
                        svc.refire(ad)
            # Transactional sink: its pending transaction shards died with
            # the task — rebuild them from the replayed outputs BEFORE any
            # commit can run (2PC abort+regenerate; TwoPhaseCommitSink
            # recoverAndAbort analog).
            if vid in self.txn_logs and n_steps > 0:
                self.txn_logs[vid].drop_uncommitted_shards(sub)
                self._rebuild_txn_shards(vid, sub, result, from_epoch,
                                         fence, n_steps)
            tp = _clock("replay", tp)

            rebuilt = np.asarray(result.rebuilt_log_rows)
            # The regenerated determinant rows must equal the recovered ones
            # (bit-identical replay; reference post-replay log asserts).
            # Skipped when rebuilt IS the recovered buffer (clean path):
            # verify() already established the only re-derived lane
            # (BUFFER_BUILT) matches, and comparing a view against itself
            # would be dead work masquerading as a check.
            if not synthesized and not result.rebuilt_is_view \
                    and not np.array_equal(
                        rebuilt, rows[: rebuilt.shape[0]]):
                raise rec.RecoveryError(
                    f"subtask {flat}: replayed determinant stream diverges "
                    f"from the recovered log")

            if pre_patch_join is not None:
                # Bootstrap's ledger-derivation thread must land before
                # _patch reads roll_gap_async; the blocked remainder is
                # the non-overlapped listener-reattach cost (the rest
                # rode inside the replay window above).
                t_j = _time.monotonic()
                pre_patch_join()
                b_j = _time.monotonic() - t_j
                phases["finalize.listener-reattach"] = (
                    phases.get("finalize.listener-reattach", 0.0)
                    + b_j * 1e3)
                tp += b_j            # exclude the wait from "patch"
                pre_patch_join = None
            patched = self._patch(patched, snap, vid, sub, flat,
                                  result, rebuilt, from_epoch, fence,
                                  n_steps, replica_src=r_best,
                                  det_n=clean_n,
                                  clean_sync=det_device is not None,
                                  ck_head=(int(ck_heads[flat])
                                           if ck_heads is not None
                                           else None))
            tp = _clock("patch", tp)

        # Replica rows held by revived subtasks: replicas are identical to
        # their owner's log by construction (same bulk appends), so rebuild
        # by copying the owner's (possibly just-restored) log row — one
        # batched scatter for the whole failure set.
        rs, os_ = [], []
        for flat in failed:
            for r in self.plan.replicas_held_by(flat):
                rs.append(r)
                os_.append(self.plan.pairs[r][0])
        if rs:
            # Fixed-size scatter (pad with out-of-range rows, mode=drop)
            # so one prewarmed program serves every failure-set size.
            nr = self.plan.num_replicas
            rs_p = np.full((nr,), nr, np.int32)
            os_p = np.zeros((nr,), np.int32)
            rs_p[:len(rs)] = rs
            os_p[:len(os_)] = os_
            patched = patched._replace(replicas=self._replica_copy_fn()(
                patched.replicas, patched.logs,
                jnp.asarray(rs_p), jnp.asarray(os_p)))

        self.executor.carry = patched
        self._bounds_cache = None
        self._route_cache = {}     # free the held routed device buffers
        tp = _clock("replica_rebuild", tp)

        # ---- final packed read: completion barrier + deferred asserts ----
        # ONE device->host transfer closes the protocol: the restored log
        # heads (graft landed), the ring bounds recovery routed against,
        # and for every fast-path subtask its parse/meta metadata, its
        # on-device output-cut verification flag, and its consumed total.
        # TPU programs execute in dispatch order, so this read — dispatched
        # last — is also the barrier the old device_sync(patched) was.
        # Sub-attribution (the bench's one-number "finalize" mystery):
        # ``finalize.barrier-read`` = the packed concatenate + d2h
        # transfer (dispatch-order barrier: it pays for every program
        # still in flight), ``finalize.state-verify`` = the host-side
        # deferred asserts. Overlapped mode drains the transfer on a
        # worker thread while the main thread runs the audit validator
        # inside the same window; the sub-spans keep their true walls
        # and ``finalize.overlap-saved`` carries the credit, so
        # sum(finalize.*) - overlap-saved == finalize (overlap
        # attributed, never hidden). The join + deferred asserts run
        # before recover() returns — a mis-speculated fast-path replay
        # raises here, before any live step, with the audit validator
        # as an independent gate on the replayed state. Revive
        # bookkeeping runs after verify in BOTH modes: a failed
        # barrier/verify/audit leaves the subtasks marked dead so the
        # failure is retryable, never silently "healthy".
        overlap = (self.overlap_recovery if overlap_finalize is None
                   else bool(overlap_finalize))
        t_fin0 = tp
        fast_mgrs = [m for m in managers if prep[m.flat_subtask]["fast"]]
        fl_d = jnp.asarray(list(failed), jnp.int32)
        pieces = [patched.logs.head[fl_d].astype(jnp.int32)]
        if nrings:
            pieces.append(bounds_dev.reshape(-1).astype(jnp.int32))
        for m in fast_mgrs:
            pf = prep[m.flat_subtask]
            pieces += [
                pf["small_d"].astype(jnp.int32),
                pf["meta_d"].reshape(-1).astype(jnp.int32),
                m.result.verify_ok_d.astype(jnp.int32).reshape(1),
                m.result.consumed_d.astype(jnp.int32).reshape(1)]
        packed_f = jnp.concatenate(pieces)        # dispatch only
        barrier: Dict[str, Any] = {"arr": None, "err": None, "ms": 0.0}

        def _drain_barrier() -> None:
            try:
                barrier["arr"] = np.asarray(packed_f)
            except Exception as err:      # surfaces at the join below
                barrier["err"] = err
            barrier["ms"] = (_time.monotonic() - t_fin0) * 1e3

        def _verify(arr_f: np.ndarray) -> int:
            verified_records = 0
            off_f = len(failed)
            heads_after = arr_f[:off_f]
            if nrings:
                bounds_np = arr_f[off_f: off_f + nrings * 2].reshape(
                    nrings, 2)
                off_f += nrings * 2
                if self._ring_mirror_valid:
                    for ri in range(nrings):
                        want = (self._ring_tail_mirror,
                                self.executor._steps_executed)
                        got = (int(bounds_np[ri, 0]),
                               int(bounds_np[ri, 1]))
                        if got != want:
                            raise rec.RecoveryError(
                                f"ring {ri}: host bound mirror {want} "
                                f"diverges from device bounds {got} — "
                                f"recovery routed against wrong "
                                f"coverage; state suspect")
            want_n = DETS_PER_STEP * n_steps
            for m in fast_mgrs:
                flat_m = m.flat_subtask
                pf = prep[flat_m]
                ck_head_m = int(ck_heads[flat_m])
                small_np = arr_f[off_f: off_f + 4]
                off_f += 4
                nh = len(pf["holders"])
                meta_np = arr_f[off_f: off_f + 2 * nh].reshape(nh, 2)
                off_f += 2 * nh
                ok_f = int(arr_f[off_f])
                consumed_f = int(arr_f[off_f + 1])
                off_f += 2
                if (tuple(int(x) for x in small_np)
                        != (want_n, ck_head_m, n_steps, 1)):
                    raise rec.RecoveryError(
                        f"subtask {flat_m}: host-derived clean stream "
                        f"(n={want_n}, start={ck_head_m}, "
                        f"anchors={n_steps}) contradicted by device "
                        f"parse {[int(x) for x in small_np]} — "
                        f"async-row ledger or fence-head cache is "
                        f"wrong; state suspect")
                for j in range(nh):
                    if (int(meta_np[j, 0]), int(meta_np[j, 1])) \
                            != (want_n, ck_head_m):
                        raise rec.RecoveryError(
                            f"subtask {flat_m}: replica holder {j} "
                            f"metadata {meta_np[j].tolist()} disagrees "
                            f"with ({want_n}, {ck_head_m}) — replicas "
                            f"inconsistent")
                if int(heads_after[list(failed).index(flat_m)]) \
                        != ck_head_m + want_n:
                    raise rec.RecoveryError(
                        f"subtask {flat_m}: restored log head "
                        f"{int(heads_after[list(failed).index(flat_m)])}"
                        f" != fence head {ck_head_m} + {want_n} rows")
                if not ok_f:
                    # Resolve the device arrays and let verify() build
                    # the detailed divergence message (failure path: the
                    # extra transfer is fine).
                    m.result.emit_counts = np.asarray(m.result.emit_counts)
                    m.result.expected_emits = np.asarray(
                        m.result.expected_emits)
                    try:
                        m.result.verify()
                    except rec.RecoveryError as err:
                        raise rec.RecoveryError(
                            f"subtask {flat_m}: {err}") from None
                    raise rec.RecoveryError(
                        f"subtask {flat_m}: device verify flag tripped "
                        f"but host recheck passed — flag/stream mismatch")
                m.result.records_replayed = consumed_f
                verified_records += consumed_f
            return verified_records

        def _revive() -> None:
            for flat in failed:
                self.heartbeats.revive(flat)
            self.failed.clear()
            if not drill:
                self.coordinator.reset_interval()

        def _audit() -> float:
            # Audit validation (obs/audit.py): recompute every replayed
            # closed epoch's digest from the patched carry and compare
            # against the sealed ledger — one match/divergence instant
            # per epoch lands under this recovery's trace id. Abort
            # policy raises AuditDivergenceError here: fail loudly
            # before the job resumes on state that did not reproduce
            # the original execution.
            if not self.auditor.enabled:
                return 0.0
            t_a = _time.monotonic()
            validator = rec.AuditValidator(
                self.executor, self.coordinator.read_ledger(),
                on_divergence=self.auditor.on_divergence)
            try:
                validator.validate(
                    range(from_epoch, self.executor.epoch_id))
            finally:
                # evidence reaches the metrics plane even when the
                # abort policy throws mid-validation
                self._m_audit_matches.inc(validator.stats["match"])
                self._m_audit_div.inc(validator.stats["divergence"])
            a_ms = (_time.monotonic() - t_a) * 1e3
            phases["audit"] = phases.get("audit", 0.0) + a_ms
            get_tracer().complete("recovery.audit", a_ms / 1e3,
                                  drill=drill)
            return a_ms

        audit_ms = 0.0
        audit_err: Optional[Exception] = None
        if overlap:
            th = threading.Thread(target=_drain_barrier,
                                  name="recovery-finalize-barrier")
            th.start()
            # Host-side finalize work folded into the barrier window:
            # the audit validator's digest recompute reads the same
            # patched carry the packed read waits on (its transfers
            # interleave with the barrier d2h instead of queuing after
            # it). Revive bookkeeping does NOT fold in: it must stay
            # after the join + state-verify below, exactly as in the
            # sequential control — if the packed read or a deferred
            # assert raises, self.failed and the heartbeat table must
            # still mark the subtasks dead so a retry of recover()
            # sees them. An audit divergence is held and re-raised
            # after verify (the control's diagnostic order: a verify
            # failure wins), and the join runs unconditionally so the
            # barrier thread never outlives this call.
            t_a0 = _time.monotonic()
            try:
                audit_ms = _audit()
            except Exception as err:
                audit_err = err
                audit_ms = (_time.monotonic() - t_a0) * 1e3
            finally:
                # KeyboardInterrupt/SystemExit skip the deferral but
                # still land here: the thread never leaks.
                th.join()
        else:
            _drain_barrier()
        if barrier["err"] is not None:
            raise barrier["err"]
        phases["finalize.barrier-read"] = (
            phases.get("finalize.barrier-read", 0.0) + barrier["ms"])
        get_tracer().complete("recovery.finalize.barrier-read",
                              barrier["ms"] / 1e3, drill=drill)
        t_v = _time.monotonic()
        total_records += _verify(barrier["arr"])
        now_v = _time.monotonic()
        verify_ms = (now_v - t_v) * 1e3
        phases["finalize.state-verify"] = (
            phases.get("finalize.state-verify", 0.0) + verify_ms)
        get_tracer().complete("recovery.finalize.state-verify",
                              verify_ms / 1e3, drill=drill)
        fin_ms = (now_v - t_fin0) * 1e3 - audit_ms
        phases["finalize"] = phases.get("finalize", 0.0) + fin_ms
        get_tracer().complete("recovery.finalize", fin_ms / 1e3,
                              drill=drill)
        tp = now_v
        if overlap:
            # Same safety order as the control: verify passed, NOW the
            # subtasks may be marked healthy; a deferred audit
            # divergence propagates after revive, exactly where the
            # sequential path would raise it.
            _revive()
            if audit_err is not None:
                raise audit_err
            # Unclamped, this is min(audit wall, barrier wall) — both
            # sub-spans keep their true walls while the window paid
            # only the longer of the two; revive runs outside the
            # window in both modes so no wall hides in the clamp
            # (which only absorbs sub-ms thread-start jitter).
            phases["finalize.overlap-saved"] = (
                phases.get("finalize.overlap-saved", 0.0)
                + max(0.0, barrier["ms"] + verify_ms - fin_ms))
        else:
            # Sequential control keeps the old order: barrier-read →
            # state-verify → revive → audit (and never writes the
            # overlap-saved key — its absence marks the control path).
            _revive()
            audit_ms = _audit()
            tp = _time.monotonic()
        report = RecoveryReport(
            failed_subtasks=failed, from_epoch=from_epoch,
            steps_replayed=n_steps, determinants_replayed=total_dets,
            records_replayed=total_records,
            ignored_checkpoints=ignored,
            recovery_ms=(_time.monotonic() - t0) * 1e3,
            managers=tuple(managers), phase_ms=phases, drill=drill,
            restore_bytes=restore_bytes, checkpoint_bytes=checkpoint_bytes)
        if not drill:
            # Rehearsals must not inflate the recovery count/latency
            # series operators alert on.
            self.reports.append(report)
            self._m_recovery_ms.update(report.recovery_ms)
            self._m_recovered_records.inc(report.records_replayed)
            # Per-phase latency distributions (recovery.replay-ms p50/p99
            # etc.) — the tuning surface for the paper's headline claim.
            for pname, ms in phases.items():
                self._mgroup.histogram(f"recovery.{pname}-ms").update(ms)
        get_tracer().complete(
            "recovery", report.recovery_ms / 1e3, drill=drill,
            failed=list(failed), from_epoch=from_epoch,
            steps_replayed=n_steps, records_replayed=total_records)
        return report

    def prewarm_recovery(self, vertex_ids: Optional[Sequence[int]] = None,
                         spill_paths: bool = False) -> float:
        """Compile every recovery program a standby will need, at job
        start — the reference keeps standby tasks *deployed* so failover
        only switches them to RUNNING (Task.java:300-302, :1040,
        Execution.java:373-377 state re-dispatch); the TPU analog of
        "deployed" is "XLA-compiled": after this, the failure path runs
        entirely on cached executables (recovery-time-to-resume drops from
        minutes of compile to milliseconds of replay).

        Requires ``num_standby >= 1`` (the knob that buys warm failover).
        Returns wall-clock seconds spent compiling. For vertices whose
        input edge is statically routed the replay program is specialized
        per subtask; all subtasks are prewarmed.
        """
        if self.standbys.num_standby_per_vertex < 1:
            raise rec.RecoveryError(
                "prewarm_recovery needs num_standby >= 1 (no standby "
                "programs requested)")
        t0 = _time.monotonic()
        from clonos_tpu.api.operators import TwoInputOperator
        from clonos_tpu.api.records import RecordBatch as RB
        ch = self._chunk()
        carry = self.executor.carry
        compiled = self.executor.compiled
        zero = lambda shape, dt=jnp.int32: jnp.zeros(shape, dt)

        def zero_batch(lead):
            return RB(zero(lead), zero(lead), zero(lead),
                      zero(lead, jnp.bool_))

        # Fetch + replica copy + ring bounds + replica-sourced log restore.
        if compiled.plan.num_replicas > 0:
            self._fetch_fn()(carry.replicas, jnp.asarray(0, jnp.int32),
                             jnp.asarray(0, jnp.int32))
            self._device_parse_fn()(carry.replicas,
                                    jnp.asarray(0, jnp.int32),
                                    jnp.asarray(0, jnp.int32))
            holders_per_owner = {}
            for (o, _h) in compiled.plan.pairs:
                holders_per_owner[o] = holders_per_owner.get(o, 0) + 1
            for h in sorted(set(holders_per_owner.values())):
                self._fetch_meta_fn(h)(carry.replicas, zero((h,)),
                                       jnp.asarray(0, jnp.int32))
            self._log_restore_from_replica_fn()(
                carry.replicas, jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32), zero((compiled.max_epochs,)),
                zero((compiled.max_epochs,), jnp.bool_),
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
            nr = compiled.plan.num_replicas
            # Donated arg: hand the prewarm a disposable dummy, never the
            # live carry (donation deletes the input buffers).
            self._replica_copy_fn()(
                jax.tree_util.tree_map(lambda x: jnp.zeros_like(x),
                                       carry.replicas),
                carry.logs, jnp.full((nr,), nr, jnp.int32), zero((nr,)))
        if carry.out_rings:
            self._ring_bounds()
        # Shared log-restore programs.
        st = clog.create(compiled.log_capacity, compiled.max_epochs)
        st = self._log_restore_fn()(
            zero((ch * DETS_PER_STEP, det.NUM_LANES)),
            jnp.asarray(0, jnp.int32), st)
        self._log_finalize_fn()(
            st, zero((compiled.max_epochs,)),
            zero((compiled.max_epochs,), jnp.bool_),
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))

        vids = (list(vertex_ids) if vertex_ids is not None
                else [v.vertex_id for v in self.job.vertices])
        # Independent compiles run CONCURRENTLY: each job below first-calls
        # one jit program; XLA compilations of distinct programs proceed in
        # parallel across threads (the executions they also trigger are
        # tiny and serialize on the device queue). This roughly divides
        # prewarm wall-clock by min(#workers, #independent programs).
        jobs: List[Any] = []
        heavy: List[Tuple[int, Any]] = []

        def _edge_jobs(vid: int) -> None:
            v = self.job.vertices[vid]
            in_edges = self.job.in_edges(vid)
            # Ring/route/concat programs for each input edge.
            for eidx in in_edges:
                e = self.job.edges[eidx]
                src_p = self.job.vertices[e.src].parallelism
                src_cap = compiled.vertex_out_capacity(e.src)
                ri = compiled.ring_index[e.src]
                el = carry.out_rings[ri]
                z = jnp.asarray(0, jnp.int32)
                # Uniform [ch] replay windows: ONE shape per edge (the
                # old first-chunk ch-1 variants doubled these compiles).
                # Both routing variants: fused lane (single failure) and
                # all-lane + select (connected-failure sharing).
                jobs.append(lambda eidx=eidx, el=el, z=z:
                            self._route_chunk_fn(eidx, ch)(
                                el, z, z, z, z, z))

                def _all_lane(eidx=eidx, el=el, z=z):
                    routed, *_ = self._route_chunk_fn(
                        eidx, ch, all_lanes=True)(el, z, z, z, z)
                    self._lane_select_fn(eidx, ch)(routed, z)
                jobs.append(_all_lane)
                if spill_paths:
                    # Spill-path twin (AVAILABILITY wrap recovery):
                    # doubles the exchange compiles, so opt-in — a
                    # ring-covered recovery (the common case) never
                    # takes this path.
                    jobs.append(lambda ri=ri, el=el, z=z:
                                self._ring_chunk_fn(ri, ch)(el, z))
                    jobs.append(lambda eidx=eidx, src_p=src_p,
                                src_cap=src_cap, z=z:
                                self._route_raw_fn(eidx, ch)(
                                    zero_batch((ch, src_p, src_cap)),
                                    z, z, z, z, z))
                    jobs.append(lambda eidx=eidx, src_p=src_p,
                                src_cap=src_cap, z=z:
                                self._route_raw_fn(
                                    eidx, ch, all_lanes=True)(
                                    zero_batch((ch, src_p, src_cap)),
                                    z, z, z, z))
                jobs.append(lambda eidx=eidx, e=e:
                            self._first_chunk_fn(eidx)(
                                zero_batch((1, e.capacity)),
                                zero_batch((ch, e.capacity))))

        def _vertex_jobs(vid: int) -> None:
            v = self.job.vertices[vid]
            in_edges = self.job.in_edges(vid)
            _edge_jobs(vid)
            # Replay block program(s).
            slot_keys = compiled.consumer_slot_keys(vid)
            subs = range(v.parallelism) if slot_keys is not None else [0]
            in_cap = (self.job.edges[in_edges[0]].capacity if in_edges
                      else compiled.vertex_out_capacity(vid))
            state0 = jax.tree_util.tree_map(
                lambda x: x[0][None], carry.op_states[vid])
            if isinstance(v.operator, TwoInputOperator):
                cap2 = self.job.edges[in_edges[1]].capacity
                chunk0 = (zero_batch((ch, in_cap)), zero_batch((ch, cap2)))
            else:
                chunk0 = zero_batch((ch, in_cap))

            def _replay_job(sub, state0=state0, chunk0=chunk0):
                rp = self._make_replayer(vid, sub)
                rp._jit_block(state0, chunk0, zero((ch,)), zero((ch,)),
                              jnp.asarray(sub, jnp.int32),
                              jnp.zeros((), jnp.int32))
                # tslice serves the pad-fixed stream length (the shape
                # every failure uses; see LogReplayer.pad_steps).
                rp._jit_tslice(zero((rp.pad_steps or ch,)),
                               jnp.asarray(0, jnp.int32))
            for sub in subs:
                jobs.append(lambda sub=sub: _replay_job(sub))

            heavy.append((vid, state0))

        for vid in vids:
            _vertex_jobs(vid)

        def _heavy_chain():
            # Donated-dummy programs (graft / kill / ring write) allocate
            # carry-scale buffers — running them concurrently multiplies
            # GB-scale dummies and OOMs the chip. ONE dummy carry is
            # threaded serially through every vertex's programs instead
            # (donation recycles it), bounding peak memory to a single
            # extra carry.
            dummy = jax.tree_util.tree_map(
                lambda x: jnp.zeros_like(x), carry)
            nrp = max(compiled.plan.num_replicas, 1)
            for vid, state0 in heavy:
                dummy = self._graft_fn(vid)(
                    dummy, state0, st, jnp.asarray(0, jnp.int32),
                    jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
                dummy = self._inject_fn(vid)(
                    dummy, jnp.asarray(0, jnp.int32),
                    jnp.asarray(0, jnp.int32),
                    jnp.full((nrp,), nrp, jnp.int32))
            rings = list(dummy.out_rings)
            for vid, _ in heavy:
                if vid not in compiled.ring_index:
                    continue
                ri = compiled.ring_index[vid]
                out_cap = compiled.vertex_out_capacity(vid)
                z = jnp.asarray(0, jnp.int32)
                rings[ri], _b = self._ring_write_fn(ri, ch)(
                    rings[ri], zero_batch((ch, out_cap)),
                    z, z, jnp.asarray(1, jnp.int32), z)
        jobs.append(_heavy_chain)

        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=4) as pool:
            for res in pool.map(lambda j: j(), jobs):
                pass
        # AOT-lower the standby's first-step (block) program into the
        # persistent compile cache too — sharded AND unsharded (both
        # namespaces; utils/compile_cache.py keeps them from colliding).
        # A rehydrated standby's first dispatch after restore is then a
        # cache hit, not the finalize-tail recompile BENCH_r05
        # attributes ~448 ms to; a failure to lower emits the
        # recovery.aot-lower-failed instant + counter so the cold
        # standby shows in `top` now, not at failover.
        from clonos_tpu.utils.compile_cache import aot_lower_first_step
        aot_lower_first_step(self.executor, self._mgroup)
        return _time.monotonic() - t0

    def failover_drill(self, flats: Optional[Sequence[int]] = None
                       ) -> float:
        """Rehearse a failover end-to-end and return its wall-clock
        seconds: inject a failure, run the full recovery protocol, and
        rely on bit-identical recovery to leave the job state canonically
        unchanged (executor.canonical_carry: live log/ring content equal;
        physically-dead pre-fence slots may differ — nothing ever reads
        them). The reference's RunStandbyTaskStrategy keeps standby
        executions *running* (Task.java:300-302, Execution.java:373-377),
        so their whole failure path is hot; compiling programs
        (prewarm_recovery) is necessary but not sufficient for that — the
        first execution still pays allocator growth, transfer-path and
        host-pool warmup (~4x on a tunneled backend). One drill moves all
        of it off the real failure path.

        Default drill set: one subtask of every vertex class, failed
        together (a connected multi-class failure exercises every class's
        replay program and the staged topological recovery)."""
        if self.failed:
            raise rec.RecoveryError("cannot drill with real failures "
                                    "pending")
        if not self.standbys.has_state():
            raise rec.RecoveryError(
                "failover_drill needs a completed checkpoint")
        t0 = _time.monotonic()
        fence = self._fence_step[self.standbys.latest.checkpoint_id + 1]
        if self.global_step == fence:
            import warnings
            warnings.warn(
                "failover_drill at an epoch fence replays zero steps; "
                "run it mid-epoch so the chunked replay path executes")
        if flats is None:
            flats = [self.job.subtask_base(v.vertex_id)
                     for v in self.job.vertices]
        flats = list(flats)
        # The drill must NEVER corrupt a healthy job: verify every drilled
        # log has a surviving replica holder BEFORE zeroing any device
        # state (recover() makes the same check, but only after the
        # injection has already destroyed the state it needs).
        if self.global_step > fence:
            fset = set(flats)
            for flat in flats:
                vid, _ = self._vertex_of(flat)
                if not self.job.out_edges(vid):
                    continue       # sinks synthesize; no holder needed
                if not any(o == flat and h not in fset
                           for (o, h) in self.plan.pairs):
                    raise rec.RecoveryError(
                        f"failover_drill: subtask {flat} would have no "
                        f"surviving determinant replica under drill set "
                        f"{sorted(fset)} — drill fewer subtasks at once "
                        f"or deepen sharing/replication")
            # Input reconstruction needs the whole replay window in the
            # upstream rings (or spill): check BEFORE zeroing state too.
            n_steps = self.global_step - fence
            if (n_steps > self.executor.compiled.inflight_ring_steps
                    and self.executor.spill_logs is None):
                raise rec.RecoveryError(
                    f"failover_drill: {n_steps} steps since the last "
                    f"completed checkpoint exceed the in-flight ring "
                    f"({self.executor.compiled.inflight_ring_steps} "
                    f"steps) and spill is disabled — drill earlier or "
                    f"enable spill")
        self.inject_failure(flats)
        self.recover(drill=True)
        return _time.monotonic() - t0

    def _rebuild_txn_shards(self, vid: int, sub: int,
                            result: rec.ReplayResult, from_epoch: int,
                            fence: int, n_steps: int) -> None:
        """Reconstruct the failed sink subtask's pending transaction
        shards from its replayed output chunks, epoch by epoch."""
        tl = self.txn_logs[vid]
        chunks = [jax.tree_util.tree_map(np.asarray, c)
                  for c in (result.out_chunks or [])]

        def steps_slice(lo: int, hi: int) -> np.ndarray:
            rows = []
            for i, c in enumerate(chunks):
                ch_n = c.keys.shape[0]
                base = i * self._chunk()
                a = max(lo, base)
                b = min(hi, base + ch_n)
                for s in range(a, b):
                    m = c.valid[s - base]
                    if m.any():
                        rows.append(np.stack(
                            [c.keys[s - base][m], c.values[s - base][m],
                             c.timestamps[s - base][m]], axis=1))
            return (np.concatenate(rows, axis=0) if rows
                    else np.zeros((0, 3), np.int32))

        cur = self.executor.epoch_id
        for e in range(from_epoch, cur + 1):
            if e not in self._fence_step:
                continue
            lo = self._fence_step[e] - fence
            hi = (self._fence_step.get(e + 1, fence + n_steps) - fence
                  if e < cur else n_steps)
            tl.rebuild_shard(e, sub, steps_slice(lo, min(hi, n_steps)))

    # --- input reconstruction ------------------------------------------------

    def _ring_steps(self, patched: JobCarry, src_vid: int, start: int,
                    n: int, need: Optional[int] = None):
        """Raw output steps [start, start+n) of a producer vertex, from the
        device ring — falling back to the host spill for steps the ring no
        longer retains (reference SpilledReplayIterator.java:61).

        ``need``: how many leading steps must actually be present
        (default n). With need < n the returned [n]-shaped batch may hold
        dead entries past ``need`` — chunked replay reads fixed-size
        [CH] windows whose tail can extend past the ring head."""
        if need is None:
            need = n
        compiled = self.executor.compiled
        ri = compiled.ring_index[src_vid]
        el = patched.out_rings[ri]
        # Coverage math from the bounds cache (one read per recover();
        # ring offsets are stable across recovery — write-backs replace
        # contents only), so the fast path costs zero host round-trips.
        if getattr(self, "_bounds_cache", None) and ri in self._bounds_cache:
            tail, head = self._bounds_cache[ri]
        else:
            tail, head = int(el.tail), int(el.head)
        got_start = max(start, tail)
        cnt = max(min(head - got_start, n), 0)
        # Steps physically retained by the ring: slice_steps only clamps to
        # ``tail``, but when checkpoints stall past ring capacity newer
        # appends have clobbered positions of steps < head - ring_steps —
        # those must come from the spill even though tail hasn't advanced.
        ring_lo = max(tail, head - el.ring_steps)
        batch, _, _ = self._ring_chunk_fn(ri, n)(
            el, jnp.asarray(start, jnp.int32))
        if got_start == start and start >= ring_lo and cnt >= need:
            return batch
        # Ring shortfall: pull the missing leading steps from the spill.
        if self.executor.spill_logs is None:
            raise rec.RecoveryError(
                f"in-flight log of vertex {src_vid} lost steps "
                f"[{start}, {max(got_start, ring_lo)}) and spill is disabled")
        spill = self.executor.spill_logs[ri]
        boundary = min(start + n, max(got_start, ring_lo))
        required_end = min(start + need, boundary)
        parts = []
        have = start
        # Prefetching epoch reads (reference SpilledReplayIterator.java:61
        # — async reads run ahead of consumption).
        eps = spill.retained_epochs()
        if eps:
            it = ifl.ReplayIterator(spill, eps[0], eps[-1])
            try:
                for ep_start, ep_batch in it.epochs():
                    ep_n = ep_batch.keys.shape[0]
                    lo = max(have, ep_start)
                    hi = min(ep_start + ep_n, boundary)
                    if hi > lo:
                        parts.append(jax.tree_util.tree_map(
                            lambda x: x[lo - ep_start: hi - ep_start],
                            ep_batch))
                        have = hi
                    if have >= boundary:
                        break
            except (SegmentCorruptError, StorageError) as e:
                # Torn/corrupt/missing segment on refill: surface as a
                # labeled recovery failure, never as garbage replay bytes
                # (satellite: spill-file durability).
                raise rec.RecoveryError(
                    f"vertex {src_vid}: tiered refill failed — {e}") from e
            finally:
                it.close()
        if have < required_end:
            raise rec.RecoveryError(
                f"vertex {src_vid}: spill does not cover steps "
                f"[{have}, {required_end})")
        if have < boundary:
            # Dead filler past the needed range (fixed-shape chunk reads).
            ref = parts[0] if parts else batch
            parts.append(jax.tree_util.tree_map(
                lambda x: jnp.zeros((boundary - have,) + x.shape[1:],
                                    x.dtype), ref))
        if boundary < start + n:
            parts.append(jax.tree_util.tree_map(
                lambda x: x[boundary - got_start: start + n - got_start],
                batch))
        out = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        if out.keys.shape[0] != n:
            raise rec.RecoveryError(
                f"vertex {src_vid}: reconstructed {out.keys.shape[0]} of "
                f"{n} in-flight steps")
        return out

    def _replay_inputs(self, patched: JobCarry, snap: LeanSnapshot,
                       eidx: int, sub: int, fence: int, n_steps: int):
        """The failed consumer's lost inputs on edge ``eidx``: the
        checkpointed depth-1 edge buffer (its input at the first lost step)
        followed by the upstream's ring outputs [fence, fence+n-1), routed
        through the deterministic exchange.

        Returns a LIST of block-sized chunks ([CH, cap] each; the last
        covers the tail) so every device program here is fixed-shape and
        prewarm-compiled — recovery pays no XLA compile (warm standby)."""
        e = self.job.edges[eidx]
        ch = self._chunk()
        compiled = self.executor.compiled
        ri = compiled.ring_index[e.src]
        first = jax.tree_util.tree_map(
            lambda x: x[sub][None], snap.edge_bufs[eidx])
        if n_steps <= 0:
            return []
        el = patched.out_rings[ri]
        if self._bounds_cache and ri in self._bounds_cache:
            tail, head = self._bounds_cache[ri]
        else:
            tail, head = int(el.tail), int(el.head)
        ring_lo = max(tail, head - el.ring_steps)
        # Uniform [ch] windows: window i covers absolute steps
        # [fence-1+i*ch, fence-1+(i+1)*ch). Window slot j (global) holds
        # step fence-1+j; slot 0 is dead (pre-fence) — masked by ``lead``
        # and replaced with the checkpointed edge buffer. One compiled
        # program per edge serves every chunk (prewarm halved vs the old
        # first-chunk (ch-1) shape variants). Loop state lives ON DEVICE
        # (a host scalar put per chunk costs a tunnel round-trip);
        # coverage decisions use the host bounds.
        start_d = jnp.asarray(fence - 1, jnp.int32)
        sub_d = jnp.asarray(sub, jnp.int32)
        rr_d = jnp.asarray(snap.rr_offsets[eidx][0], jnp.int32)
        need_d = jnp.asarray(n_steps, jnp.int32)
        lead_d = jnp.asarray(1, jnp.int32)
        chunks = []
        nblocks = -(-n_steps // ch)
        for i in range(nblocks):
            h_start = fence - 1 + i * ch
            # Real ring steps this window must provide (its live slots).
            lo_real = max(h_start, fence)
            hi_real = min(h_start + ch, fence - 1 + n_steps)
            h_need = max(hi_real - lo_real, 0)
            covered = (lo_real >= ring_lo and lo_real >= tail
                       and head - lo_real >= h_need)
            share = self._route_cache_enabled

            def raw_window():
                # Spill-backed window, shaped like the ring window: pull
                # the real steps from ring+spill and shift window 0 down
                # one slot (its dead leading slot carries no step).
                raw = self._ring_steps(patched, e.src, lo_real, ch,
                                       need=h_need)
                if i == 0:
                    raw = jax.tree_util.tree_map(
                        lambda x: jnp.roll(x, 1, axis=0).at[0].set(
                            jnp.zeros_like(x[0])), raw)
                return raw

            if not share:
                # Single failed consumer: the fused variant scatters only
                # this lane's rows (~P times cheaper than materializing
                # the whole routed block).
                if covered:
                    lane, start_d, rr_d, need_d, lead_d = \
                        self._route_chunk_fn(eidx, ch)(
                            el, start_d, sub_d, rr_d, need_d, lead_d)
                else:
                    lane, start_d, rr_d, need_d, lead_d = \
                        self._route_raw_fn(eidx, ch)(
                            raw_window(), start_d, sub_d, rr_d, need_d,
                            lead_d)
            else:
                # Multiple failed consumers: route the window once to all
                # lanes, cache it, and lane-select per consumer
                # (recover() scopes the cache to one vertex's group).
                key = (eidx, i)
                cached = self._route_cache.get(key)
                if cached is None:
                    if covered:
                        routed, start_d, rr_d, need_d, lead_d = \
                            self._route_chunk_fn(eidx, ch, all_lanes=True)(
                                el, start_d, rr_d, need_d, lead_d)
                    else:
                        routed, start_d, rr_d, need_d, lead_d = \
                            self._route_raw_fn(eidx, ch, all_lanes=True)(
                                raw_window(), start_d, rr_d, need_d,
                                lead_d)
                    self._route_cache[key] = routed
                else:
                    routed = cached
                    self._route_cache_hits += 1
                lane = self._lane_select_fn(eidx, ch)(routed, sub_d)
            if i == 0:
                chunks.append(self._first_chunk_fn(eidx)(first, lane))
            else:
                chunks.append(lane)
        return chunks

    def _reread_feed(self, vid: int, sub: int, snap: LeanSnapshot,
                     rows: np.ndarray, n_steps: int):
        """Rebuild a HostFeedSource's lost input batches: offset from the
        checkpointed operator state, per-step pull counts from the recorded
        BUFFER_BUILT determinants, records from the rewindable reader.
        Returns block-sized chunks (zero-padded tail) like
        :meth:`_replay_inputs`."""
        reader = self.executor.feed_readers.get(vid)
        if reader is None:
            raise rec.RecoveryError(
                f"vertex {vid}: HostFeedSource has no registered feed "
                f"reader to re-read from")
        v = self.job.vertices[vid]
        b = v.operator.batch_size
        anchors = det.sync_anchors(rows)[:n_steps]
        counts = rows[anchors + 3, det.LANE_P].astype(np.int64)
        offset = int(np.asarray(snap.op_states[vid]["offset"][sub]))
        ch = self._chunk()
        padded = -(-n_steps // ch) * ch
        keys = np.zeros((padded, b), np.int32)
        vals = np.zeros((padded, b), np.int32)
        valid = np.zeros((padded, b), bool)
        for i, c in enumerate(counts):
            ks, vs = reader.read_at(sub, offset, int(c))
            keys[i, :int(c)], vals[i, :int(c)] = ks, vs
            valid[i, :int(c)] = True
            offset += int(c)
        from clonos_tpu.api.records import RecordBatch as RB
        zts = np.zeros((padded, b), np.int32)
        return [RB(jnp.asarray(keys[lo:lo + ch]),
                   jnp.asarray(vals[lo:lo + ch]),
                   jnp.asarray(zts[lo:lo + ch]),
                   jnp.asarray(valid[lo:lo + ch]))
                for lo in range(0, padded, ch)]

    def _synthesize_det_rows(self, fence_global: int,
                             n_steps: int) -> np.ndarray:
        """Rebuild a sink's per-step determinant rows from the executor's
        step-input ledger (times/rng draws for the lost steps). BUFFER_BUILT
        payloads are placeholders — the replayer fills real emit counts into
        the rebuilt rows."""
        hist = self.executor.step_input_history[fence_global:
                                                fence_global + n_steps]
        if len(hist) < n_steps:
            raise rec.RecoveryError("step-input ledger shorter than the "
                                    "lost step range")
        rows = np.zeros((n_steps * DETS_PER_STEP, det.NUM_LANES), np.int32)
        for i, (t, r) in enumerate(hist):
            base = i * DETS_PER_STEP
            rows[base, det.LANE_TAG] = det.TIMESTAMP
            rows[base, det.LANE_P] = -1 if t < 0 else 0
            rows[base, det.LANE_P + 1] = t
            rows[base + 1, det.LANE_TAG] = det.RNG
            rows[base + 1, det.LANE_P] = r
            rows[base + 2, det.LANE_TAG] = det.ORDER
            rows[base + 3, det.LANE_TAG] = det.BUFFER_BUILT
        return rows

    def _make_replayer(self, vid: int, sub: int) -> rec.LogReplayer:
        """Standby replay program for (vertex, subtask); compiled programs
        are cached on the operator so repeated failures (and prewarm)
        share them."""
        v = self.job.vertices[vid]
        slot_keys = self.executor.compiled.consumer_slot_keys(vid)
        return rec.LogReplayer(
            v.operator, v.parallelism,
            block_steps=self._recovery_ch,
            in_slot_keys=(slot_keys[sub:sub + 1]
                          if slot_keys is not None else None),
            pad_steps=self.executor.compiled.inflight_ring_steps)

    def _log_restore_fn(self):
        cap = self.executor.compiled.log_capacity

        def make():
            def f(rows_chunk, count, state):
                return clog.append(state, rows_chunk, count)
            return f
        return self._jitted(("log_append",), make)

    def _log_restore_from_replica_fn(self):
        """Rebuild a failed task's log row ON DEVICE from a surviving
        replica: the replayed determinant stream was verified equal to the
        recovered one, so the replica's bytes ARE the restored log — no
        host round-trip of the rows."""
        cap = self.executor.compiled.log_capacity
        me = self.executor.compiled.max_epochs

        def make():
            def f(replicas, r, from_epoch, used, ck_head,
                  epoch_offs, epoch_mask, latest, base):
                rep_one = jax.tree_util.tree_map(lambda x: x[r], replicas)
                buf, _cnt, _start = clog.get_determinants(
                    rep_one, from_epoch, cap)
                st = clog.create(cap, me)
                st = st._replace(head=ck_head, tail=ck_head)
                st = clog.append(st, buf, used)
                return st._replace(
                    epoch_starts=jnp.where(epoch_mask, epoch_offs,
                                           st.epoch_starts),
                    latest_epoch=jnp.maximum(st.latest_epoch, latest),
                    epoch_base=jnp.maximum(st.epoch_base, base))
            return f
        return self._jitted(("log_restore_replica",), make)

    def _log_finalize_fn(self):
        def make():
            def f(state, epoch_offs, epoch_mask, latest, base):
                starts = jnp.where(epoch_mask, epoch_offs,
                                   state.epoch_starts)
                return state._replace(
                    epoch_starts=starts,
                    latest_epoch=jnp.maximum(state.latest_epoch, latest),
                    epoch_base=jnp.maximum(state.epoch_base, base))
            return f
        return self._jitted(("log_finalize",), make)

    def _graft_fn(self, vid: int):
        def make():
            def f(carry, new_state, restored_log, sub, flat, rc):
                ops = list(carry.op_states)
                ops[vid] = jax.tree_util.tree_map(
                    lambda live_x, new_x: live_x.at[sub].set(new_x[0]),
                    ops[vid], new_state)
                logs = jax.tree_util.tree_map(
                    lambda s, r: s.at[flat].set(r), carry.logs,
                    restored_log)
                return carry._replace(
                    op_states=tuple(ops), logs=logs,
                    record_counts=carry.record_counts.at[flat].set(rc))
            return f
        # Donated: an un-donated graft copies the whole multi-GB carry
        # (rings included) per failed subtask, thrashing the allocator.
        return self._jitted(("graft", vid), make, donate=(0,))

    def _ring_write_fn(self, ri: int, m: int):
        """Write an [m, cap] replayed output chunk into ring ``ri`` at
        steps [base, base+m), keeping only steps in [keep_from, hi);
        returns (ring, base + m) so the loop cursor stays on device."""
        def make():
            def f(el, chunk, base, sub, keep_from, hi):
                steps = base + jnp.arange(m, dtype=jnp.int32)
                keep = (steps >= keep_from) & (steps < hi)
                pos = jnp.where(keep, steps & (el.ring_steps - 1),
                                el.ring_steps)        # OOB row -> dropped
                return el._replace(
                    keys=el.keys.at[pos, sub].set(chunk.keys, mode="drop"),
                    values=el.values.at[pos, sub].set(chunk.values,
                                                      mode="drop"),
                    timestamps=el.timestamps.at[pos, sub].set(
                        chunk.timestamps, mode="drop"),
                    valid=el.valid.at[pos, sub].set(chunk.valid,
                                                    mode="drop")), base + m
            return f
        return self._jitted(("ring_write", ri, m), make, donate=(0,))

    def _patch(self, carry: JobCarry, snap: LeanSnapshot, vid: int,
               sub: int, flat: int, result: rec.ReplayResult,
               det_rows: np.ndarray, from_epoch: int, fence: int,
               n_steps: int, replica_src: Optional[int] = None,
               det_n: Optional[int] = None, clean_sync: bool = False,
               ck_head: Optional[int] = None) -> JobCarry:
        """Graft the rebuilt subtask back into the live carry. Every
        device program here is fixed-shape (chunked appends/writes) so a
        prewarmed standby pays zero XLA compile on the failure path.

        ``clean_sync`` (device-resident determinant stream): the rows
        never came to the host, but the stream is pure k-row sync blocks
        so the anchors are exactly ``i * DETS_PER_STEP``; ``det_n`` is
        its device-verified row count."""
        compiled = self.executor.compiled
        ch4 = self._chunk() * DETS_PER_STEP
        if ck_head is None:
            ck_head = int(np.asarray(snap.log_heads[flat]))
        n = det_rows.shape[0] if det_n is None else det_n
        # Epoch->offset index entries died with the task; rebuild them from
        # the fence-step ledger. Sync blocks anchor at TIMESTAMP rows.
        if clean_sync:
            ts_pos = np.arange(n // DETS_PER_STEP,
                               dtype=np.int64) * DETS_PER_STEP
        elif n > 0:
            ts_pos = det.sync_anchors(det_rows)
        else:
            ts_pos = np.zeros((0,), np.int64)
        me = compiled.max_epochs
        epoch_offs = np.zeros((me,), np.int32)
        epoch_mask = np.zeros((me,), bool)
        latest = 0
        for e in range(from_epoch, self.executor.epoch_id + 1):
            if e in self._fence_step:
                step_i = self._fence_step[e] - fence
                # from_epoch starts exactly at the checkpointed head (async
                # rows appended in the roll gap come after the fence);
                # later fences anchor at their first step's TIMESTAMP row
                # minus the roll-gap ledger — async rows appended after
                # the roll but before the epoch's first step (fence
                # SOURCE_CHECKPOINTs, ignore broadcasts, between-epoch
                # service calls) precede that anchor yet belong to the
                # NEW epoch (executor.roll_gap_async).
                gap = self.executor.roll_gap_async.get((flat, e), 0)
                if step_i == 0:
                    off = ck_head
                elif step_i < len(ts_pos):
                    off = ck_head + int(ts_pos[step_i]) - gap
                else:
                    off = ck_head + n - gap
                epoch_offs[e % me] = off
                epoch_mask[e % me] = True
                latest = max(latest, e)
        if replica_src is not None:
            # The replayed stream was verified equal to the recovered one,
            # so the replica's device bytes ARE the restored log (no h2d).
            restored = self._log_restore_from_replica_fn()(
                carry.replicas, jnp.asarray(replica_src, jnp.int32),
                jnp.asarray(from_epoch, jnp.int32),
                jnp.asarray(n, jnp.int32), jnp.asarray(ck_head, jnp.int32),
                jnp.asarray(epoch_offs), jnp.asarray(epoch_mask),
                jnp.asarray(latest, jnp.int32),
                jnp.asarray(from_epoch, jnp.int32))
        else:
            # Synthesized streams (sink recovery) upload in fixed chunks.
            restored = clog.create(compiled.log_capacity,
                                   compiled.max_epochs)
            base = jnp.asarray(ck_head, jnp.int32)
            restored = restored._replace(head=base, tail=base)
            app = self._log_restore_fn()
            for lo in range(0, n, ch4):
                cnt = min(ch4, n - lo)
                chunk = np.zeros((ch4, det.NUM_LANES), np.int32)
                chunk[:cnt] = det_rows[lo:lo + cnt]
                restored = app(jnp.asarray(chunk),
                               jnp.asarray(cnt, jnp.int32), restored)
            restored = self._log_finalize_fn()(
                restored, jnp.asarray(epoch_offs), jnp.asarray(epoch_mask),
                jnp.asarray(latest, jnp.int32),
                jnp.asarray(from_epoch, jnp.int32))
        # Operator state slice + log row + record count in one program.
        # Deferred replays keep the consumed total on device — the add
        # happens there and the host never waits for it.
        rc = snap.record_counts[flat] + (
            result.consumed_d if result.deferred
            else result.records_replayed)
        carry = self._graft_fn(vid)(
            carry, result.op_state, restored,
            jnp.asarray(sub, jnp.int32), jnp.asarray(flat, jnp.int32), rc)
        # In-flight ring shard reconstruction: write the replayed outputs
        # back into the producer's ring at their original step offsets
        # (reference buildAndLogBuffer — the standby re-cuts identical
        # buffers and re-logs them so downstream recoveries can be
        # served). Only the last ring_steps replayed steps fit; earlier
        # chunks are masked out (spill-backed replays longer than the
        # ring must not wrap into newer steps).
        rings = list(carry.out_rings)
        if vid in compiled.ring_index and result.out_chunks is not None \
                and n_steps > 0:
            ri = compiled.ring_index[vid]
            el = rings[ri]
            keep_from = jnp.asarray(fence + n_steps
                                    - min(n_steps, el.ring_steps),
                                    jnp.int32)
            hi = jnp.asarray(fence + n_steps, jnp.int32)
            sub_j = jnp.asarray(sub, jnp.int32)
            ch = self._chunk()
            base_d = None
            for i, chunk in enumerate(result.out_chunks):
                m = chunk.keys.shape[0]
                base_i = fence + i * ch
                if base_i + m <= fence + n_steps - min(n_steps,
                                                       el.ring_steps):
                    continue      # wholly before the retained window
                if base_d is None:
                    base_d = jnp.asarray(base_i, jnp.int32)
                el, base_d = self._ring_write_fn(ri, m)(
                    el, chunk, base_d, sub_j, keep_from, hi)
            rings[ri] = el
        return carry._replace(out_rings=tuple(rings))
