"""Cluster runner: failure detection, standby management, causal recovery.

This is the control-plane layer tying the executor, checkpoint coordinator,
replication plan, and recovery FSM together — capability parity with the
reference's JobMaster-side machinery:

- ``HeartbeatMonitor``   <-  runtime/heartbeat (JobMaster.java:258-266)
- ``StandbyPool``        <-  ExecutionVertex.addStandbyExecution /
                             CheckpointCoordinator state dispatch (:1226)
- ``ClusterRunner``      <-  RunStandbyTaskStrategy.onTaskFailure
                             (failover/RunStandbyTaskStrategy.java:85):
                             remove failed, ignore unacked checkpoints,
                             back off the checkpoint interval, run the
                             standby through the recovery FSM (§3.4)

Failure model (TPU deployment semantics): the unit of loss is a subtask's
device-resident state — its operator-state slice, its thread causal log
row, the replica rows it holds for others, AND its shard of its vertex's
in-flight output ring (the producer's subpartition log dies with the
producer, exactly the reference's PipelinedSubpartition ownership).
Recovery rebuilds the lost ring shard from the replayed operator's
re-emitted batches — reconstruction, not just verification (reference
buildAndLogBuffer, PipelinedSubpartition.java:536-599).

"Local recovery instead of global rollback" (README.md:13-20): healthy
subtasks are never rolled back — the failed subtask alone is rebuilt from
the last checkpoint plus determinant replay, then patched into the live
carry. The proof obligation (and the test): the patched carry is
bit-identical to a never-failed run on the canonical (logically-live)
state — executor.canonical_carry.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import recovery as rec
from clonos_tpu.causal import replication as rep
from clonos_tpu.graph.job_graph import JobGraph, PartitionType
from clonos_tpu.inflight import log as ifl
from clonos_tpu.parallel import routing
from clonos_tpu.runtime import checkpoint as cp
from clonos_tpu.runtime.executor import (DETS_PER_STEP, JobCarry,
                                         LeanSnapshot, LocalExecutor)


class HeartbeatMonitor:
    """Deadline-based liveness tracking (reference runtime/heartbeat)."""

    def __init__(self, subtasks: Sequence[int], timeout_s: float = 5.0,
                 clock=_time.monotonic):
        self._clock = clock
        self.timeout_s = timeout_s
        self._last: Dict[int, float] = {s: clock() for s in subtasks}
        self._dead: Set[int] = set()

    def beat(self, subtask: int) -> None:
        if subtask not in self._dead:
            self._last[subtask] = self._clock()

    def beat_all_except(self, dead: Set[int]) -> None:
        now = self._clock()
        for s in self._last:
            if s not in dead and s not in self._dead:
                self._last[s] = now

    def mark_dead(self, subtask: int) -> None:
        self._dead.add(subtask)

    def expired(self) -> List[int]:
        now = self._clock()
        out = [s for s, t in self._last.items()
               if s not in self._dead and now - t > self.timeout_s]
        return sorted(out)

    def revive(self, subtask: int) -> None:
        self._dead.discard(subtask)
        self._last[subtask] = self._clock()


class StandbyPool:
    """Holds the state standbys restore from: the latest completed
    checkpoint, refreshed on every completion (the reference re-dispatches
    state to STANDBY executions on each checkpoint, Execution.java:373)."""

    def __init__(self, num_standby_per_vertex: int = 1):
        self.num_standby_per_vertex = num_standby_per_vertex
        self.latest: Optional[cp.CompletedCheckpoint] = None
        self.dispatch_count = 0

    def on_completed_checkpoint(self, ckpt: cp.CompletedCheckpoint) -> None:
        self.latest = ckpt
        self.dispatch_count += 1

    def has_state(self) -> bool:
        return self.latest is not None


@dataclasses.dataclass
class RecoveryReport:
    """What one failure's recovery did (metrics + test surface)."""

    failed_subtasks: Tuple[int, ...]
    from_epoch: int
    steps_replayed: int
    determinants_replayed: int
    records_replayed: int
    ignored_checkpoints: Tuple[int, ...]
    recovery_ms: float
    managers: Tuple[rec.RecoveryManager, ...]
    #: wall-clock per recovery phase (fetch_determinants / inputs / replay /
    #: patch / replica_rebuild) — the cold-recovery cost breakdown.
    phase_ms: Dict[str, float] = dataclasses.field(default_factory=dict)


class OverflowError_(RuntimeError):
    """An un-checkpointed log/ring overflow was detected — the state is no
    longer recoverable and the control plane must not keep running."""


class ClusterRunner:
    """Single-process cluster (MiniCluster analog) with failure injection.

    Drives epochs; at every epoch fence triggers a checkpoint, collects
    acks from healthy subtasks, and on completion truncates logs and
    refreshes standbys."""

    def __init__(self, job: JobGraph, steps_per_epoch: int = 8,
                 num_standby: int = 1, heartbeat_timeout_s: float = 5.0,
                 checkpoint_dir: Optional[str] = None, **executor_kw):
        self.job = job
        self.executor = LocalExecutor(job, steps_per_epoch=steps_per_epoch,
                                      **executor_kw)
        storage = (cp.FileCheckpointStorage(checkpoint_dir)
                   if checkpoint_dir else cp.InMemoryCheckpointStorage())
        self.coordinator = cp.CheckpointCoordinator(
            storage, num_subtasks=job.total_subtasks(),
            base_interval_steps=steps_per_epoch)
        self.standbys = StandbyPool(num_standby)
        self.coordinator.subscribe_completed_state(
            self.standbys.on_completed_checkpoint)
        self.coordinator.subscribe_completion(
            self.executor.notify_checkpoint_complete)
        self.heartbeats = HeartbeatMonitor(
            range(job.total_subtasks()), timeout_s=heartbeat_timeout_s)
        self.failed: Set[int] = set()
        self.global_step = 0
        self._fence_step: Dict[int, int] = {}   # epoch -> global step at start
        self._fence_step[0] = 0
        self.plan = self.executor.compiled.plan
        self.reports: List[RecoveryReport] = []
        # Observability (reference MetricRegistryImpl + Clonos determinant
        # watchdog; see utils/metrics.py).
        from clonos_tpu.utils import metrics as met
        self.metrics = met.MetricRegistry()
        g = self.metrics.group(f"job.{job.name}")
        self._m_steps = g.counter("supersteps")
        self._m_records = g.meter("records-per-sec")
        self._m_epochs = g.counter("epochs")
        self._m_ckpt_bytes = g.gauge(
            "checkpoint.latest-bytes",
            lambda: (self.standbys.latest.size_bytes
                     if self.standbys.latest else 0))
        self._m_recovery_ms = g.histogram("recovery.duration-ms")
        self._m_recovered_records = g.counter("recovery.records-replayed")
        self.watchdog = met.LogOccupancyWatchdog(self.executor, g)

    # --- steady state --------------------------------------------------------

    def run_epoch(self, complete_checkpoint: bool = True) -> None:
        """Run to the next epoch fence and trigger its checkpoint.

        ``complete_checkpoint=False`` leaves the checkpoint pending (no
        acks): logs keep accumulating across epochs — the large-checkpoint-
        interval regime the spillable in-flight log exists for, and the
        setup for multi-epoch recovery gaps."""
        if self.failed:
            raise rec.RecoveryError(
                f"cannot run with failed subtasks {sorted(self.failed)}; "
                f"call recover() first")
        closed = self.executor.epoch_id
        n = self.executor.steps_per_epoch - self.executor.step_in_epoch
        rc_before = int(np.sum(np.asarray(
            self.executor.carry.record_counts)))
        self.executor.run_epoch()
        self.global_step += n
        self._fence_step[self.executor.epoch_id] = self.global_step
        self.heartbeats.beat_all_except(self.failed)
        self._m_steps.inc(n)
        self._m_epochs.inc()
        self._m_records.mark(int(np.sum(np.asarray(
            self.executor.carry.record_counts))) - rc_before)
        # Overflow guards at every roll: an un-truncated ring that wrapped
        # has silently clobbered recovery state — fail loudly, never limp.
        violations = self.executor.check_overflow()
        if violations:
            raise OverflowError_("; ".join(violations))
        # Checkpoint at the fence: the lean fence snapshot (op state +
        # offsets; logs/rings are truncated on completion, not persisted).
        self.coordinator.trigger(closed, self.executor.lean_snapshot(),
                                 async_write=False)
        if complete_checkpoint:
            self.coordinator.ack_all(closed)

    def step(self) -> None:
        if self.failed:
            raise rec.RecoveryError("failed subtasks present; recover() first")
        self.executor.step()
        self.global_step += 1
        self._m_steps.inc()
        self.heartbeats.beat_all_except(self.failed)

    # --- failure injection ---------------------------------------------------

    def inject_failure(self, flat_subtasks: Sequence[int]) -> None:
        """Kill subtasks: zero their device state — operator slice, causal
        log row, held replica rows, and their shard of the vertex's
        in-flight output ring (the producer's subpartition log dies with
        the producer). (Fault-injection API the reference delegates to
        Jepsen, flink-jepsen/.)"""
        carry = self.executor.carry
        compiled = self.executor.compiled
        for flat in flat_subtasks:
            self.failed.add(flat)
            self.heartbeats.mark_dead(flat)
            vid, sub = self._vertex_of(flat)
            # Operator state slice -> zeros.
            op = carry.op_states[vid]
            op = jax.tree_util.tree_map(
                lambda x: x.at[sub].set(jnp.zeros_like(x[sub])), op)
            ops = list(carry.op_states)
            ops[vid] = op
            # Causal log row -> fresh.
            fresh = clog.create(compiled.log_capacity, compiled.max_epochs)
            logs = jax.tree_util.tree_map(
                lambda s, f: s.at[flat].set(f), carry.logs, fresh)
            # Replica rows held by the dead subtask -> fresh.
            replicas = carry.replicas
            for r in self.plan.replicas_held_by(flat):
                replicas = jax.tree_util.tree_map(
                    lambda s, f: s.at[r].set(f), replicas, fresh)
            # The producer's in-flight ring shard -> zeros (content only;
            # offsets are vertex-uniform and survive on the control plane).
            rings = list(carry.out_rings)
            if vid in compiled.ring_index:
                ri = compiled.ring_index[vid]
                el = rings[ri]
                rings[ri] = el._replace(
                    keys=el.keys.at[:, sub].set(0),
                    values=el.values.at[:, sub].set(0),
                    timestamps=el.timestamps.at[:, sub].set(0),
                    valid=el.valid.at[:, sub].set(False))
            carry = carry._replace(
                op_states=tuple(ops), logs=logs, replicas=replicas,
                out_rings=tuple(rings),
                record_counts=carry.record_counts.at[flat].set(0))
        self.executor.carry = carry

    def _vertex_of(self, flat: int) -> Tuple[int, int]:
        for v in self.job.vertices:
            base = self.job.subtask_base(v.vertex_id)
            if base <= flat < base + v.parallelism:
                return v.vertex_id, flat - base
        raise ValueError(f"no subtask {flat}")

    # --- recovery (reference §3.4 signature path) ----------------------------

    def detect_failures(self) -> List[int]:
        return self.heartbeats.expired()

    def recover(self) -> RecoveryReport:
        """Run the full causal-recovery protocol for all failed subtasks,
        in topological order (an upstream's reconstructed ring shard feeds
        its downstream's replay — the reference's staged
        WaitingConnections/in-flight-request ordering)."""
        if not self.failed:
            raise rec.RecoveryError("no failed subtasks")
        if not self.standbys.has_state():
            raise rec.RecoveryError(
                "no completed checkpoint to restore standbys from")
        t0 = _time.monotonic()
        topo_pos = {vid: i for i, vid in
                    enumerate(self.executor.compiled.topo)}
        failed = tuple(sorted(
            self.failed, key=lambda f: (topo_pos[self._vertex_of(f)[0]], f)))

        # (1) RunStandbyTaskStrategy.onTaskFailure: ignore checkpoints the
        # dead tasks never acked; back off the checkpoint interval.
        ignored = tuple(self.coordinator.ignore_unacked_for(set(failed)))
        self.coordinator.backoff()

        ckpt = self.standbys.latest
        from_epoch = ckpt.checkpoint_id + 1
        fence = self._fence_step[from_epoch]
        n_steps = self.global_step - fence
        snap: LeanSnapshot = jax.tree_util.tree_map(jnp.asarray, ckpt.carry)
        managers: List[rec.RecoveryManager] = []
        total_dets = 0
        total_records = 0
        phases: Dict[str, float] = {}

        def _clock(name: str, since: float) -> float:
            now = _time.monotonic()
            phases[name] = phases.get(name, 0.0) + (now - since) * 1e3
            return now

        patched = self.executor.carry
        tp = _clock("restore", t0)

        for flat in failed:
            vid, sub = self._vertex_of(flat)
            v = self.job.vertices[vid]
            mgr = rec.RecoveryManager(
                vid, sub, flat,
                rec.LogReplayer(v.operator, v.parallelism,
                                block_steps=self.executor.block_steps))
            managers.append(mgr)
            in_edges = self.job.in_edges(vid)
            out_edges = self.job.out_edges(vid)

            # FSM: standby -> connections re-established + state restored.
            mgr.notify_start_recovery(in_edges, out_edges)
            mgr.notify_state_restoration_complete()
            for e in in_edges:
                mgr.notify_new_input_channel(e)
            for e in out_edges:
                mgr.notify_new_output_channel(e)

            # DeterminantRequest flood to surviving holders of this log.
            holders = [
                (r, h) for r, (o, h) in enumerate(self.plan.pairs)
                if o == flat and h not in self.failed]
            synthesized = False
            if not holders and n_steps > 0:
                if out_edges:
                    raise rec.RecoveryError(
                        f"subtask {flat}: no surviving replica holds its "
                        f"determinant log (sharing depth / replication "
                        f"factor too shallow for this failure pattern)")
                # Pure sink: nobody downstream replicates its log. Its
                # inputs replay exactly from the upstream ring; its own
                # nondeterminism (time/rng step inputs) is re-synthesized
                # from the coordinator's input ledger. (The reference has
                # the same boundary: sink exactly-once needs transactional
                # sinks, TwoPhaseCommitSinkFunction.)
                synthesized = True
            mgr.expect_determinant_responses(len(holders))
            for r, _h in holders:
                one = jax.tree_util.tree_map(lambda x: x[r], patched.replicas)
                buf, count, start = clog.get_determinants(
                    one, from_epoch, max_out=self._det_request_max())
                mgr.notify_determinant_response(
                    np.asarray(buf)[: int(count)], int(start))
            if synthesized:
                rows = self._synthesize_det_rows(fence, n_steps)
                start = int(np.asarray(snap.log_heads[flat]))
            else:
                rows, start = mgr.merged_determinants()
            total_dets += len(rows)
            tp = _clock("fetch_determinants", tp)

            # Lost inputs: the checkpointed edge buffer (the depth-1 batch
            # spanning the fence) + the upstream rings' raw outputs,
            # re-routed through the deterministic exchange. Upstream ring
            # shards zeroed by a connected failure were rebuilt earlier in
            # this loop (topological order).
            from clonos_tpu.api.operators import (HostFeedSource,
                                                  TwoInputOperator)
            input_steps = None
            if isinstance(v.operator, TwoInputOperator):
                input_steps = (
                    self._replay_inputs(patched, snap, in_edges[0], sub,
                                        fence, n_steps),
                    self._replay_inputs(patched, snap, in_edges[1], sub,
                                        fence, n_steps))
            elif in_edges:
                input_steps = self._replay_inputs(patched, snap, in_edges[0],
                                                  sub, fence, n_steps)
            elif isinstance(v.operator, HostFeedSource) and n_steps > 0:
                input_steps = self._reread_feed(vid, sub, snap, rows, n_steps)
            if input_steps is not None:
                jax.block_until_ready(input_steps)
            tp = _clock("inputs", tp)

            plan = rec.ReplayPlan(
                vertex_id=vid, subtask=sub, flat_subtask=flat,
                from_epoch=from_epoch, input_steps=input_steps,
                det_rows=rows, det_start=start,
                checkpoint_op_state=snap.op_states[vid],
                n_steps=n_steps, verify_outputs=not synthesized)
            result = mgr.run_replay(plan)
            total_records += result.records_replayed
            tp = _clock("replay", tp)

            rebuilt = np.asarray(result.rebuilt_log_rows)
            # The regenerated determinant rows must equal the recovered ones
            # (bit-identical replay; reference post-replay log asserts).
            if not synthesized and not np.array_equal(
                    rebuilt, rows[: rebuilt.shape[0]]):
                raise rec.RecoveryError(
                    f"subtask {flat}: replayed determinant stream diverges "
                    f"from the recovered log")

            patched = self._patch(patched, snap, vid, sub, flat,
                                  result, rebuilt, from_epoch, fence, n_steps)
            tp = _clock("patch", tp)

        # Replica rows held by revived subtasks: replicas are identical to
        # their owner's log by construction (same bulk appends), so rebuild
        # by copying the owner's (possibly just-restored) log row.
        for flat in failed:
            for r in self.plan.replicas_held_by(flat):
                o = self.plan.pairs[r][0]
                patched = patched._replace(replicas=jax.tree_util.tree_map(
                    lambda s, l: s.at[r].set(l[o]),
                    patched.replicas, patched.logs))

        self.executor.carry = patched
        jax.block_until_ready(patched)
        tp = _clock("replica_rebuild", tp)
        for flat in failed:
            self.heartbeats.revive(flat)
        self.failed.clear()
        self.coordinator.reset_interval()
        report = RecoveryReport(
            failed_subtasks=failed, from_epoch=from_epoch,
            steps_replayed=n_steps, determinants_replayed=total_dets,
            records_replayed=total_records,
            ignored_checkpoints=ignored,
            recovery_ms=(_time.monotonic() - t0) * 1e3,
            managers=tuple(managers), phase_ms=phases)
        self.reports.append(report)
        self._m_recovery_ms.update(report.recovery_ms)
        self._m_recovered_records.inc(report.records_replayed)
        return report

    # --- input reconstruction ------------------------------------------------

    def _ring_steps(self, patched: JobCarry, src_vid: int, start: int,
                    n: int):
        """Raw output steps [start, start+n) of a producer vertex, from the
        device ring — falling back to the host spill for steps the ring no
        longer retains (reference SpilledReplayIterator.java:61)."""
        compiled = self.executor.compiled
        ri = compiled.ring_index[src_vid]
        el = patched.out_rings[ri]
        batch, cnt, s0 = ifl.slice_steps(el, start, n)
        got_start = int(s0)
        # Steps physically retained by the ring: slice_steps only clamps to
        # ``tail``, but when checkpoints stall past ring capacity newer
        # appends have clobbered positions of steps < head - ring_steps —
        # those must come from the spill even though tail hasn't advanced.
        ring_lo = max(int(el.tail), int(el.head) - el.ring_steps)
        if got_start <= start and start >= ring_lo \
                and int(cnt) >= (start - got_start) + n:
            return jax.tree_util.tree_map(
                lambda x: x[start - got_start: start - got_start + n], batch)
        # Ring shortfall: pull the missing leading steps from the spill.
        if self.executor.spill_logs is None:
            raise rec.RecoveryError(
                f"in-flight log of vertex {src_vid} lost steps "
                f"[{start}, {max(got_start, ring_lo)}) and spill is disabled")
        spill = self.executor.spill_logs[ri]
        boundary = min(start + n, max(got_start, ring_lo))
        parts = []
        have = start
        for ep in spill.retained_epochs():
            ep_start, ep_batch = spill.load_epoch(ep)
            ep_n = ep_batch.keys.shape[0]
            lo = max(have, ep_start)
            hi = min(ep_start + ep_n, boundary)
            if hi > lo:
                parts.append(jax.tree_util.tree_map(
                    lambda x: x[lo - ep_start: hi - ep_start], ep_batch))
                have = hi
            if have >= boundary:
                break
        if have < boundary:
            raise rec.RecoveryError(
                f"vertex {src_vid}: spill does not cover steps "
                f"[{have}, {boundary})")
        if boundary < start + n:
            parts.append(jax.tree_util.tree_map(
                lambda x: x[boundary - got_start: start + n - got_start],
                batch))
        out = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        if out.keys.shape[0] != n:
            raise rec.RecoveryError(
                f"vertex {src_vid}: reconstructed {out.keys.shape[0]} of "
                f"{n} in-flight steps")
        return out

    def _replay_inputs(self, patched: JobCarry, snap: LeanSnapshot,
                       eidx: int, sub: int, fence: int, n_steps: int):
        """The failed consumer's lost inputs on edge ``eidx``: the
        checkpointed depth-1 edge buffer (its input at the first lost step)
        followed by the upstream's ring outputs [fence, fence+n-1), routed
        through the deterministic exchange."""
        e = self.job.edges[eidx]
        first = jax.tree_util.tree_map(
            lambda x: x[sub][None], snap.edge_bufs[eidx])
        if n_steps <= 1:
            return first if n_steps == 1 else jax.tree_util.tree_map(
                lambda x: x[:0], first)
        raw = self._ring_steps(patched, e.src, fence, n_steps - 1)
        routed = self._route_block(eidx, raw, snap)
        routed_sub = jax.tree_util.tree_map(lambda x: x[:, sub], routed)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b], axis=0), first, routed_sub)

    def _route_block(self, eidx: int, raw, snap: LeanSnapshot):
        """Re-run the exchange for a block of raw producer outputs — the
        replay-side of 'exchanges are deterministic, so the network needs
        no determinants' (parallel/routing.py)."""
        e = self.job.edges[eidx]
        dst_p = self.job.vertices[e.dst].parallelism
        if e.partition == PartitionType.HASH:
            r, _ = routing.route_hash_block(
                raw, dst_p, self.job.num_key_groups, e.capacity)
        elif e.partition == PartitionType.FORWARD:
            r, _ = routing.route_forward_block(raw, e.capacity)
        elif e.partition == PartitionType.REBALANCE:
            counts = raw.count().sum(axis=1)
            offs = (jnp.asarray(snap.rr_offsets[eidx][0], jnp.int32)
                    + jnp.cumsum(counts) - counts)
            r, _ = routing.route_rebalance_block(raw, dst_p, e.capacity,
                                                 offs)
        else:
            r, _ = routing.route_broadcast_block(raw, dst_p, e.capacity)
        return r

    def _reread_feed(self, vid: int, sub: int, snap: LeanSnapshot,
                     rows: np.ndarray, n_steps: int):
        """Rebuild a HostFeedSource's lost input batches: offset from the
        checkpointed operator state, per-step pull counts from the recorded
        BUFFER_BUILT determinants, records from the rewindable reader."""
        reader = self.executor.feed_readers.get(vid)
        if reader is None:
            raise rec.RecoveryError(
                f"vertex {vid}: HostFeedSource has no registered feed "
                f"reader to re-read from")
        v = self.job.vertices[vid]
        b = v.operator.batch_size
        anchors = np.where((rows[:, det.LANE_TAG] == det.TIMESTAMP)
                           & (rows[:, det.LANE_RC] == 0))[0][:n_steps]
        counts = rows[anchors + 3, det.LANE_P].astype(np.int64)
        offset = int(np.asarray(snap.op_states[vid]["offset"][sub]))
        keys = np.zeros((n_steps, b), np.int32)
        vals = np.zeros((n_steps, b), np.int32)
        valid = np.zeros((n_steps, b), bool)
        for i, c in enumerate(counts):
            ks, vs = reader.read_at(sub, offset, int(c))
            keys[i, :int(c)], vals[i, :int(c)] = ks, vs
            valid[i, :int(c)] = True
            offset += int(c)
        from clonos_tpu.api.records import RecordBatch as RB
        return RB(jnp.asarray(keys), jnp.asarray(vals),
                  jnp.zeros((n_steps, b), jnp.int32), jnp.asarray(valid))

    def _synthesize_det_rows(self, fence_global: int,
                             n_steps: int) -> np.ndarray:
        """Rebuild a sink's per-step determinant rows from the executor's
        step-input ledger (times/rng draws for the lost steps). BUFFER_BUILT
        payloads are placeholders — the replayer fills real emit counts into
        the rebuilt rows."""
        hist = self.executor.step_input_history[fence_global:
                                                fence_global + n_steps]
        if len(hist) < n_steps:
            raise rec.RecoveryError("step-input ledger shorter than the "
                                    "lost step range")
        rows = np.zeros((n_steps * DETS_PER_STEP, det.NUM_LANES), np.int32)
        for i, (t, r) in enumerate(hist):
            base = i * DETS_PER_STEP
            rows[base, det.LANE_TAG] = det.TIMESTAMP
            rows[base, det.LANE_P] = -1 if t < 0 else 0
            rows[base, det.LANE_P + 1] = t
            rows[base + 1, det.LANE_TAG] = det.RNG
            rows[base + 1, det.LANE_P] = r
            rows[base + 2, det.LANE_TAG] = det.ORDER
            rows[base + 3, det.LANE_TAG] = det.BUFFER_BUILT
        return rows

    def _det_request_max(self) -> int:
        # A replica can never serve more rows than its ring retains.
        return self.executor.compiled.log_capacity

    def _patch(self, carry: JobCarry, snap: LeanSnapshot, vid: int,
               sub: int, flat: int, result: rec.ReplayResult,
               det_rows: np.ndarray, from_epoch: int, fence: int,
               n_steps: int) -> JobCarry:
        """Graft the rebuilt subtask back into the live carry."""
        compiled = self.executor.compiled
        # Operator state slice.
        ops = list(carry.op_states)
        ops[vid] = jax.tree_util.tree_map(
            lambda live_x, new_x: live_x.at[sub].set(new_x[0]),
            ops[vid], result.op_state)
        # Causal log row: an empty log re-based at the fence offset (the
        # pre-fence rows were truncated by the completed checkpoint — the
        # lean snapshot deliberately doesn't carry them) + recovered rows.
        ck_head = int(np.asarray(snap.log_heads[flat]))
        base = jnp.asarray(ck_head, jnp.int32)
        restored = clog.create(compiled.log_capacity, compiled.max_epochs)
        restored = restored._replace(head=base, tail=base)
        n = det_rows.shape[0]
        if n > 0:
            restored = clog.append(restored, jnp.asarray(det_rows), n)
        # Epoch->offset index entries died with the task; rebuild them from
        # the fence-step ledger. Sync blocks anchor at TIMESTAMP rows.
        ts_pos = (np.where((det_rows[:, det.LANE_TAG] == det.TIMESTAMP)
                           & (det_rows[:, det.LANE_RC] == 0))[0]
                  if n > 0 else np.zeros((0,), np.int64))
        for e in range(from_epoch, self.executor.epoch_id + 1):
            if e in self._fence_step:
                step_i = self._fence_step[e] - fence
                # from_epoch starts exactly at the checkpointed head (async
                # rows appended in the roll gap come after the fence);
                # later fences anchor at their first step's TIMESTAMP row
                # (one-row skew if an async row landed in that roll gap —
                # conservative side, matches round-1 semantics).
                if step_i == 0:
                    off = ck_head
                elif step_i < len(ts_pos):
                    off = ck_head + int(ts_pos[step_i])
                else:
                    off = ck_head + n
                slot = e % restored.max_epochs
                restored = restored._replace(
                    epoch_starts=restored.epoch_starts.at[slot].set(off),
                    latest_epoch=jnp.maximum(
                        restored.latest_epoch,
                        jnp.asarray(e, jnp.int32)))
        restored = restored._replace(
            epoch_base=jnp.maximum(restored.epoch_base,
                                   jnp.asarray(from_epoch, jnp.int32)))
        logs = jax.tree_util.tree_map(
            lambda s, r: s.at[flat].set(r), carry.logs, restored)
        # In-flight ring shard reconstruction: write the replayed outputs
        # back into the producer's ring at their original step offsets
        # (reference buildAndLogBuffer — the standby re-cuts identical
        # buffers and re-logs them so downstream recoveries can be served).
        rings = list(carry.out_rings)
        if vid in compiled.ring_index and result.out_steps is not None \
                and n_steps > 0:
            ri = compiled.ring_index[vid]
            el = rings[ri]
            # Only the last ring_steps replayed steps fit in the ring; a
            # spill-backed replay longer than the ring would otherwise
            # scatter wrapped duplicate indices (unspecified winner).
            m = min(n_steps, el.ring_steps)
            os_ = jax.tree_util.tree_map(
                lambda x: x[n_steps - m:], result.out_steps)
            idx = (jnp.asarray(fence + n_steps - m, jnp.int32)
                   + jnp.arange(m, dtype=jnp.int32)) \
                & (el.ring_steps - 1)
            rings[ri] = el._replace(
                keys=el.keys.at[idx, sub].set(
                    os_.keys, unique_indices=True),
                values=el.values.at[idx, sub].set(
                    os_.values, unique_indices=True),
                timestamps=el.timestamps.at[idx, sub].set(
                    os_.timestamps, unique_indices=True),
                valid=el.valid.at[idx, sub].set(
                    os_.valid, unique_indices=True))
        # Record count: checkpoint value + replayed records.
        rc = snap.record_counts[flat] + result.records_replayed
        return carry._replace(
            op_states=tuple(ops), logs=logs, out_rings=tuple(rings),
            record_counts=carry.record_counts.at[flat].set(rc))
