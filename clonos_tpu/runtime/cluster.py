"""Cluster runner: failure detection, standby management, causal recovery.

This is the control-plane layer tying the executor, checkpoint coordinator,
replication plan, and recovery FSM together — capability parity with the
reference's JobMaster-side machinery:

- ``HeartbeatMonitor``   <-  runtime/heartbeat (JobMaster.java:258-266)
- ``StandbyPool``        <-  ExecutionVertex.addStandbyExecution /
                             CheckpointCoordinator state dispatch (:1226)
- ``ClusterRunner``      <-  RunStandbyTaskStrategy.onTaskFailure
                             (failover/RunStandbyTaskStrategy.java:85):
                             remove failed, ignore unacked checkpoints,
                             back off the checkpoint interval, run the
                             standby through the recovery FSM (§3.4)

Failure model (TPU deployment semantics): the unit of loss is a subtask's
device-resident state — its operator-state slice, its thread causal log row,
and the replica rows it holds for others. In-flight edge rings are owned by
the *producing* vertex (they are its output subpartition logs, exactly the
reference's PipelinedSubpartition ownership) and are modeled as surviving a
single-subtask loss (vertex-level redundancy across the producer's devices);
the BUFFER_BUILT verification in replay additionally proves the producer
could rebuild them bit-identically (reference buildAndLogBuffer:536-571) —
the round-2 refinement is per-producer-subtask ring shards.

"Local recovery instead of global rollback" (README.md:13-20): healthy
subtasks are never rolled back — the failed subtask alone is rebuilt from
the last checkpoint plus determinant replay, then patched into the live
carry. The proof obligation (and the test): the patched carry is
bit-identical to a never-failed run.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import recovery as rec
from clonos_tpu.causal import replication as rep
from clonos_tpu.graph.job_graph import JobGraph
from clonos_tpu.inflight import log as ifl
from clonos_tpu.runtime import checkpoint as cp
from clonos_tpu.runtime.executor import (DETS_PER_STEP, JobCarry,
                                         LocalExecutor)


class HeartbeatMonitor:
    """Deadline-based liveness tracking (reference runtime/heartbeat)."""

    def __init__(self, subtasks: Sequence[int], timeout_s: float = 5.0,
                 clock=_time.monotonic):
        self._clock = clock
        self.timeout_s = timeout_s
        self._last: Dict[int, float] = {s: clock() for s in subtasks}
        self._dead: Set[int] = set()

    def beat(self, subtask: int) -> None:
        if subtask not in self._dead:
            self._last[subtask] = self._clock()

    def beat_all_except(self, dead: Set[int]) -> None:
        now = self._clock()
        for s in self._last:
            if s not in dead and s not in self._dead:
                self._last[s] = now

    def mark_dead(self, subtask: int) -> None:
        self._dead.add(subtask)

    def expired(self) -> List[int]:
        now = self._clock()
        out = [s for s, t in self._last.items()
               if s not in self._dead and now - t > self.timeout_s]
        return sorted(out)

    def revive(self, subtask: int) -> None:
        self._dead.discard(subtask)
        self._last[subtask] = self._clock()


class StandbyPool:
    """Holds the state standbys restore from: the latest completed
    checkpoint, refreshed on every completion (the reference re-dispatches
    state to STANDBY executions on each checkpoint, Execution.java:373)."""

    def __init__(self, num_standby_per_vertex: int = 1):
        self.num_standby_per_vertex = num_standby_per_vertex
        self.latest: Optional[cp.CompletedCheckpoint] = None
        self.dispatch_count = 0

    def on_completed_checkpoint(self, ckpt: cp.CompletedCheckpoint) -> None:
        self.latest = ckpt
        self.dispatch_count += 1

    def has_state(self) -> bool:
        return self.latest is not None


@dataclasses.dataclass
class RecoveryReport:
    """What one failure's recovery did (metrics + test surface)."""

    failed_subtasks: Tuple[int, ...]
    from_epoch: int
    steps_replayed: int
    determinants_replayed: int
    records_replayed: int
    ignored_checkpoints: Tuple[int, ...]
    recovery_ms: float
    managers: Tuple[rec.RecoveryManager, ...]


class ClusterRunner:
    """Single-process cluster (MiniCluster analog) with failure injection.

    Drives epochs; at every epoch fence triggers a checkpoint, collects
    acks from healthy subtasks, and on completion truncates logs and
    refreshes standbys."""

    def __init__(self, job: JobGraph, steps_per_epoch: int = 8,
                 num_standby: int = 1, heartbeat_timeout_s: float = 5.0,
                 checkpoint_dir: Optional[str] = None, **executor_kw):
        self.job = job
        self.executor = LocalExecutor(job, steps_per_epoch=steps_per_epoch,
                                      **executor_kw)
        storage = (cp.FileCheckpointStorage(checkpoint_dir)
                   if checkpoint_dir else cp.InMemoryCheckpointStorage())
        self.coordinator = cp.CheckpointCoordinator(
            storage, num_subtasks=job.total_subtasks(),
            base_interval_steps=steps_per_epoch)
        self.standbys = StandbyPool(num_standby)
        self.coordinator.subscribe_completed_state(
            self.standbys.on_completed_checkpoint)
        self.coordinator.subscribe_completion(
            self.executor.notify_checkpoint_complete)
        self.heartbeats = HeartbeatMonitor(
            range(job.total_subtasks()), timeout_s=heartbeat_timeout_s)
        self.failed: Set[int] = set()
        self.global_step = 0
        self._fence_step: Dict[int, int] = {}   # epoch -> global step at start
        self._fence_step[0] = 0
        self.plan = self.executor.compiled.plan
        self.reports: List[RecoveryReport] = []
        # Observability (reference MetricRegistryImpl + Clonos determinant
        # watchdog; see utils/metrics.py).
        from clonos_tpu.utils import metrics as met
        self.metrics = met.MetricRegistry()
        g = self.metrics.group(f"job.{job.name}")
        self._m_steps = g.counter("supersteps")
        self._m_records = g.meter("records-per-sec")
        self._m_epochs = g.counter("epochs")
        self._m_ckpt_bytes = g.gauge(
            "checkpoint.latest-bytes",
            lambda: (self.standbys.latest.size_bytes
                     if self.standbys.latest else 0))
        self._m_recovery_ms = g.histogram("recovery.duration-ms")
        self._m_recovered_records = g.counter("recovery.records-replayed")
        self.watchdog = met.LogOccupancyWatchdog(self.executor, g)

    # --- steady state --------------------------------------------------------

    def run_epoch(self, complete_checkpoint: bool = True) -> None:
        """Run to the next epoch fence and trigger its checkpoint.

        ``complete_checkpoint=False`` leaves the checkpoint pending (no
        acks): logs keep accumulating across epochs — the large-checkpoint-
        interval regime the spillable in-flight log exists for, and the
        setup for multi-epoch recovery gaps."""
        if self.failed:
            raise rec.RecoveryError(
                f"cannot run with failed subtasks {sorted(self.failed)}; "
                f"call recover() first")
        closed = self.executor.epoch_id
        n = self.executor.steps_per_epoch - self.executor.step_in_epoch
        rc_before = int(np.sum(np.asarray(
            self.executor.carry.record_counts)))
        self.executor.run_epoch()
        self.global_step += n
        self._fence_step[self.executor.epoch_id] = self.global_step
        self.heartbeats.beat_all_except(self.failed)
        self._m_steps.inc(n)
        self._m_epochs.inc()
        self._m_records.mark(int(np.sum(np.asarray(
            self.executor.carry.record_counts))) - rc_before)
        # Checkpoint at the fence: snapshot is the post-roll carry.
        self.coordinator.trigger(closed, self.executor.carry,
                                 async_write=False)
        if complete_checkpoint:
            self.coordinator.ack_all(closed)

    def step(self) -> None:
        if self.failed:
            raise rec.RecoveryError("failed subtasks present; recover() first")
        self.executor.step()
        self.global_step += 1
        self._m_steps.inc()
        self.heartbeats.beat_all_except(self.failed)

    # --- failure injection ---------------------------------------------------

    def inject_failure(self, flat_subtasks: Sequence[int]) -> None:
        """Kill subtasks: zero their device state (operator slice, causal
        log row, held replica rows) — the information a lost device takes
        with it. (Fault-injection API the reference delegates to Jepsen,
        flink-jepsen/.)"""
        carry = self.executor.carry
        for flat in flat_subtasks:
            self.failed.add(flat)
            self.heartbeats.mark_dead(flat)
            vid, sub = self._vertex_of(flat)
            # Operator state slice -> zeros.
            op = carry.op_states[vid]
            op = jax.tree_util.tree_map(
                lambda x: x.at[sub].set(jnp.zeros_like(x[sub])), op)
            ops = list(carry.op_states)
            ops[vid] = op
            # Causal log row -> fresh.
            fresh = clog.create(self.executor.compiled.log_capacity,
                                self.executor.compiled.max_epochs)
            logs = jax.tree_util.tree_map(
                lambda s, f: s.at[flat].set(f), carry.logs, fresh)
            # Replica rows held by the dead subtask -> fresh.
            replicas = carry.replicas
            for r in self.plan.replicas_held_by(flat):
                replicas = jax.tree_util.tree_map(
                    lambda s, f: s.at[r].set(f), replicas, fresh)
            carry = carry._replace(
                op_states=tuple(ops), logs=logs, replicas=replicas,
                record_counts=carry.record_counts.at[flat].set(0))
        self.executor.carry = carry

    def _vertex_of(self, flat: int) -> Tuple[int, int]:
        for v in self.job.vertices:
            base = self.job.subtask_base(v.vertex_id)
            if base <= flat < base + v.parallelism:
                return v.vertex_id, flat - base
        raise ValueError(f"no subtask {flat}")

    # --- recovery (reference §3.4 signature path) ----------------------------

    def detect_failures(self) -> List[int]:
        return self.heartbeats.expired()

    def recover(self) -> RecoveryReport:
        """Run the full causal-recovery protocol for all failed subtasks."""
        if not self.failed:
            raise rec.RecoveryError("no failed subtasks")
        if not self.standbys.has_state():
            raise rec.RecoveryError(
                "no completed checkpoint to restore standbys from")
        t0 = _time.monotonic()
        failed = tuple(sorted(self.failed))

        # (1) RunStandbyTaskStrategy.onTaskFailure: ignore checkpoints the
        # dead tasks never acked; back off the checkpoint interval.
        ignored = tuple(self.coordinator.ignore_unacked_for(set(failed)))
        self.coordinator.backoff()

        ckpt = self.standbys.latest
        from_epoch = ckpt.checkpoint_id + 1
        fence = self._fence_step[from_epoch]
        n_steps = self.global_step - fence
        managers: List[rec.RecoveryManager] = []
        total_dets = 0
        total_records = 0

        live = self.executor.carry
        ckpt_carry = jax.tree_util.tree_map(jnp.asarray, ckpt.carry)
        patched = live

        for flat in failed:
            vid, sub = self._vertex_of(flat)
            v = self.job.vertices[vid]
            mgr = rec.RecoveryManager(
                vid, sub, flat,
                rec.LogReplayer(v.operator, v.parallelism))
            managers.append(mgr)
            in_edges = self.job.in_edges(vid)
            out_edges = self.job.out_edges(vid)

            # FSM: standby -> connections re-established + state restored.
            mgr.notify_start_recovery(in_edges, out_edges)
            mgr.notify_state_restoration_complete()
            for e in in_edges:
                mgr.notify_new_input_channel(e)
            for e in out_edges:
                mgr.notify_new_output_channel(e)

            # DeterminantRequest flood to surviving holders of this log.
            holders = [
                (r, h) for r, (o, h) in enumerate(self.plan.pairs)
                if o == flat and h not in self.failed]
            synthesized = False
            if not holders and n_steps > 0:
                if out_edges:
                    raise rec.RecoveryError(
                        f"subtask {flat}: no surviving replica holds its "
                        f"determinant log (sharing depth too shallow for "
                        f"this failure pattern)")
                # Pure sink: nobody downstream replicates its log. Its
                # inputs replay exactly from the upstream ring; its own
                # nondeterminism (time/rng step inputs) is re-synthesized
                # from the coordinator's input ledger. (The reference has
                # the same boundary: sink exactly-once needs transactional
                # sinks, TwoPhaseCommitSinkFunction.)
                synthesized = True
            mgr.expect_determinant_responses(len(holders))
            for r, _h in holders:
                one = jax.tree_util.tree_map(lambda x: x[r], live.replicas)
                buf, count, start = clog.get_determinants(
                    one, from_epoch, max_out=self._det_request_max())
                mgr.notify_determinant_response(
                    np.asarray(buf)[: int(count)], int(start))
            if synthesized:
                rows = self._synthesize_det_rows(fence, n_steps)
                start = int(np.asarray(ckpt_carry.logs.head[flat]))
            else:
                rows, start = mgr.merged_determinants()
            total_dets += len(rows)

            # InFlightLogRequest to the upstream ring(s) of the input
            # edge(s); HostFeedSources instead re-read the rewindable
            # external feed at the checkpointed offset with the recorded
            # per-step counts (Kafka-offset-restore pattern).
            def _ring_inputs(e: int):
                el = live.edge_logs[e]
                fence_off = int(ifl.epoch_start_step(el, from_epoch))
                batch, cnt, s0 = ifl.slice_steps(
                    el, fence_off, max(n_steps, 1))
                got = int(cnt)
                if got < n_steps:
                    raise rec.RecoveryError(
                        f"in-flight log of edge {e} lost steps: have "
                        f"{got}, need {n_steps}")
                return jax.tree_util.tree_map(
                    lambda x: x[:n_steps, sub], batch)

            from clonos_tpu.api.operators import (HostFeedSource,
                                                  TwoInputOperator)
            input_steps = None
            if isinstance(v.operator, TwoInputOperator):
                input_steps = (_ring_inputs(in_edges[0]),
                               _ring_inputs(in_edges[1]))
            elif in_edges:
                input_steps = _ring_inputs(in_edges[0])
            elif isinstance(v.operator, HostFeedSource) and n_steps > 0:
                input_steps = self._reread_feed(vid, sub, ckpt_carry,
                                                rows, n_steps)

            plan = rec.ReplayPlan(
                vertex_id=vid, subtask=sub, flat_subtask=flat,
                from_epoch=from_epoch, input_steps=input_steps,
                det_rows=rows, det_start=start,
                checkpoint_op_state=ckpt_carry.op_states[vid],
                n_steps=n_steps, verify_outputs=not synthesized)
            result = mgr.run_replay(plan)
            total_records += result.records_replayed

            rebuilt = np.asarray(result.rebuilt_log_rows)
            # The regenerated determinant rows must equal the recovered ones
            # (bit-identical replay; reference post-replay log asserts).
            if not synthesized and not np.array_equal(
                    rebuilt, rows[: rebuilt.shape[0]]):
                raise rec.RecoveryError(
                    f"subtask {flat}: replayed determinant stream diverges "
                    f"from the recovered log")

            patched = self._patch(patched, ckpt_carry, vid, sub, flat,
                                  result, rebuilt, from_epoch)

        # Replica rows held by revived subtasks: restore from checkpoint and
        # let one catch-up replication round pull them level.
        for flat in failed:
            for r in self.plan.replicas_held_by(flat):
                patched = patched._replace(replicas=jax.tree_util.tree_map(
                    lambda s, c: s.at[r].set(c[r]),
                    patched.replicas, ckpt_carry.replicas))
        if any(self.plan.replicas_held_by(f) for f in failed):
            # Snapshot predates the completion truncation; re-apply (no-op
            # for rows already truncated — truncate never moves backwards).
            patched = patched._replace(
                replicas=clog.v_truncate(patched.replicas, from_epoch - 1))
        if self.plan.num_replicas > 0:
            replicas, _ = rep.replicate_step(
                patched.replicas, patched.logs,
                self.executor.compiled._owner_idx,
                max_delta=self._det_request_max())
            patched = patched._replace(replicas=replicas)

        self.executor.carry = patched
        for flat in failed:
            self.heartbeats.revive(flat)
        self.failed.clear()
        self.coordinator.reset_interval()
        report = RecoveryReport(
            failed_subtasks=failed, from_epoch=from_epoch,
            steps_replayed=n_steps, determinants_replayed=total_dets,
            records_replayed=total_records,
            ignored_checkpoints=ignored,
            recovery_ms=(_time.monotonic() - t0) * 1e3,
            managers=tuple(managers))
        self.reports.append(report)
        self._m_recovery_ms.update(report.recovery_ms)
        self._m_recovered_records.inc(report.records_replayed)
        return report

    def _reread_feed(self, vid: int, sub: int, ckpt_carry: JobCarry,
                     rows: np.ndarray, n_steps: int):
        """Rebuild a HostFeedSource's lost input batches: offset from the
        checkpointed operator state, per-step pull counts from the recorded
        BUFFER_BUILT determinants, records from the rewindable reader."""
        reader = self.executor.feed_readers.get(vid)
        if reader is None:
            raise rec.RecoveryError(
                f"vertex {vid}: HostFeedSource has no registered feed "
                f"reader to re-read from")
        v = self.job.vertices[vid]
        b = v.operator.batch_size
        anchors = np.where((rows[:, det.LANE_TAG] == det.TIMESTAMP)
                           & (rows[:, det.LANE_RC] == 0))[0][:n_steps]
        counts = rows[anchors + 3, det.LANE_P].astype(np.int64)
        offset = int(np.asarray(ckpt_carry.op_states[vid]["offset"][sub]))
        keys = np.zeros((n_steps, b), np.int32)
        vals = np.zeros((n_steps, b), np.int32)
        valid = np.zeros((n_steps, b), bool)
        for i, c in enumerate(counts):
            ks, vs = reader.read_at(sub, offset, int(c))
            keys[i, :int(c)], vals[i, :int(c)] = ks, vs
            valid[i, :int(c)] = True
            offset += int(c)
        from clonos_tpu.api.records import RecordBatch as RB
        return RB(jnp.asarray(keys), jnp.asarray(vals),
                  jnp.zeros((n_steps, b), jnp.int32), jnp.asarray(valid))

    def _synthesize_det_rows(self, fence_global: int,
                             n_steps: int) -> np.ndarray:
        """Rebuild a sink's per-step determinant rows from the executor's
        step-input ledger (times/rng draws for the lost steps). BUFFER_BUILT
        payloads are placeholders — the replayer fills real emit counts into
        the rebuilt rows."""
        hist = self.executor.step_input_history[fence_global:
                                                fence_global + n_steps]
        if len(hist) < n_steps:
            raise rec.RecoveryError("step-input ledger shorter than the "
                                    "lost step range")
        rows = np.zeros((n_steps * DETS_PER_STEP, det.NUM_LANES), np.int32)
        for i, (t, r) in enumerate(hist):
            base = i * DETS_PER_STEP
            rows[base, det.LANE_TAG] = det.TIMESTAMP
            rows[base, det.LANE_P] = -1 if t < 0 else 0
            rows[base, det.LANE_P + 1] = t
            rows[base + 1, det.LANE_TAG] = det.RNG
            rows[base + 1, det.LANE_P] = r
            rows[base + 2, det.LANE_TAG] = det.ORDER
            rows[base + 3, det.LANE_TAG] = det.BUFFER_BUILT
        return rows

    def _det_request_max(self) -> int:
        return 4 * DETS_PER_STEP * max(self.executor.steps_per_epoch, 1) * \
            max(len(self._fence_step), 2)

    def _patch(self, carry: JobCarry, ckpt_carry: JobCarry, vid: int,
               sub: int, flat: int, result: rec.ReplayResult,
               det_rows: np.ndarray, from_epoch: int) -> JobCarry:
        """Graft the rebuilt subtask back into the live carry."""
        # Operator state slice.
        ops = list(carry.op_states)
        ops[vid] = jax.tree_util.tree_map(
            lambda live_x, new_x: live_x.at[sub].set(new_x[0]),
            ops[vid], result.op_state)
        # Causal log row: checkpoint-fence log + recovered rows appended.
        ck_row = jax.tree_util.tree_map(lambda x: x[flat], ckpt_carry.logs)
        n = det_rows.shape[0]
        if n > 0:
            restored = clog.append(ck_row, jnp.asarray(det_rows), n)
        else:
            restored = ck_row
        # Epoch->offset index entries recorded after the fence died with the
        # task; rebuild them from the fence-step ledger. Sync blocks anchor
        # at TIMESTAMP rows (async rows may interleave, shifting offsets;
        # an async row appended in the roll gap attributes to the new epoch
        # here — one-row truncation skew at worst, conservative side).
        ck_head = int(np.asarray(ckpt_carry.logs.head[flat]))
        ts_pos = (np.where((det_rows[:, det.LANE_TAG] == det.TIMESTAMP)
                           & (det_rows[:, det.LANE_RC] == 0))[0]
                  if n > 0 else np.zeros((0,), np.int64))
        fence_global = self._fence_step[from_epoch]
        for e in range(from_epoch + 1, self.executor.epoch_id + 1):
            if e in self._fence_step:
                step_i = self._fence_step[e] - fence_global
                off = (ck_head + int(ts_pos[step_i])
                       if step_i < len(ts_pos)
                       else ck_head + n)
                slot = e % restored.max_epochs
                restored = restored._replace(
                    epoch_starts=restored.epoch_starts.at[slot].set(off),
                    latest_epoch=jnp.maximum(
                        restored.latest_epoch,
                        jnp.asarray(e, jnp.int32)))
        # The snapshot predates the checkpoint-completion truncation the
        # live logs already applied; apply it to the restored row too.
        restored = clog.truncate(restored, from_epoch - 1)
        logs = jax.tree_util.tree_map(
            lambda s, r: s.at[flat].set(r), carry.logs, restored)
        # Record count: checkpoint value + replayed records.
        rc = ckpt_carry.record_counts[flat] + result.records_replayed
        return carry._replace(
            op_states=tuple(ops), logs=logs,
            record_counts=carry.record_counts.at[flat].set(rc))
