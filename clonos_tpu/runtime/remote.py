"""Cross-host control plane: JobMaster endpoint + remote determinant
mirroring.

This gives the framework a real multi-process story (SURVEY §1 rows 4/5,
§2.6 control transport) with the same division of labor as the
reference:

- :class:`JobMasterServer` — registration + deadline heartbeats + the
  ignore-checkpoint RPC surface (JobMaster.java:151, heartbeat wiring
  :258-266, TaskExecutorGateway.java:170-233), served over
  parallel/transport.py.
- :class:`HostLogEndpoint` — a running host answers determinant-delta
  requests for the task logs it owns: the device rows' fresh suffix is
  pulled once and framed with causal/serde.py (the piggyback delta wire
  format; AbstractDeltaSerializerDeserializer.java:89-140).
- :class:`RemoteReplicaMirror` — a standby HOST keeps host-side replica
  logs of remote tasks by polling deltas and merging them with the same
  offset-dedup rule as on-chip replication (log.merge_delta — the
  ThreadCausalLogImpl.processUpstreamDelta:117 semantics). After a host
  loss, these mirrors are the determinant source a rebuilt cluster
  recovers from — replication that survives a whole-host failure domain,
  which intra-chip replicas cannot.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import log as clog
from clonos_tpu.causal import serde
from clonos_tpu.obs import get_tracer
from clonos_tpu.parallel import transport as tp


class JobMasterServer:
    """Minimal dispatcher/JobMaster endpoint: executors register, then
    heartbeat against a deadline; expiry marks them failed (the trigger
    for standby failover on the control plane).

    Scheduling surface (the SlotPool feed — reference
    jobmaster/slotpool/SlotPool.java offer path +
    TaskExecutorGateway.java state reports): registration carries a
    ``slots`` advertisement (how many task slices the worker will host),
    SLOT_OFFER adds capacity later, and TASK_STATE records per-deployed-
    task transitions (``DEPLOYING``/``RUNNING``/``FINISHED``/…) keyed by
    ``(executor_id, group)`` together with the ports the task opened
    (determinant-log endpoint, edge exports) — the JobMaster-side
    scheduler reads both through :meth:`slots` / :meth:`task_state`."""

    def __init__(self, heartbeat_timeout_s: float = 5.0,
                 host: str = "127.0.0.1", port: int = 0):
        self.timeout_s = heartbeat_timeout_s
        self._last: Dict[str, float] = {}
        self._meta: Dict[str, dict] = {}
        self._ignored: List[int] = []
        self._slots: Dict[str, int] = {}
        #: (executor_id, job_id, group) -> last TASK_STATE report;
        #: job_id "" is the legacy single-job cluster
        self._tasks: Dict[Tuple[str, str, int], dict] = {}
        #: executor_id -> last metric snapshot piggybacked on HEARTBEAT
        self._hb_metrics: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype == tp.REGISTER:
            info = tp.unpack_json(payload)
            with self._lock:
                self._meta[info["executor_id"]] = info
                self._last[info["executor_id"]] = time.monotonic()
                self._slots[info["executor_id"]] = int(info.get("slots", 0))
            return tp.OK, tp.pack_json({"registered": True})
        if mtype == tp.HEARTBEAT:
            info = tp.unpack_json(payload)
            tp.adopt_hlc(info, verb="HEARTBEAT")
            with self._lock:
                self._last[info["executor_id"]] = time.monotonic()
                metrics = info.get("metrics")
                if metrics is not None:
                    self._hb_metrics[info["executor_id"]] = metrics
            return tp.OK, b""
        if mtype == tp.IGNORE_CHECKPOINT:
            info = tp.unpack_json(payload)
            with self._lock:
                self._ignored.append(info["checkpoint_id"])
            return tp.OK, b""
        if mtype == tp.SLOT_OFFER:
            info = tp.unpack_json(payload)
            eid = info["executor_id"]
            with self._lock:
                self._slots[eid] = self._slots.get(eid, 0) \
                    + int(info["slots"])
            return tp.OK, tp.pack_json({"slots": self._slots[eid]})
        if mtype == tp.TASK_STATE:
            info = tp.unpack_json(payload)
            key = (info["executor_id"], str(info.get("job_id") or ""),
                   int(info["group"]))
            with self._lock:
                self._tasks[key] = info
            return tp.OK, b""
        return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._meta)

    def slots(self) -> Dict[str, int]:
        """Advertised slot capacity per registered executor."""
        with self._lock:
            return dict(self._slots)

    def info(self, executor_id: str) -> dict:
        """The registration record for ``executor_id`` (deploy endpoint,
        slot count, …) — what the scheduler dials to submit tasks."""
        with self._lock:
            if executor_id not in self._meta:
                raise KeyError(f"executor {executor_id!r} never registered")
            return dict(self._meta[executor_id])

    def task_state(self, executor_id: str, group: int,
                   job_id: str = "") -> Optional[dict]:
        """Latest TASK_STATE report for ``(executor_id, job_id, group)``
        (empty job_id = the legacy single-job cluster)."""
        with self._lock:
            return self._tasks.get((executor_id, str(job_id or ""),
                                    int(group)))

    def cluster_metrics(self) -> Dict[str, object]:
        """Cluster-wide metric view: every worker's last heartbeat
        snapshot, flattened under ``worker.<executor_id>.`` — the
        ``extra`` supplier for the JobMaster's MetricsEndpoint, so one
        scrape covers the whole slot pool. When any worker reports audit
        gauges (obs/audit.py rides the same piggyback), a
        ``cluster.audit.*`` rollup is appended — the live exactly-once
        health line an operator alerts on; audit-off clusters get no
        extra keys."""
        with self._lock:
            snaps = {eid: dict(m) for eid, m in self._hb_metrics.items()}
            slots = dict(self._slots)
        out = {f"worker.{eid}.{name}": v
               for eid, m in sorted(snaps.items())
               for name, v in m.items()}
        for eid, n in sorted(slots.items()):
            if n:  # zero-slot registrants host no tasks — no worker row
                out[f"worker.{eid}.slots"] = int(n)
        audit = {k: v for k, v in out.items()
                 if ".audit." in k and isinstance(v, (int, float))}
        if audit:
            sealed = sum(v for k, v in audit.items()
                         if k.endswith("audit.epochs-sealed"))
            validated = sum(v for k, v in audit.items()
                            if k.endswith("audit.epochs-validated"))
            div = sum(v for k, v in audit.items()
                      if k.endswith("audit.divergences"))
            out["cluster.audit.epochs-sealed"] = int(sealed)
            out["cluster.audit.epochs-validated"] = int(validated)
            out["cluster.audit.divergences"] = int(div)
            out["cluster.audit.exactly-once-ok"] = int(div == 0)
        # Overhead rollup (obs/profile.py rides the same piggyback):
        # the worst per-worker FT fraction is the cluster's headline
        # number — overhead hides in the max, not the mean.
        fracs = [v for k, v in out.items()
                 if k.endswith("overhead.ft-fraction")
                 and isinstance(v, (int, float))]
        if fracs:
            out["cluster.overhead.ft-fraction-max"] = round(
                max(fracs), 6)
            out["cluster.overhead.ft-fraction-mean"] = round(
                sum(fracs) / len(fracs), 6)
        # Per-job rollups (multi-tenant pool): slice workers prefix a
        # job-scoped slice's metrics ``job.<jid>.group.<g>.`` — roll
        # each job's slice count and audit chain up under
        # ``cluster.job.<jid>.*`` so /metrics.json and `clonos_tpu top`
        # read exactly-once health PER TENANT. Single-job clusters emit
        # no job-prefixed keys and get no extra rows.
        jobs: Dict[str, dict] = {}
        for k, v in out.items():
            parts = k.split(".")
            if (len(parts) < 6 or parts[0] != "worker"
                    or parts[2] != "job" or parts[4] != "group"):
                continue
            rec = jobs.setdefault(parts[3], {
                "groups": set(), "sealed": 0, "validated": 0,
                "div": 0, "audited": False})
            rec["groups"].add(parts[5])
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if k.endswith("audit.epochs-sealed"):
                rec["sealed"] += v
                rec["audited"] = True
            elif k.endswith("audit.epochs-validated"):
                rec["validated"] += v
                rec["audited"] = True
            elif k.endswith("audit.divergences"):
                rec["div"] += v
                rec["audited"] = True
        for jid, rec in sorted(jobs.items()):
            out[f"cluster.job.{jid}.groups"] = len(rec["groups"])
            if rec["audited"]:
                out[f"cluster.job.{jid}.audit.epochs-sealed"] = \
                    int(rec["sealed"])
                out[f"cluster.job.{jid}.audit.epochs-validated"] = \
                    int(rec["validated"])
                out[f"cluster.job.{jid}.audit.divergences"] = \
                    int(rec["div"])
                out[f"cluster.job.{jid}.audit.exactly-once-ok"] = \
                    int(rec["div"] == 0)
        return out

    def expired(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return sorted(e for e, t in self._last.items()
                          if now - t > self.timeout_s)

    def close(self) -> None:
        self.server.close()


class TaskExecutorClient:
    """Executor-side stub: register once, heartbeat on a thread.

    ``payload_fn`` (zero-arg, returns a dict) is merged into every
    HEARTBEAT — the metric-piggyback hook. It runs on the heartbeat
    thread, so it must return host-side data only (the worker caches a
    snapshot on its MAIN loop; jax dispatch is main-thread-only)."""

    def __init__(self, executor_id: str, jm_address: Tuple[str, int],
                 interval_s: float = 1.0,
                 info: Optional[dict] = None,
                 payload_fn=None):
        self.executor_id = executor_id
        self._client = tp.ControlClient(tuple(jm_address))
        self._client.call_json(tp.REGISTER, {"executor_id": executor_id,
                                             **(info or {})})
        self._payload_fn = payload_fn
        self._interval = interval_s
        #: consecutive heartbeat RPC failures (0 when healthy)
        self.missed_beats = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._beat, daemon=True)
        self._t.start()

    def _beat(self) -> None:
        # A transient socket error must not kill the heartbeat thread —
        # a dead thread makes the JobMaster declare a HEALTHY executor
        # failed after timeout_s (spurious failover). Keep trying; the
        # JM's deadline is the arbiter of real failure, not one dropped
        # RPC. ``missed_beats`` surfaces persistent trouble.
        while not self._stop.wait(self._interval):
            try:
                msg = {"executor_id": self.executor_id}
                if self._payload_fn is not None:
                    try:
                        msg.update(self._payload_fn() or {})
                    except Exception:
                        pass       # the beat matters more than the extras
                tp.attach_hlc(msg, verb="HEARTBEAT")
                self._client.call_json(tp.HEARTBEAT, msg)
                self.missed_beats = 0
            except (OSError, RuntimeError):
                self.missed_beats += 1

    def close(self) -> None:
        self._stop.set()
        self._client.close()


class HostLogEndpoint:
    """Serves this host's determinant logs to remote mirrors.

    The request handler runs on a server thread and must touch NO device
    state (jax dispatch is main-thread-only on some backends, and the
    device path shouldn't block on remote peers anyway) — so the endpoint
    serves a host-side numpy snapshot that the MAIN loop refreshes at
    block/epoch boundaries via :meth:`refresh`. Served deltas are
    prefix-consistent and at most one refresh behind — exactly the lag
    the replication protocol's offset-dedup merge already tolerates (the
    netty frames in flight of the reference)."""

    def __init__(self, executor, host: str = "127.0.0.1", port: int = 0):
        self.executor = executor
        self._lock = threading.Lock()
        self._rows: Dict[int, np.ndarray] = {}    # flat -> [n, lanes]
        self._starts: Dict[int, int] = {}         # abs offset of rows[0]
        #: ring -> (window start step, window end step, field arrays) —
        #: the served in-flight tail (refresh_inflight)
        self._inflight: Dict[int, Tuple[int, int, Optional[dict]]] = {}
        self.refresh()
        self.server = tp.ControlServer(self._handle, host, port)
        self.address = self.server.address

    def refresh(self) -> None:
        """Main-thread snapshot of every log's live suffix."""
        logs = self.executor.carry.logs
        heads = np.asarray(logs.head)
        tails = np.asarray(logs.tail)
        rows = np.asarray(logs.rows)
        cap = rows.shape[1]
        snap_rows: Dict[int, np.ndarray] = {}
        snap_starts: Dict[int, int] = {}
        for flat in range(rows.shape[0]):
            t, h = int(tails[flat]), int(heads[flat])
            pos = np.arange(t, h) & (cap - 1)
            snap_rows[flat] = rows[flat][pos]
            snap_starts[flat] = t
        with self._lock:
            self._rows = snap_rows
            self._starts = snap_starts

    def refresh_inflight(self, max_steps: int = 256) -> None:
        """Main-thread snapshot of every in-flight ring's tail window
        (bounded to ``max_steps``) for remote serving — the wire analog
        of the reference's InFlightLogRequestEvent path, where a
        recovering task pulls lost inputs from a REMOTE upstream
        (flink-runtime .../causal/events/InFlightLogRequestEvent.java +
        backwards task events)."""
        from clonos_tpu.inflight import log as ifl
        import jax.numpy as jnp
        snap: Dict[int, Tuple[int, int, Optional[dict]]] = {}
        for ri, el in enumerate(self.executor.carry.out_rings):
            head, tail = int(el.head), int(el.tail)
            lo = max(tail, head - max_steps)
            if head <= lo:
                snap[ri] = (lo, head, None)
                continue
            batch, _, _ = ifl.slice_steps(el, jnp.asarray(lo, jnp.int32),
                                          max_steps)
            snap[ri] = (lo, head, {
                "keys": np.asarray(batch.keys)[:head - lo],
                "values": np.asarray(batch.values)[:head - lo],
                "timestamps": np.asarray(batch.timestamps)[:head - lo],
                "valid": np.asarray(batch.valid)[:head - lo]})
        with self._lock:
            self._inflight = snap

    def _handle_inflight(self, payload: bytes) -> Tuple[int, bytes]:
        req = tp.unpack_json(payload)
        ri, start, count = req["ring"], req["start"], req["count"]
        with self._lock:
            win = self._inflight.get(ri)
        if win is None:
            return tp.ERROR, tp.pack_json(
                {"error": f"no in-flight snapshot for ring {ri}"})
        lo, head, fields = win
        got_lo = max(start, lo)
        got_hi = min(start + count, head)
        if fields is None or got_hi <= got_lo:
            hdr = tp.pack_json({"ring": ri, "start": got_lo, "count": 0,
                                "floor": lo})
            return tp.INFLIGHT_RESPONSE, (
                len(hdr).to_bytes(4, "little") + hdr)
        sl = slice(got_lo - lo, got_hi - lo)
        k = np.ascontiguousarray(fields["keys"][sl], np.int32)
        v = np.ascontiguousarray(fields["values"][sl], np.int32)
        t = np.ascontiguousarray(fields["timestamps"][sl], np.int32)
        m = np.ascontiguousarray(fields["valid"][sl], np.uint8)
        hdr = tp.pack_json({"ring": ri, "start": got_lo,
                            "count": got_hi - got_lo, "floor": lo,
                            "shape": list(k.shape)})
        return tp.INFLIGHT_RESPONSE, (
            len(hdr).to_bytes(4, "little") + hdr
            + k.tobytes() + v.tobytes() + t.tobytes() + m.tobytes())

    def _handle(self, mtype: int, payload: bytes) -> Tuple[int, bytes]:
        if mtype == tp.INFLIGHT_REQUEST:
            return self._handle_inflight(payload)
        if mtype != tp.DETERMINANT_REQUEST:
            return tp.ERROR, tp.pack_json({"error": f"bad mtype {mtype}"})
        req = tp.unpack_json(payload)
        known = req.get("known_heads", {})
        encoding = req.get("encoding", "flat")
        tp.adopt_trace(req)
        tp.adopt_hlc(req, verb="DETERMINANT_REQUEST")
        tr = get_tracer()
        deltas = []
        floors: Dict[int, int] = {}
        with self._lock:
            for flat in req["flats"]:
                rows = self._rows.get(flat)
                if rows is None:
                    continue
                start = self._starts[flat]
                floors[flat] = start
                lo = max(int(known.get(str(flat), -1)), start)
                if lo - start >= rows.shape[0]:
                    continue
                deltas.append((flat, lo, rows[lo - start:]))
        if deltas and tr.enabled:
            # only when rows are actually served — the mirror polls
            # frequently and empty rounds would drown the trace
            tr.event("determinants.served",
                     flats=[d[0] for d in deltas],
                     rows=int(sum(d[2].shape[0] for d in deltas)))
        frame = serde.encode_delta(deltas, encoding=encoding)
        # Response = u32 header length | JSON header | delta frame. The
        # floors (each owner log's truncation point) let mirrors release
        # rows below them — a remote notifyCheckpointComplete — so mirror
        # memory tracks the owner's un-truncated window, not all history.
        hdr = tp.pack_json({"floors": {str(f): v
                                       for f, v in floors.items()}})
        return (tp.DETERMINANT_RESPONSE,
                len(hdr).to_bytes(4, "little") + hdr + frame)

    def close(self) -> None:
        self.server.close()


class JobMasterController:
    """Drives standby-HOST failover around :class:`JobMasterServer` — the
    piece that turns the control-plane endpoints into a running recovery
    loop (reference JobMaster.java:151 failover driving +
    RunStandbyTaskStrategy dispatch):

    - every registered worker that advertises a log endpoint gets a
      :class:`RemoteReplicaMirror` (the standby host's copy of its
      determinant logs), pulled by :meth:`sync`;
    - :meth:`failed` surfaces heartbeat-expired workers;
    - :meth:`rebuild` reconstructs a dead worker's ENTIRE job in this
      process via ``ClusterRunner.bootstrap_standby`` — durable
      checkpoint + mirror rows — CONSUMING the ignore-checkpoint ledger
      workers reported (an ignored checkpoint must never be a restore
      point).

    Mirror peer assignment in multi-worker deployments follows the same
    rotate-by-one placement rule as
    ``parallel.distributed.standby_device_order`` — a host never mirrors
    itself, so a host loss cannot take a log and its mirror together."""

    def __init__(self, jm: JobMasterServer,
                 mirror_capacity: int = 1 << 14, max_epochs: int = 64):
        self.jm = jm
        self.mirror_capacity = mirror_capacity
        self.max_epochs = max_epochs
        self.mirrors: Dict[str, RemoteReplicaMirror] = {}

    def attach(self) -> List[str]:
        """Create mirrors for newly-registered workers (idempotent)."""
        new = []
        with self.jm._lock:
            meta = dict(self.jm._meta)
        for eid, info in meta.items():
            if eid in self.mirrors or "log_port" not in info:
                continue
            self.mirrors[eid] = RemoteReplicaMirror(
                (info.get("log_host", "127.0.0.1"), info["log_port"]),
                flats=list(range(info["num_subtasks"])),
                capacity=self.mirror_capacity, max_epochs=self.max_epochs)
            new.append(eid)
        return sorted(new)

    def sync(self) -> Dict[str, int]:
        """One pull round over every healthy worker's mirror."""
        out = {}
        dead = set(self.jm.expired())
        for eid, m in self.mirrors.items():
            if eid in dead:
                continue
            try:
                out[eid] = m.sync()
            except OSError:
                out[eid] = -1          # endpoint gone; heartbeats decide
        return out

    def failed(self) -> List[str]:
        return self.jm.expired()

    def ignored_checkpoints(self) -> List[int]:
        with self.jm._lock:
            return sorted(set(self.jm._ignored))

    def rebuild(self, executor_id: str, job, **runner_kw):
        """Standby-host failover for ``executor_id``'s job: bootstrap a
        fresh runner in THIS process from the worker's durable
        checkpoint dir + this controller's mirror of its logs."""
        from clonos_tpu.runtime.cluster import ClusterRunner
        with self.jm._lock:
            info = dict(self.jm._meta[executor_id])
        mirror = self.mirrors[executor_id]
        rows = {f: mirror.rows_with_start(f) for f in mirror.flats}
        return ClusterRunner.bootstrap_standby(
            job, info["checkpoint_dir"], rows,
            ignored_checkpoints=self.ignored_checkpoints(), **runner_kw)

    def close(self) -> None:
        for m in self.mirrors.values():
            m.close()


class RemoteReplicaMirror:
    """Standby-host replica of remote task logs: host-side
    :class:`clog.ThreadCausalLog` wrappers merged with the on-chip
    offset-dedup rule."""

    def __init__(self, address: Tuple[str, int], flats: List[int],
                 capacity: int = 1 << 14, max_epochs: int = 64,
                 encoding: str = "flat"):
        self._client = tp.ControlClient(tuple(address))
        self.flats = list(flats)
        self.encoding = encoding
        self._replicas: Dict[int, clog.ThreadCausalLog] = {
            f: clog.ThreadCausalLog(capacity, max_epochs)
            for f in self.flats}

    def head(self, flat: int) -> int:
        return self._replicas[flat].head

    def rows(self, flat: int) -> np.ndarray:
        log = self._replicas[flat]
        return log.delta_for_consumer(
            log.tail, max(0, log.head - log.tail))[0]

    def rows_with_start(self, flat: int) -> Tuple[np.ndarray, int]:
        """(live rows, absolute offset of rows[0]) — the determinant-
        source form ClusterRunner.bootstrap_standby consumes."""
        log = self._replicas[flat]
        return (self.rows(flat), int(log.tail))

    def fetch_inflight(self, ring: int, start: int, count: int
                       ) -> Tuple[int, Optional[dict]]:
        """Pull a window of a remote upstream's in-flight log (the
        InFlightLogRequestEvent wire analog): returns
        (absolute start of the served window, field dict with
        keys/values/timestamps [n, P, cap] int32 + valid [n, P, cap]
        bool), or (floor, None) when the requested range holds no
        retained steps."""
        rt, resp = self._client.call(
            tp.INFLIGHT_REQUEST,
            tp.pack_json({"ring": ring, "start": start, "count": count}))
        if rt == tp.ERROR:
            raise RuntimeError(tp.unpack_json(resp)["error"])
        hlen = int.from_bytes(resp[:4], "little")
        hdr = tp.unpack_json(resp[4: 4 + hlen])
        if hdr["count"] == 0:
            return hdr["floor"], None
        shape = tuple(hdr["shape"])
        n = int(np.prod(shape)) * 4
        body = resp[4 + hlen:]
        k = np.frombuffer(body[:n], np.int32).reshape(shape)
        v = np.frombuffer(body[n:2 * n], np.int32).reshape(shape)
        t = np.frombuffer(body[2 * n:3 * n], np.int32).reshape(shape)
        m = np.frombuffer(body[3 * n:3 * n + n // 4],
                          np.uint8).reshape(shape).astype(bool)
        return hdr["start"], {"keys": k, "values": v, "timestamps": t,
                              "valid": m}

    def sync(self) -> int:
        """One pull round: request each owned log's suffix past our head,
        merge with offset dedup. Returns rows absorbed.

        A merge gap (delta starting past our head) can only mean the
        owner TRUNCATED its log across a completed checkpoint — the
        pull-from-known-head protocol never skips live rows — so the
        mirror applies the same truncation: rebase to the delta's start
        and absorb from there (a remote notifyCheckpointComplete)."""
        known = {str(f): self.head(f) for f in self.flats}
        req = tp.attach_hlc(
            tp.attach_trace({"flats": self.flats, "known_heads": known,
                             "encoding": self.encoding}),
            verb="DETERMINANT_REQUEST")
        rt, resp = self._client.call(tp.DETERMINANT_REQUEST,
                                     tp.pack_json(req))
        if rt == tp.ERROR:
            raise RuntimeError(tp.unpack_json(resp)["error"])
        hlen = int.from_bytes(resp[:4], "little")
        floors = tp.unpack_json(resp[4: 4 + hlen]).get("floors", {})
        frame = resp[4 + hlen:]
        absorbed = 0
        for flat, start, rows in serde.decode_delta(frame):
            log = self._replicas[flat]
            rows = np.asarray(rows, np.int32)
            if rows.shape[0] > log.capacity:
                raise RuntimeError(
                    f"mirror of log {flat}: delta of {rows.shape[0]} rows "
                    f"exceeds mirror capacity {log.capacity} — size the "
                    f"mirror at least as large as the owner's log")
            if not log.merge_delta(rows, start):
                log.state = log.state._replace(
                    head=jnp.asarray(start, jnp.int32),
                    tail=jnp.asarray(start, jnp.int32))
                if not log.merge_delta(rows, start):
                    raise RuntimeError(
                        f"mirror of log {flat}: delta rejected even "
                        f"after rebase to {start}")
            absorbed += rows.shape[0]
        # Owner truncation points release mirror history (the remote
        # checkpoint-complete); a mirror that STILL overflows is
        # undersized for the owner's un-truncated window — corrupt ring
        # state, so fail loudly instead of serving garbage to recovery.
        for flat, log in self._replicas.items():
            floor = int(floors.get(str(flat), log.tail))
            if floor > log.tail:
                # The floor can sit PAST our merged head: the owner
                # truncated its whole log across a completed checkpoint
                # before we absorbed those rows, so this round served no
                # delta at all. Rows below a completed-checkpoint floor
                # are never a restore input — rebase to an EMPTY window
                # at the floor instead of leaving tail > head (a
                # negative live window that corrupts later slices).
                log.state = log.state._replace(
                    tail=jnp.asarray(floor, jnp.int32),
                    head=jnp.asarray(max(floor, int(log.head)), jnp.int32))
            if int(log.head) - int(log.tail) > log.capacity:
                raise RuntimeError(
                    f"mirror of log {flat}: {int(log.head) - int(log.tail)}"
                    f" live rows exceed capacity {log.capacity}; increase "
                    f"mirror capacity or checkpoint more often")
        return absorbed

    def close(self) -> None:
        self._client.close()
