"""Transactional (exactly-once) sink egress — the 2-phase-commit analog.

Reference: TwoPhaseCommitSinkFunction.java (flink-streaming-java
.../functions/sink/): outputs accumulate in a per-checkpoint transaction,
pre-commit on snapshot, commit on notifyCheckpointComplete — so the
external world only ever observes output backed by a completed
checkpoint, and a replayed epoch can never double-emit.

TPU mapping: the sink operator stays a device op; the host-side
TransactionLog buffers each epoch's emitted records as the *pending
transaction* (sharded per sink subtask, matching the reference's
one-transaction-per-sink-instance ownership), seals it at the epoch
fence, and commits when the checkpoint coordinator reports the epoch's
checkpoint complete. A failed sink subtask loses ITS pending shards
(they lived with the task); recovery replays the lost epochs and
rebuilds those shards from the replayed outputs before any commit — so
the committed stream is bit-identical to a never-failed run's.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class _Txn:
    epoch: int
    #: per-subtask accumulated [n, 3] (key, value, timestamp) records
    shards: Dict[int, List[np.ndarray]] = dataclasses.field(
        default_factory=dict)
    sealed: bool = False


class TransactionLog:
    """Per-sink-vertex 2PC state machine with per-subtask transaction
    shards."""

    def __init__(self, vertex_id: int,
                 committer: Optional[Callable[[int, np.ndarray], None]]
                 = None):
        self.vertex_id = vertex_id
        self.committer = committer
        #: pre-commit hook, called at seal with the epoch's per-subtask
        #: shards — durable sinks persist pending parts here BEFORE the
        #: checkpoint can complete (runtime/filesink.py; the reference's
        #: preCommit-on-snapshot durability promise).
        self.pre_committer: Optional[
            Callable[[int, Dict[int, np.ndarray]], None]] = None
        self._pending: Dict[int, _Txn] = {}
        self.committed: List[Tuple[int, np.ndarray]] = []

    # --- pre-commit side -----------------------------------------------------

    def absorb(self, epoch: int, keys: np.ndarray, values: np.ndarray,
               timestamps: np.ndarray, valid: np.ndarray) -> None:
        """Append one block's sink emissions ([K, P, cap] arrays) to the
        epoch's pending transaction, sharded per subtask."""
        txn = self._pending.setdefault(epoch, _Txn(epoch))
        if txn.sealed:
            raise RuntimeError(f"epoch {epoch} transaction already sealed")
        p = keys.shape[1]
        for sub in range(p):
            m = valid[:, sub].reshape(-1)
            flat = np.stack([keys[:, sub].reshape(-1)[m],
                             values[:, sub].reshape(-1)[m],
                             timestamps[:, sub].reshape(-1)[m]], axis=1)
            txn.shards.setdefault(sub, []).append(flat)

    def seal(self, epoch: int) -> None:
        """Epoch fence: the transaction stops accepting records
        (pre-commit; reference preCommit on snapshot)."""
        txn = self._pending.setdefault(epoch, _Txn(epoch))
        txn.sealed = True
        if self.pre_committer is not None:
            self.pre_committer(epoch, self._merged_shards(txn))

    @staticmethod
    def _merged_shards(txn: _Txn) -> Dict[int, np.ndarray]:
        return {s: (np.concatenate(txn.shards[s], axis=0)
                    if txn.shards[s] else np.zeros((0, 3), np.int32))
                for s in sorted(txn.shards)}

    # --- commit / abort ------------------------------------------------------

    def commit(self, epoch: int) -> None:
        """Checkpoint complete: externalize every sealed transaction up to
        ``epoch``, subtask-major within an epoch (commits are ordered;
        reference commit on notifyCheckpointComplete)."""
        for e in sorted(self._pending):
            if e > epoch:
                break
            txn = self._pending.pop(e)
            parts = [np.concatenate(txn.shards[s], axis=0)
                     for s in sorted(txn.shards) if txn.shards[s]]
            recs = (np.concatenate(parts, axis=0) if parts
                    else np.zeros((0, 3), np.int32))
            self.committed.append((e, recs))
            if self.committer is not None:
                self.committer(e, recs)

    def drop_uncommitted_shards(self, sub: int) -> List[int]:
        """Sink-subtask failure: its pending shards lived with the task
        and are lost; recovery rebuilds them from replayed outputs."""
        lost = []
        for e, txn in self._pending.items():
            if sub in txn.shards:
                del txn.shards[sub]
                lost.append(e)
        return sorted(lost)

    def rebuild_shard(self, epoch: int, sub: int,
                      records: np.ndarray) -> None:
        """Install a replay-reconstructed shard for (epoch, subtask) —
        and re-persist its pending part if the epoch already sealed (the
        replayed bytes are bit-identical; the overwrite is the abort +
        regenerate of the reference's recoverAndAbort)."""
        txn = self._pending.setdefault(epoch, _Txn(epoch))
        txn.shards[sub] = [records]
        if txn.sealed and self.pre_committer is not None:
            self.pre_committer(epoch, {sub: np.asarray(records, np.int32)})

    # --- introspection -------------------------------------------------------

    def pending_shards(self, epoch: int) -> Dict[int, np.ndarray]:
        """One epoch's accumulated per-subtask ``[n, 3]`` records (the
        merged view :meth:`seal` pre-commits) — empty when the epoch has
        no pending transaction. Read-only: the lineage plane scans this
        at the fence for dyed sink termini."""
        txn = self._pending.get(epoch)
        return self._merged_shards(txn) if txn is not None else {}

    def committed_stream(self) -> np.ndarray:
        """All committed records in commit order — what the external
        consumer has observed."""
        if not self.committed:
            return np.zeros((0, 3), np.int32)
        return np.concatenate([r for _, r in self.committed], axis=0)

    def pending_epochs(self) -> List[int]:
        return sorted(self._pending)
