"""clonos_tpu — a TPU-native stream-processing fault-tolerance framework.

Capabilities of Clonos (PSilvestre/Clonos, SIGMOD '21; causal logging +
standby tasks + in-flight-log replay on Apache Flink 1.7), re-imagined for
JAX/XLA/Pallas on TPU:

- exactly-once, highly-available streaming dataflows
- nondeterminism tolerated via *determinant logging*: input interleaving
  order, timestamps, RNG draws, timer firings, checkpoint RPC arrivals and
  output buffer cuts are recorded as packed fixed-width tensor records in HBM
- determinant replication rides step-boundary collectives over the device
  mesh instead of per-message Netty piggybacking
- recovery replay is a vectorized XLA scan over the determinant tensors
- standby tasks restore pushed checkpoints and replay only the lost epochs

Layer map (mirrors SURVEY.md §1 of the reference):
  api/       user API: StreamExecutionEnvironment, DataStream, services
  graph/     StreamGraph -> JobGraph translation, vertex graph info
  runtime/   task plane: superstep executor, channels, checkpoints, scheduler
  causal/    the causal fault-tolerance core (determinants, logs, recovery)
  inflight/  epoch-scoped in-flight log of emitted batches (spillable)
  parallel/  mesh/sharding/collective helpers
  ops/       Pallas kernels for the hot paths
  config/    typed configuration system
"""

from clonos_tpu.version import __version__

__all__ = ["__version__"]
