"""Static determinism lint for the Clonos causal-services contract.

``clonos_tpu lint [paths...]`` — see ``core`` for the rule registry,
``nondet``/``tracesafe``/``concurrency``/``markers``/``overlapwindow``
for the rule families, ``waivers`` for exemption syntax, ``runner`` for
the driver.

Importing this package registers every built-in rule; external rules
register the same way (subclass ``Rule``, decorate with
``register_rule``) before calling ``run_lint``.
"""

from clonos_tpu.lint.core import (ERROR, WARNING, RULES, FileContext,
                                  Finding, Rule, all_rules,
                                  register_rule, rule_names)
# Rule modules register themselves on import — order is alphabetical
# and irrelevant; each touches only the registry.
from clonos_tpu.lint import concurrency  # noqa: F401
from clonos_tpu.lint import markers      # noqa: F401
from clonos_tpu.lint import nondet       # noqa: F401
from clonos_tpu.lint import overlapwindow  # noqa: F401
from clonos_tpu.lint import tracesafe    # noqa: F401
from clonos_tpu.lint.runner import (DEFAULT_WAIVER_FILE, LintResult,
                                    format_json, format_text, run_lint)

__all__ = [
    "ERROR", "WARNING", "RULES", "FileContext", "Finding", "Rule",
    "all_rules", "register_rule", "rule_names",
    "DEFAULT_WAIVER_FILE", "LintResult", "format_json", "format_text",
    "run_lint",
]
