"""Rule family 3: lock discipline in the threaded runtime.

runtime/cluster.py, runtime/checkpoint.py, runtime/dispatcher.py and
obs/history.py document shared attributes as lock-guarded
(``_writer_lock``, ``_lock``, ``_rjit_lock``): every mutation of the
guarded state is supposed to happen inside ``with self.<lock>:``. The guard set is inferred rather
than declared: an attribute counts as guarded once any method mutates
it under the lock. A mutation of a guarded attribute on a path that
provably never holds the lock is then a finding — exactly the
``storage.mark_complete`` race this rule was built to catch.

Approximations, chosen to keep the rule quiet on correct code:

- ``__init__`` is exempt (no concurrent access before construction
  completes — the repo-wide convention).
- Methods named ``*_locked`` assert the caller's lock by convention;
  they are treated as lock-held, and so is any method *only* reachable
  from lock-held contexts (a fixed point over the intra-class call
  graph).
- Reads are not flagged — the runtime deliberately does lock-free
  reads of monotonic state (double-checked ``_jitted`` cache); only
  stores and mutating method calls count.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  register_rule)

#: attribute names that look like locks when used as `with self.X:`.
_LOCK_HINT = ("lock", "mutex", "cond")

#: constructor dotted names that make an attribute a lock regardless of
#: what it is called — `self._cv = threading.Condition()` guards state
#: exactly like `self._lock` does, and the race pass must agree with
#: the lint on that.
LOCK_TYPES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}

#: method names whose call mutates the receiver.
MUTATING_METHODS = {
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "clear", "pop", "popleft", "appendleft", "setdefault", "write",
    "mark_complete", "delete", "compact_ledger", "flush", "truncate",
}

#: exempt methods: construction and teardown run single-threaded.
EXEMPT_METHODS = {"__init__", "__new__", "__enter__", "__del__",
                  "__repr__", "__str__"}


def _lock_attr(node: ast.AST,
               known: frozenset = frozenset()) -> Optional[str]:
    """`self._writer_lock` (possibly through one hop like
    `self.jm._lock`) used as a context manager -> its attribute name.
    ``known`` extends the name hints with attributes proven to be locks
    by their constructor type (:func:`lock_attrs`)."""
    if isinstance(node, ast.Attribute) \
            and (any(h in node.attr.lower() for h in _LOCK_HINT)
                 or node.attr in known):
        return node.attr
    return None


def lock_attrs(ctx: FileContext) -> frozenset:
    """Attribute names assigned a :data:`LOCK_TYPES` constructor
    anywhere in the file (``self._cv = threading.Condition()``) — the
    type-based half of guard recognition, feeding :func:`_lock_attr`'s
    ``known`` set so oddly-named guards still count."""
    out = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            dotted = ctx.resolve(node.value.func)
            if dotted in LOCK_TYPES:
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        out.add(a)
    return frozenset(out)


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X...` -> base attribute name X (`self._r._parts[s]` -> _r)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        node = node.value
    return None


class _MethodScan:
    """Per-method facts: mutations split by lock-held/not, whether the
    method ever takes a lock, and intra-class calls made outside locks."""

    def __init__(self, cls_name: str, fn: ast.FunctionDef,
                 known: frozenset = frozenset()):
        self.cls_name = cls_name
        self.fn = fn
        self.name = fn.name
        self.known = known
        #: attr -> [lineno] mutated while a lock is held
        self.locked_mut: Dict[str, List[int]] = {}
        #: attr -> [(lineno, verb)] mutated with no lock held
        self.unlocked_mut: Dict[str, List[Tuple[int, str]]] = {}
        self.takes_lock = False
        #: self.method() calls made outside any lock region
        self.unlocked_calls: Set[str] = set()
        self._walk(fn.body, depth=0)

    def _walk(self, stmts, depth: int) -> int:
        # Bare `self._lock.acquire()` / `.release()` statements adjust
        # the depth for SUBSEQUENT statements, so `acquire()` +
        # try/finally-`release()` counts as a locked region exactly
        # like `with self._lock:` does.
        for stmt in stmts:
            depth = self._visit(stmt, depth)
        return depth

    def _bare_lock_verb(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("acquire", "release") \
                and _lock_attr(expr.func.value,
                               self.known) is not None:
            return expr.func.attr
        return None

    def _visit(self, node: ast.AST, depth: int) -> int:
        if isinstance(node, ast.Expr):
            verb = self._bare_lock_verb(node.value)
            if verb == "acquire":
                self.takes_lock = True
                return depth + 1
            if verb == "release":
                return max(depth - 1, 0)
        if isinstance(node, ast.With):
            inner = depth
            for item in node.items:
                if _lock_attr(item.context_expr,
                              self.known) is not None:
                    self.takes_lock = True
                    inner = depth + 1
            self._walk(node.body, inner)
            return depth
        if isinstance(node, ast.Try):
            d = self._walk(node.body, depth)
            for h in node.handlers:
                self._walk(h.body, depth)
            d = self._walk(node.orelse, d)
            return self._walk(node.finalbody, d)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # Nested defs run later, possibly on another thread — their
            # bodies are analysed as lock-free.
            body = node.body if isinstance(node.body, list) else [node.body]
            self._walk(body, 0)
            return depth
        self._record(node, depth)
        for child in ast.iter_child_nodes(node):
            self._visit(child, depth)
        return depth

    def _record(self, node: ast.AST, depth: int):
        attr = None
        verb = "stores to"
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                a = _self_attr(t)
                if a is not None:
                    attr = a
        elif isinstance(node, ast.Delete):
            # `del self._jobs[jid]` mutates the container just like a
            # store does — the dispatcher's job table shrinks this way.
            for t in node.targets:
                a = _self_attr(t)
                if a is not None:
                    attr = a
                    verb = "deletes from"
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATING_METHODS:
                attr = _self_attr(node.func.value)
                verb = f"calls .{node.func.attr}() on"
            elif isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" and depth == 0:
                self.unlocked_calls.add(node.func.attr)
        if attr is None:
            return
        if depth > 0:
            self.locked_mut.setdefault(attr, []).append(node.lineno)
        else:
            self.unlocked_mut.setdefault(attr, []).append(
                (node.lineno, verb))


@register_rule
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("lock-guarded shared attribute mutated on a path "
                   "not holding the lock")

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        known = lock_attrs(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node, known))
        return out

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     known: frozenset) -> List[Finding]:
        scans = [
            _MethodScan(cls.name, item, known) for item in cls.body
            if isinstance(item, ast.FunctionDef)
        ]
        if not any(s.takes_lock for s in scans):
            return []                  # class is not lock-disciplined

        # Guarded set: mutated under a lock by any non-exempt method.
        guarded: Set[str] = set()
        for s in scans:
            if s.name in EXEMPT_METHODS:
                continue
            guarded.update(s.locked_mut)
        # Lock attributes themselves are assigned, not guarded state.
        guarded = {a for a in guarded
                   if not any(h in a.lower() for h in _LOCK_HINT)
                   and a not in known}
        if not guarded:
            return []

        # Fixed point: a method is "lock-held" if named *_locked, or if
        # every intra-class caller only reaches it from inside a lock.
        by_name = {s.name: s for s in scans}
        held = {s.name for s in scans if s.name.endswith("_locked")}
        callers: Dict[str, Set[str]] = {s.name: set() for s in scans}
        for s in scans:
            for callee in s.unlocked_calls:
                if callee in callers:
                    callers[callee].add(s.name)
        # Methods called from at least one non-held context, seeded with
        # public entry points (anything can call those unlocked).
        changed = True
        while changed:
            changed = False
            for s in scans:
                if s.name in held or s.name in EXEMPT_METHODS:
                    continue
                unlocked_callers = {c for c in callers[s.name]
                                    if c not in held
                                    and c not in EXEMPT_METHODS}
                # Called intra-class, and every such call site sits
                # inside a lock region -> treat body as lock-held.
                called_anywhere = any(
                    s.name in o.unlocked_calls
                    or self._called_locked(o, s.name, known)
                    for o in scans if o is not s)
                if called_anywhere and not unlocked_callers \
                        and self._only_called_locked(scans, s.name,
                                                     known):
                    held.add(s.name)
                    changed = True

        out: List[Finding] = []
        for s in scans:
            if s.name in EXEMPT_METHODS or s.name in held:
                continue
            for attr, sites in s.unlocked_mut.items():
                if attr not in guarded:
                    continue
                for lineno, verb in sites:
                    out.append(self.finding(
                        ctx, lineno,
                        f"{cls.name}.{s.name} {verb} `self.{attr}` "
                        f"without holding the lock that guards it "
                        f"elsewhere in {cls.name} — a concurrent "
                        f"locked writer can interleave; wrap the "
                        f"mutation in the guarding `with` block"))
        return out

    @staticmethod
    def _called_locked(scan: "_MethodScan", name: str,
                       known: frozenset = frozenset()) -> bool:
        """Does ``scan`` call self.<name>() from inside a lock region?"""
        found = False

        def visit(node, depth):
            nonlocal found
            if isinstance(node, ast.With):
                inner = depth
                for item in node.items:
                    if _lock_attr(item.context_expr,
                                  known) is not None:
                        inner = depth + 1
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == name \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" and depth > 0:
                found = True
            for child in ast.iter_child_nodes(node):
                visit(child, depth)

        for stmt in scan.fn.body:
            visit(stmt, 0)
        return found

    def _only_called_locked(self, scans, name: str,
                            known: frozenset = frozenset()) -> bool:
        any_call = False
        for o in scans:
            if name in o.unlocked_calls:
                return False
            if self._called_locked(o, name, known):
                any_call = True
        return any_call
