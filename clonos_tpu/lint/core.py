"""Lint core: findings, the pluggable rule registry, per-file context.

The Clonos exactly-once guarantee is a *contract*: every
nondeterministic decision in operator/runtime code must flow through the
causal services (causal/services.py) so it lands in the determinant log
and replays bit-identically. PR 3's audit ledger enforces that contract
at runtime — a violation shows up as a ``recovery.audit.divergence``
long after the offending line was written. This package enforces it
*statically*: an AST pass over pipeline and runtime code that names the
exact file:line where nondeterminism escapes the log.

Rules are pluggable the same way determinant types are
(causal/determinant.py's registry): each rule subclasses :class:`Rule`
and registers under a stable name via :func:`register_rule`; waivers
(clonos_tpu/lint/waivers.py) reference those names, so an unknown name
in a waiver is itself a finding.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional

#: severity levels; only unwaived ERROR findings fail the exit code.
ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass
class Finding:
    """One lint finding, addressable as ``path:line`` (repo-relative)."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = ERROR
    waived: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "severity": self.severity, "waived": self.waived,
                "message": self.message}


def _is_test_path(path: str) -> bool:
    parts = path.split("/")
    return "tests" in parts or parts[-1] == "conftest.py"


class Rule:
    """One checkable clause of the determinism contract.

    Subclasses set ``name`` (stable — waivers reference it),
    ``description`` (one line, shown by ``lint --list-rules``), and
    implement :meth:`check`. ``applies_to`` scopes a rule by path:
    the default skips test files — tests exercise clocks and threads
    legitimately and are not pipeline code (the markers rule inverts
    this)."""

    name: str = ""
    description: str = ""
    severity: str = ERROR

    def applies_to(self, path: str) -> bool:
        return not _is_test_path(path)

    def check(self, ctx: "FileContext") -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", line: int,
                message: str) -> Finding:
        return Finding(rule=self.name, path=ctx.path, line=line,
                       message=message, severity=self.severity)


#: rule registry: name -> instance (the determinant-type-registry shape).
RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and register a rule by its name."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if inst.name in RULES:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    RULES[inst.name] = inst
    return cls


def rule_names() -> List[str]:
    return sorted(RULES)


def all_rules() -> List[Rule]:
    return [RULES[n] for n in sorted(RULES)]


class FileContext:
    """One parsed file: source lines, AST, and import-alias resolution.

    ``resolve(node)`` maps a Name/Attribute expression to its canonical
    dotted path — ``_time.time`` under ``import time as _time`` resolves
    to ``time.time``; ``datetime.now`` under ``from datetime import
    datetime`` resolves to ``datetime.datetime.now`` — so rules match
    *what is called*, not how the import spelled it."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._aliases = self._collect_aliases(self.tree)

    @staticmethod
    def _collect_aliases(tree: ast.AST) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    continue             # relative: package-internal
                for a in node.names:
                    aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")
        # Conventional shorthands resolve to canonical module names.
        for local, canon in list(aliases.items()):
            if canon == "numpy":
                aliases[local] = "numpy"
        return aliases

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, or None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self._aliases.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""
