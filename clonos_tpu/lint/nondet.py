"""Rule family 1: nondeterminism escapes.

The causal-services contract (causal/services.py, PAPER.md's
``getTimeService()`` wrappers): wall clocks, RNG draws, and entropy
reads in anything reachable from operator/source/sink/timer code must
be routed through a causal service so the value is logged as a
determinant and replays bit-identically. A direct ``time.time()`` or
``os.urandom()`` produces a value the determinant log never sees —
exactly the bug class ``examples/audit_nondet.py`` plants and the PR-3
runtime audit catches as a ``recovery.audit.divergence``; these rules
catch it at review time instead, naming the line.

Legitimate wall reads exist (lease clocks in runtime/leader.py, span
timestamps in obs/trace.py — observability metadata, never replayed
data); those carry ``# clonos: allow(<rule>)`` waivers with a one-line
justification rather than being silently exempt.
"""

from __future__ import annotations

import ast
from typing import List, Set

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  register_rule)

#: wall-clock reads — comparable across processes, different on replay.
WALLCLOCK = {
    "time.time", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

#: process-seeded / global RNG draws (a seeded
#: ``np.random.RandomState(seed)`` is deterministic and NOT flagged).
RNG_FNS = {
    "random.random", "random.randint", "random.randrange",
    "random.choice", "random.choices", "random.shuffle",
    "random.sample", "random.uniform", "random.getrandbits",
    "random.gauss", "random.normalvariate", "random.betavariate",
    "random.expovariate", "random.triangular",
}
NP_RANDOM_DRAWS = {
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "bytes", "exponential", "poisson",
}
#: RNG constructors that are only deterministic when explicitly seeded.
SEEDABLE_CTORS = {
    "random.Random", "numpy.random.RandomState",
    "numpy.random.default_rng",
}

#: pure entropy: different every process, by design.
ENTROPY = {
    "os.urandom",
    "uuid.uuid1", "uuid.uuid3", "uuid.uuid4", "uuid.uuid5",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.randbelow", "secrets.choice",
}

#: process identity: stable within one process, different on every
#: restart — anything derived from it diverges on recovery exactly like
#: entropy does (a pid-salted key is the audit_nondet SALT bug in
#: disguise).
PROCESS_IDENTITY = {
    "os.getpid", "os.getppid",
}


class _ResolvedRefRule(Rule):
    """Shared walk: flag every Name/Attribute whose canonical dotted
    name lands in the rule's match set — references count, not just
    calls (``clock=time.time`` stashes the wall clock just as surely as
    calling it)."""

    matches: Set[str] = set()

    def message(self, dotted: str) -> str:
        raise NotImplementedError

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            dotted = ctx.resolve(node)
            if dotted is None or dotted not in self.matches:
                continue
            key = (node.lineno, dotted)
            # one finding per (line, symbol): `time.time` is both an
            # Attribute and, on the call path, the func of a Call.
            if key in seen:
                continue
            seen.add(key)
            out.append(self.finding(ctx, node.lineno,
                                    self.message(dotted)))
        return out


@register_rule
class WallclockRule(_ResolvedRefRule):
    name = "wallclock"
    description = ("direct wall-clock read (time.time / datetime.now) "
                   "outside the causal time service")
    matches = WALLCLOCK

    def message(self, dotted: str) -> str:
        return (f"direct wall-clock read `{dotted}` bypasses the causal "
                f"time service — replay sees a different value; use "
                f"ctx.time / CausalTimeService.current_time_millis(), "
                f"or waive with a justification if the value is never "
                f"replayed data")


@register_rule
class RngRule(_ResolvedRefRule):
    name = "rng"
    description = ("global/unseeded RNG draw outside the causal random "
                   "service")
    matches = RNG_FNS | {f"numpy.random.{f}" for f in NP_RANDOM_DRAWS}

    def message(self, dotted: str) -> str:
        return (f"global RNG draw `{dotted}` is not logged as a "
                f"determinant — replay re-draws a different value; use "
                f"ctx.rng_bits / CausalRandomService.next_int(), or a "
                f"seeded np.random.RandomState carried in state")

    def check(self, ctx: FileContext) -> List[Finding]:
        out = super().check(ctx)
        # Unseeded constructor calls: `np.random.RandomState()` seeds
        # from OS entropy; with an explicit seed it is deterministic.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve(node.func)
            if dotted in SEEDABLE_CTORS and not node.args \
                    and not node.keywords:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"`{dotted}()` without a seed draws its state from "
                    f"OS entropy — pass an explicit seed so replay "
                    f"reconstructs the same stream"))
        return out


@register_rule
class EntropyRule(_ResolvedRefRule):
    name = "entropy"
    description = ("os.urandom / uuid / secrets / os.getpid read "
                   "(fresh per process)")
    matches = ENTROPY | PROCESS_IDENTITY

    def message(self, dotted: str) -> str:
        if dotted in PROCESS_IDENTITY:
            return (f"`{dotted}` changes on every restart — a value "
                    f"derived from the process id diverges on recovery "
                    f"just like entropy; key on logged job/subtask "
                    f"identity instead, or waive with a justification "
                    f"if the value is never replayed data")
        return (f"`{dotted}` is fresh entropy every process — a "
                f"restarted worker computes different values from the "
                f"same replayed inputs (the audit_nondet SALT bug); "
                f"route it through a causal service or derive it from "
                f"logged determinants")


@register_rule
class UnorderedIterRule(Rule):
    name = "unordered-iter"
    description = ("iteration over a set feeding ordered output "
                   "(serialization paths must be order-stable)")

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        iters: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                iters.extend(g.iter for g in node.generators)
        for it in iters:
            if self._is_set_expr(ctx, it):
                out.append(self.finding(
                    ctx, it.lineno,
                    "iterating a set is unordered across processes — "
                    "serialized output (causal/serde.py frames, wire "
                    "headers, digests) built from it diverges on "
                    "replay; wrap in sorted(...)"))
        return out

    @staticmethod
    def _is_set_expr(ctx: FileContext, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            dotted = ctx.resolve(node.func)
            return dotted in {"set", "frozenset"}
        return False
