"""Marker registry rule (absorbed from ``tools/check_markers.py``).

Every ``pytest.mark.<name>`` in tests/ must be either a pytest builtin
or registered in :data:`REGISTERED_MARKERS` (which
tests/conftest.py registers with pytest at configure time, keeping this
module the single source of truth). Unregistered markers are silent
no-ops under ``-m`` filters — a test tagged with a typo'd ``slow``
would run in tier-1 forever.

``tools/check_markers.py`` remains as a thin shim over this module
(the ``replay_dissect`` -> ``dissect`` precedent), so both
``python tools/check_markers.py`` and ``clonos_tpu lint tests/``
enforce the same registry.
"""

from __future__ import annotations

import os
import re
from typing import List

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  _is_test_path, register_rule)

#: Markers this repo registers (tier-1 deselects `slow`).
REGISTERED_MARKERS = {
    "slow": "long-running test, excluded from the tier-1 gate "
            "(-m 'not slow')",
}

#: Pytest's own markers — always legal, never need registration.
BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}

_MARK_RE = re.compile(r"\bpytest\.mark\.([A-Za-z_]\w*)")


@register_rule
class MarkersRule(Rule):
    name = "markers"
    description = ("pytest marker not registered in "
                   "clonos_tpu/lint/markers.py:REGISTERED_MARKERS")

    def applies_to(self, path: str) -> bool:
        # Inverted scope: this is the one rule that checks *tests*.
        return _is_test_path(path)

    def check(self, ctx: FileContext) -> List[Finding]:
        allowed = BUILTIN_MARKERS | set(REGISTERED_MARKERS)
        out: List[Finding] = []
        for lineno, line in enumerate(ctx.lines, 1):
            for m in _MARK_RE.finditer(line):
                name = m.group(1)
                if name not in allowed:
                    out.append(self.finding(
                        ctx, lineno,
                        f"unregistered marker {name!r} — a typo'd "
                        f"marker silently passes -m filters; register "
                        f"it in clonos_tpu/lint/markers.py:"
                        f"REGISTERED_MARKERS"))
        return out


def check(tests_dir) -> List[str]:
    """Scan ``tests_dir`` for marker uses; return a list of
    '<file>:<line>: unregistered marker <name>' violations.

    Kept line-compatible with the historical tools/check_markers.py
    output so the conftest wiring and the shim keep working."""
    rule = MarkersRule()
    violations: List[str] = []
    for fn in sorted(os.listdir(tests_dir)):
        if not fn.endswith(".py"):
            continue
        path = os.path.join(tests_dir, fn)
        with open(path) as f:
            source = f.read()
        ctx = FileContext(os.path.join("tests", fn), source)
        for finding in rule.check(ctx):
            m = re.search(r"marker ('[^']*')", finding.message)
            name = m.group(1) if m else "?"
            violations.append(
                f"{finding.location()}: unregistered marker {name}")
    return violations
