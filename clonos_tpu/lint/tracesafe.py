"""Rule family 2: trace safety of operator step functions.

Operator bodies (``process`` / ``process2`` / ``process_block`` methods
and the lambdas handed to ``StreamEnvironment.map``/``filter``) compile
into ONE fused XLA program (api/operators.py). Three host-level
constructs silently break that:

- **host branches** — a Python ``if``/``while``/ternary on a traced
  value either fails to trace or, worse, bakes one branch in at trace
  time; either way replay and live runs can take different paths;
- **mutable closures** — a step function mutating a captured host
  object smuggles state around the carry: it is invisible to the
  checkpoint, so a rebuilt worker starts from a different value;
- **host callbacks** — ``print``/``open``/``jax.pure_callback`` inside
  the compiled block run at trace time or punch host round-trips into
  the fused scan, and their effects are not replayed.

Static config branches (``if self.reduce_fn is not jnp.add``) are fine
and not flagged: the rules trigger only on direct mentions of the step
function's traced parameters (state/batch/ctx and their kin), with
``.shape``/``.dtype``/``.ndim`` accesses exempt (shapes are static
under jit).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  register_rule)

#: operator entry points, per the Operator base contract.
TRACED_METHODS = {"process", "process2", "process_block",
                  "process_block_static_keys"}

#: StreamEnvironment combinators whose fn argument traces.
TRACED_COMBINATORS = {"map", "filter"}

#: attribute reads that are static under jit — mentions beneath them
#: are not host branches on traced *values*.
STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}

#: obviously-host calls that must not appear inside a compiled body.
HOST_CALLS = {
    "print", "input", "open", "breakpoint", "exec", "eval",
    "jax.debug.print", "jax.debug.callback", "jax.pure_callback",
    "jax.experimental.io_callback", "jax.experimental.host_callback.call",
}
#: method calls that force a device sync mid-trace.
HOST_METHOD_CALLS = {"item", "tolist", "block_until_ready"}

MUTATORS = {"append", "extend", "add", "update", "insert", "remove",
            "discard", "clear", "pop", "popleft", "appendleft",
            "setdefault", "write"}

#: decorators that put a def's body under trace: plain jit plus the
#: SPMD wrappers (pjit, shard_map) used by the mesh-sharded block
#: programs — a host branch inside any of them fails the same way.
TRACED_DECORATORS = frozenset({
    "jax.jit", "jit",
    "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
})


def _traced_roots(ctx: FileContext) -> List[Tuple[ast.AST, Set[str]]]:
    """(function node, traced param names) for every step-function body
    in the file: operator methods, jit/pjit/shard_map-wrapped defs, and
    combinator lambdas/defs."""
    roots: List[Tuple[ast.AST, Set[str]]] = []
    module_defs = {n.name: n for n in ctx.tree.body
                   if isinstance(n, ast.FunctionDef)}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name in TRACED_METHODS:
                    roots.append((item, _params(item, skip_self=True)))
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if ctx.resolve(target) in TRACED_DECORATORS:
                    roots.append((node, _params(node)))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in TRACED_COMBINATORS and node.args:
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                roots.append((fn, _params(fn)))
            elif isinstance(fn, ast.Name) and fn.id in module_defs:
                d = module_defs[fn.id]
                roots.append((d, _params(d)))
    return roots


def _params(fn: ast.AST, skip_self: bool = False) -> Set[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    for v in (a.vararg, a.kwarg):
        if v is not None:
            names.append(v.arg)
    if skip_self and names and names[0] in {"self", "cls"}:
        names = names[1:]
    return set(names)


def _walk_with_nested_params(root: ast.AST, traced: Set[str]):
    """Yield (node, traced-name set in scope): nested defs inside a
    traced body are traced too (vmapped/scanned helpers), with their own
    params joining the traced set."""
    stack = [(root, traced)]
    while stack:
        node, names = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.Lambda)):
                stack.append((child, names | _params(child)))
            else:
                stack.append((child, names))
            yield child, names


def _traced_mentions(ctx: FileContext, expr: ast.AST,
                     traced: Set[str]) -> Optional[str]:
    """First traced name mentioned in ``expr`` outside a static
    attribute chain, or None."""
    exempt = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    exempt.add(id(sub))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced \
                and id(node) not in exempt:
            return node.id
    return None


class _TracedBodyRule(Rule):
    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for root, traced in _traced_roots(ctx):
            self._check_body(ctx, root, traced, out)
        return out

    def _check_body(self, ctx, root, traced, out):
        raise NotImplementedError


@register_rule
class HostBranchRule(_TracedBodyRule):
    name = "host-branch"
    description = ("Python-level branch/loop on a traced value inside "
                   "a step function")

    def _check_body(self, ctx, root, traced, out):
        for node, names in _walk_with_nested_params(root, traced):
            test = None
            what = None
            if isinstance(node, (ast.If, ast.While)):
                test, what = node.test, "branches"
            elif isinstance(node, ast.IfExp):
                test, what = node.test, "selects"
            elif isinstance(node, ast.Assert):
                test, what = node.test, "asserts"
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                test, what = node.iter, "loops over"
            if test is None:
                continue
            hit = _traced_mentions(ctx, test, names)
            if hit is not None:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"host control flow {what} traced value `{hit}` — "
                    f"this does not trace into the fused block (or "
                    f"bakes one path in at compile time); use "
                    f"jnp.where / lax.cond / lax.scan"))


@register_rule
class MutableClosureRule(_TracedBodyRule):
    name = "mutable-closure"
    description = ("step function mutates captured host state outside "
                   "the carry")

    def _check_body(self, ctx, root, traced, out):
        local = _collect_locals(root)
        for node, _names in _walk_with_nested_params(root, traced):
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                local = local | _collect_locals(node)
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"step function rebinds enclosing name(s) "
                    f"{', '.join(node.names)} — state outside the "
                    f"carry is invisible to checkpoints and diverges "
                    f"on replay; thread it through operator state"))
                continue
            base = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = _base_name(t)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS:
                base = _base_name(node.func.value)
            if base is not None and base not in local \
                    and base not in {"self", "cls"}:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"step function mutates captured object `{base}` — "
                    f"host-side state outside the carry is not "
                    f"checkpointed and not replayed; carry it in "
                    f"operator state or log it as a determinant"))


@register_rule
class HostCallbackRule(_TracedBodyRule):
    name = "host-callback"
    description = ("host call (print/open/pure_callback/.item) inside "
                   "a compiled step function")

    def _check_body(self, ctx, root, traced, out):
        for node, names in _walk_with_nested_params(root, traced):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve(node.func)
            if dotted in HOST_CALLS:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"host call `{dotted}` inside a compiled step "
                    f"function runs at trace time (or forces a host "
                    f"round-trip) and its effect is not replayed"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in HOST_METHOD_CALLS \
                    and _traced_mentions(ctx, node.func.value,
                                         names) is not None:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"`.{node.func.attr}()` on a traced value forces a "
                    f"device sync inside the compiled block — keep the "
                    f"computation on-device"))


def _collect_locals(fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn`` (params, assignments, loop targets,
    comprehension targets, with-as, nested def names)."""
    names = _params(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)
            elif isinstance(node, ast.FunctionDef):
                names.add(node.name)
            elif isinstance(node, ast.comprehension):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
    return names


def _base_name(node: ast.AST) -> Optional[str]:
    """Peel Attribute/Subscript chains to the root Name id."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None
