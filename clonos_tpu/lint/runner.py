"""Lint driver: file collection, rule dispatch, waiver application,
and the ``--report json`` / exit-code contract shared with
``clonos_tpu audit``.

Exit semantics (CI contract): exit 1 iff any unwaived ERROR-severity
finding remains; waived findings and WARNING findings (stale waivers)
never fail the run but are always reported.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, List, Optional, Sequence

from clonos_tpu.lint.core import (ERROR, WARNING, RULES, FileContext,
                                  Finding, all_rules)
from clonos_tpu.lint.waivers import (WaiverSet, collect_inline,
                                     load_waiver_file)

#: repo-level waiver file, discovered next to the linted tree.
DEFAULT_WAIVER_FILE = ".clonos-waivers"

#: synthetic rule for files the AST cannot parse.
SYNTAX = "syntax"

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "build", "dist",
              ".pytest_cache"}


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files: List[str]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == ERROR and not f.waived]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == WARNING and not f.waived]

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def ok(self) -> bool:
        return not self.errors

    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files": len(self.files),
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "waived": len(self.waived),
            "findings": [f.to_dict() for f in self.findings],
        }


def _norm(path: str) -> str:
    """Repo-relative forward-slash path (finding addresses are stable
    across where the linter was invoked from)."""
    rel = os.path.relpath(path)
    return rel.replace(os.sep, "/")


def collect_files(paths: Sequence[str],
                  waivers: Optional[WaiverSet] = None) -> List[str]:
    """Expand targets to .py files. Directories are walked with
    ``exclude`` waivers applied; explicitly-named files are ALWAYS
    linted, exclusions notwithstanding — pointing the linter at a file
    is the override."""
    out: List[str] = []
    seen = set()

    def add(p: str):
        n = _norm(p)
        if n not in seen:
            seen.add(n)
            out.append(n)

    for target in paths:
        if os.path.isfile(target):
            if waivers is not None:
                waivers.excluded(_norm(target), mark_only=True)
            add(target)
            continue
        if waivers is not None and os.path.isdir(target):
            waivers.traversed = True
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = _norm(os.path.join(dirpath, fn))
                if waivers is not None and waivers.excluded(p):
                    continue
                add(p)
    return out


def build_waivers(waiver_file: Optional[str] = None,
                  use_waivers: bool = True) -> WaiverSet:
    """Assemble the run's WaiverSet from the repo-level waiver file
    (inline waivers join per-file during the lint pass)."""
    ws = WaiverSet()
    if not use_waivers:
        return ws
    path = waiver_file
    if path is None and os.path.isfile(DEFAULT_WAIVER_FILE):
        path = DEFAULT_WAIVER_FILE
    if path is not None and os.path.isfile(path):
        entries, problems = load_waiver_file(_norm(path))
        ws.entries = entries
        ws.waiver_path = _norm(path)
        ws.problems.extend(problems)
    return ws


def run_lint(paths: Sequence[str],
             waiver_file: Optional[str] = None,
             use_waivers: bool = True,
             rules: Optional[Iterable[str]] = None) -> LintResult:
    """Lint ``paths`` (files and/or directories) and return the result.

    ``rules`` restricts the run to a subset of registry names (used by
    tests and ``lint --rule``); unknown names raise ValueError so a
    typo'd CI invocation fails loudly rather than checking nothing."""
    ws = build_waivers(waiver_file, use_waivers)
    files = collect_files(paths, ws if use_waivers else None)

    if rules is not None:
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
        active = [RULES[n] for n in sorted(set(rules))]
    else:
        active = all_rules()

    findings: List[Finding] = []
    for path in files:
        try:
            with open(path) as f:
                source = f.read()
            ctx = FileContext(path, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            findings.append(Finding(
                rule=SYNTAX, path=path,
                line=getattr(exc, "lineno", None) or 1,
                severity=ERROR,
                message=f"file does not parse: {exc}"))
            continue
        file_findings: List[Finding] = []
        for rule in active:
            if rule.applies_to(path):
                file_findings.extend(rule.check(ctx))
        if use_waivers:
            inline, problems = collect_inline(ctx)
            ws.inline.extend(inline)
            ws.problems.extend(problems)
        findings.extend(file_findings)

    if use_waivers:
        for f in findings:
            if ws.waive(f):
                f.waived = True
        findings.extend(ws.problems)
        findings.extend(ws.stale())

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=findings, files=files)


def format_text(result: LintResult, verbose: bool = False) -> str:
    """Human-readable report: one ``path:line: [rule] message`` per
    finding plus a summary line (waived findings only with -v)."""
    lines: List[str] = []
    for f in result.findings:
        if f.waived and not verbose:
            continue
        tag = f"[{f.rule}]"
        if f.waived:
            tag += " (waived)"
        elif f.severity == WARNING:
            tag += " (warning)"
        lines.append(f"{f.location()}: {tag} {f.message}")
    lines.append(
        f"lint: {len(result.files)} file(s), "
        f"{len(result.errors)} error(s), "
        f"{len(result.warnings)} warning(s), "
        f"{len(result.waived)} waived")
    return "\n".join(lines)


def format_json(result: LintResult) -> str:
    """One machine-readable line (the ``clonos_tpu audit`` convention)."""
    return json.dumps(result.to_dict(), sort_keys=True)
