"""Waivers: every exemption is explicit, named, and auditable.

Two mechanisms, both referencing registry rule names (core.RULES):

- **inline** — ``# clonos: allow(rule[, rule...])`` on the offending
  line, or on a comment-only line directly above it. The rest of the
  comment is the justification; the self-lint waivers in
  runtime/leader.py and obs/trace.py are the exemplars.
- **waiver file** — repo-level ``.clonos-waivers``: ``<rule> <glob>``
  waives a rule across matching files; ``exclude <glob>`` drops files
  from *directory traversal* entirely. Explicitly-named command-line
  targets override ``exclude`` (the eslint ``--no-ignore`` convention)
  — that is how ``clonos_tpu lint examples/`` passes while
  ``clonos_tpu lint examples/audit_nondet.py`` still fails.

Misuse is itself reported: an unknown rule name in any waiver is an
ERROR finding (a typo'd waiver that silently waives nothing is worse
than no waiver), and a waiver that no longer matches any finding is a
*stale* WARNING — delete it, the code it excused is gone.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import io
import re
import tokenize
from typing import List, Optional, Set, Tuple

from clonos_tpu.lint.core import (ERROR, WARNING, FileContext, Finding,
                                  RULES)

INLINE_RE = re.compile(r"#\s*clonos:\s*allow\(([^)]*)\)")

#: synthetic rule names for waiver-machinery findings (not waivable).
UNKNOWN_RULE = "waiver-unknown-rule"
STALE_WAIVER = "stale-waiver"


@dataclasses.dataclass
class InlineWaiver:
    path: str
    line: int                 # line the waiver comment sits on
    target: int               # line whose findings it waives
    rules: Set[str]
    used: bool = False


@dataclasses.dataclass
class FileWaiverEntry:
    rule: str                 # rule name, or "exclude"
    pattern: str
    lineno: int
    used: bool = False


@dataclasses.dataclass
class WaiverSet:
    inline: List[InlineWaiver] = dataclasses.field(default_factory=list)
    entries: List[FileWaiverEntry] = dataclasses.field(
        default_factory=list)
    waiver_path: Optional[str] = None
    #: findings produced by the waiver machinery itself
    problems: List[Finding] = dataclasses.field(default_factory=list)
    #: did this run traverse any directory? exclude staleness is only
    #: meaningful when traversal could have consulted the entry.
    traversed: bool = False

    def excluded(self, path: str, mark_only: bool = False) -> bool:
        """Should directory traversal skip ``path``? Explicit targets
        call with ``mark_only=True``: the entry is credited as used (so
        deliberately linting an excluded file is not a stale waiver)
        but the file is linted anyway — see module docstring."""
        hit = False
        for e in self.entries:
            if e.rule == "exclude" and _glob_match(path, e.pattern):
                e.used = True
                hit = True
        return hit and not mark_only

    def waive(self, finding: Finding) -> bool:
        """Mark ``finding`` waived if any waiver covers it."""
        hit = False
        for w in self.inline:
            if w.path == finding.path and w.target == finding.line \
                    and finding.rule in w.rules:
                w.used = True
                hit = True
        for e in self.entries:
            if e.rule == finding.rule \
                    and _glob_match(finding.path, e.pattern):
                e.used = True
                hit = True
        return hit

    def stale(self) -> List[Finding]:
        """WARNING findings for waivers that excused nothing.

        Waivers naming only analysis-owned rules (nondet-reach,
        thread-race, …) are the analysis runner's to second-guess —
        the per-file lint never produces their findings, so from here
        they always look unused."""
        owned = _analysis_owned_rules()
        out: List[Finding] = []
        for w in self.inline:
            if not w.used and not w.rules & {UNKNOWN_RULE} \
                    and w.rules - owned:
                out.append(Finding(
                    rule=STALE_WAIVER, path=w.path, line=w.line,
                    severity=WARNING,
                    message=f"stale waiver allow("
                            f"{', '.join(sorted(w.rules))}) — no "
                            f"finding on the waived line any more; "
                            f"delete the comment"))
        for e in self.entries:
            if not e.used and self.waiver_path is not None:
                if e.rule in owned:
                    continue
                if e.rule == "exclude" and not self.traversed:
                    continue
                what = ("exclude" if e.rule == "exclude"
                        else f"{e.rule} waiver")
                out.append(Finding(
                    rule=STALE_WAIVER, path=self.waiver_path,
                    line=e.lineno, severity=WARNING,
                    message=f"stale {what} for {e.pattern!r} — "
                            f"matched no file/finding this run"))
        return out


def _glob_match(path: str, pattern: str) -> bool:
    return fnmatch.fnmatch(path, pattern) \
        or fnmatch.fnmatch(path, pattern.rstrip("/") + "/*")


def _comment_lines(source: str) -> List[Tuple[int, str]]:
    """(lineno, comment text) for real COMMENT tokens only — a waiver
    mentioned inside a docstring or string literal is documentation,
    not a waiver (this module's own docs would otherwise trip it)."""
    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenizeError, IndentationError, SyntaxError):
        pass                      # unparseable files get SYNTAX findings
    return out


def _analysis_owned_rules() -> set:
    """Rules the whole-program analysis runner owns (lazy import — see
    :func:`_register_analysis_rules`). Empty when unavailable."""
    try:
        from clonos_tpu.analysis.runner import ANALYSIS_RULES
        return set(ANALYSIS_RULES)
    except ImportError:
        return set()


def _register_analysis_rules() -> None:
    """The analysis package owns the whole-program rules (nondet-reach,
    lock-order, thread-race, join-discipline, …) and registers them in
    the shared registry on import. Load it lazily so waivers naming
    those rules validate from a bare ``clonos_tpu lint`` run too — a
    function-level import, because the analysis package imports
    lint.core and a module-level import would cycle."""
    try:
        import clonos_tpu.analysis.runner  # noqa: F401
    except ImportError:            # analysis package absent/broken:
        pass                       # its rule names stay unknown


def collect_inline(ctx: FileContext) -> Tuple[List[InlineWaiver],
                                              List[Finding]]:
    """Parse ``# clonos: allow(...)`` comments in one file.

    A waiver on a comment-only line targets the next non-comment line
    (a multi-line justification block above the code works); a trailing
    waiver targets its own line. Unknown rule names are ERROR
    findings."""
    _register_analysis_rules()
    waivers: List[InlineWaiver] = []
    problems: List[Finding] = []
    for lineno, comment in _comment_lines(ctx.source):
        m = INLINE_RE.search(comment)
        if not m:
            continue
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        unknown = sorted(n for n in names if n not in RULES)
        for n in unknown:
            problems.append(Finding(
                rule=UNKNOWN_RULE, path=ctx.path, line=lineno,
                severity=ERROR,
                message=f"waiver names unknown rule {n!r} — known "
                        f"rules: {', '.join(sorted(RULES))}"))
        names -= set(unknown)
        if not names:
            continue
        line_text = ctx.line_text(lineno)
        if line_text.strip().startswith("#"):
            target = lineno + 1
            while target <= len(ctx.lines) \
                    and ctx.line_text(target).strip().startswith("#"):
                target += 1
        else:
            target = lineno
        waivers.append(InlineWaiver(path=ctx.path, line=lineno,
                                    target=target, rules=names))
    return waivers, problems


def load_waiver_file(path: str,
                     repo_text: Optional[str] = None
                     ) -> Tuple[List[FileWaiverEntry], List[Finding]]:
    """Parse a ``.clonos-waivers`` file: ``<rule> <glob>`` /
    ``exclude <glob>`` lines, ``#`` comments. Unknown rule names are
    ERROR findings anchored to the waiver file itself."""
    _register_analysis_rules()
    entries: List[FileWaiverEntry] = []
    problems: List[Finding] = []
    if repo_text is None:
        with open(path) as f:
            repo_text = f.read()
    for lineno, raw in enumerate(repo_text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) != 2:
            problems.append(Finding(
                rule=UNKNOWN_RULE, path=path, line=lineno,
                severity=ERROR,
                message=f"malformed waiver line {raw.strip()!r} — "
                        f"expected '<rule> <glob>' or 'exclude <glob>'"))
            continue
        rule, pattern = parts
        if rule != "exclude" and rule not in RULES:
            problems.append(Finding(
                rule=UNKNOWN_RULE, path=path, line=lineno,
                severity=ERROR,
                message=f"waiver file names unknown rule {rule!r} — "
                        f"known rules: {', '.join(sorted(RULES))}"))
            continue
        entries.append(FileWaiverEntry(rule=rule, pattern=pattern,
                                       lineno=lineno))
    return entries, problems
