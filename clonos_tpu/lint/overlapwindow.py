"""Rule family 5: host synchronization inside the pipelined-fence
overlap window.

The pipelined fence (runtime/cluster.py ``_begin_fence_tail``) promises
that everything between its ``# clonos: overlap-window-begin`` /
``# clonos: overlap-window-end`` markers is DISPATCH-ONLY: device
programs and async d2h starts, never a host block. One stray
``np.asarray`` / ``jax.block_until_ready`` there silently re-serializes
the exact tail the pipeline exists to hide — the steady-state headline
regresses with no functional symptom, which is why this is a lint rule
and not a test. The async-safe primitive ``copy_to_host_async`` is
explicitly allowed; its blocking cousins are not.
"""

from __future__ import annotations

import ast
from typing import List

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  register_rule)

BEGIN = "clonos: overlap-window-begin"
END = "clonos: overlap-window-end"

#: canonical dotted names that force a host synchronization.
SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.copy",
    "jax.block_until_ready", "jax.device_get",
}

#: method names that block regardless of receiver resolution
#: (``arr.block_until_ready()``); ``copy_to_host_async`` is the allowed
#: non-blocking start and deliberately absent.
SYNC_ATTRS = {"block_until_ready", "copy_to_host", "item", "tolist"}


def _windows(ctx: FileContext) -> List[tuple]:
    """(begin_line, end_line) pairs of every marked overlap window."""
    out, start = [], None
    for i, ln in enumerate(ctx.lines, start=1):
        if BEGIN in ln:
            start = i
        elif END in ln and start is not None:
            out.append((start, i))
            start = None
    return out


@register_rule
class OverlapWindowSyncRule(Rule):
    name = "overlap-window"
    description = ("host synchronization (np.asarray / "
                   "block_until_ready / device_get) inside a pipelined-"
                   "fence overlap window — re-serializes the hidden tail")

    def check(self, ctx: FileContext) -> List[Finding]:
        wins = _windows(ctx)
        out: List[Finding] = []
        # an unclosed begin marker is itself a finding: the window it
        # was supposed to bound is silently unchecked.
        opens = sum(BEGIN in ln for ln in ctx.lines)
        if opens != len(wins):
            out.append(self.finding(
                ctx, 1, "unbalanced overlap-window markers "
                        f"({opens} begin / {len(wins)} closed)"))
        if not wins:
            return out
        seen = set()
        for node in ast.walk(ctx.tree):
            line = getattr(node, "lineno", None)
            if line is None or not any(b < line < e for b, e in wins):
                continue
            dotted = None
            if isinstance(node, (ast.Attribute, ast.Name)):
                dotted = ctx.resolve(node)
            hit = None
            if dotted in SYNC_CALLS:
                hit = dotted
            elif (isinstance(node, ast.Attribute)
                  and node.attr in SYNC_ATTRS):
                hit = f"<expr>.{node.attr}"
            if hit is None or (line, hit) in seen:
                continue
            seen.add((line, hit))
            out.append(self.finding(
                ctx, line,
                f"`{hit}` blocks on device results inside the "
                f"pipelined-fence overlap window — keep the window "
                f"dispatch-only (copy_to_host_async is the async "
                f"primitive), or move the read to the fence worker"))
        return out
