"""Rule family 5: host synchronization inside marked dispatch-only
windows.

Two window families share the machinery:

- The pipelined fence (runtime/cluster.py ``_begin_fence_tail``)
  promises that everything between its ``# clonos:
  overlap-window-begin`` / ``# clonos: overlap-window-end`` markers is
  DISPATCH-ONLY: device programs and async d2h starts, never a host
  block. One stray ``np.asarray`` / ``jax.block_until_ready`` there
  silently re-serializes the exact tail the pipeline exists to hide.

- The batched read path (runtime/serve.py ``_dispatch``) makes the
  twin promise for serving: the region between ``# clonos:
  serve-window-begin`` / ``# clonos: serve-window-end`` holds ONE fused
  gather dispatch for the whole coalesced key batch. A blocking host
  sync inside it re-serializes the batch back into the N round-trips
  the coalescing queue exists to avoid — the read-path headline
  regresses with no functional symptom, which is why both are lint
  rules and not tests. The async-safe primitive ``copy_to_host_async``
  is explicitly allowed; its blocking cousins are not.
"""

from __future__ import annotations

import ast
from typing import List

from clonos_tpu.lint.core import (FileContext, Finding, Rule,
                                  register_rule)

BEGIN = "clonos: overlap-window-begin"
END = "clonos: overlap-window-end"
SERVE_BEGIN = "clonos: serve-window-begin"
SERVE_END = "clonos: serve-window-end"

#: canonical dotted names that force a host synchronization.
SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.copy",
    "jax.block_until_ready", "jax.device_get",
}

#: method names that block regardless of receiver resolution
#: (``arr.block_until_ready()``); ``copy_to_host_async`` is the allowed
#: non-blocking start and deliberately absent.
SYNC_ATTRS = {"block_until_ready", "copy_to_host", "item", "tolist"}


def _windows(ctx: FileContext, begin: str = BEGIN,
             end: str = END) -> List[tuple]:
    """(begin_line, end_line) pairs of every marked window."""
    out, start = [], None
    for i, ln in enumerate(ctx.lines, start=1):
        if begin in ln:
            start = i
        elif end in ln and start is not None:
            out.append((start, i))
            start = None
    return out


class _DispatchOnlyWindowRule(Rule):
    """Shared checker: flag blocking host syncs between a marker pair,
    plus unbalanced markers (an unclosed begin leaves its window
    silently unchecked)."""

    begin: str
    end: str
    window_desc: str

    def check(self, ctx: FileContext) -> List[Finding]:
        wins = _windows(ctx, self.begin, self.end)
        out: List[Finding] = []
        opens = sum(self.begin in ln for ln in ctx.lines)
        if opens != len(wins):
            out.append(self.finding(
                ctx, 1, f"unbalanced {self.name} markers "
                        f"({opens} begin / {len(wins)} closed)"))
        if not wins:
            return out
        seen = set()
        for node in ast.walk(ctx.tree):
            line = getattr(node, "lineno", None)
            if line is None or not any(b < line < e for b, e in wins):
                continue
            dotted = None
            if isinstance(node, (ast.Attribute, ast.Name)):
                dotted = ctx.resolve(node)
            hit = None
            if dotted in SYNC_CALLS:
                hit = dotted
            elif (isinstance(node, ast.Attribute)
                  and node.attr in SYNC_ATTRS):
                hit = f"<expr>.{node.attr}"
            if hit is None or (line, hit) in seen:
                continue
            seen.add((line, hit))
            out.append(self.finding(
                ctx, line,
                f"`{hit}` blocks on device results inside "
                f"{self.window_desc} — keep the window dispatch-only "
                f"(copy_to_host_async is the async primitive), or move "
                f"the read outside the markers"))
        return out


@register_rule
class OverlapWindowSyncRule(_DispatchOnlyWindowRule):
    name = "overlap-window"
    description = ("host synchronization (np.asarray / "
                   "block_until_ready / device_get) inside a pipelined-"
                   "fence overlap window — re-serializes the hidden tail")
    begin = BEGIN
    end = END
    window_desc = ("the pipelined-fence overlap window")


@register_rule
class ServeWindowSyncRule(_DispatchOnlyWindowRule):
    name = "serve-window"
    description = ("host synchronization inside a batched-read serve "
                   "window — re-serializes the coalesced gather back "
                   "into per-key round-trips")
    begin = SERVE_BEGIN
    end = SERVE_END
    window_desc = ("a batched-read serve window (one fused gather per "
                   "device dispatch)")
